"""Environment tier flags, importable without pulling in jax or the fork
registry (test modules read these at collection time)."""
import os

# Heavy crypto tier gate (jit-compile-bound tests; ``make test-crypto``)
HEAVY = os.environ.get("CS_TPU_HEAVY") == "1"


# ---------------------------------------------------------------------------
# Unified engine-switch accessor
# ---------------------------------------------------------------------------
# Every accelerated engine hangs off one boolean ``CS_TPU_*`` variable
# with the same contract: on unless the variable is exactly ``"0"``.
# Before PR 9 the live-re-read behavior was implemented per engine
# (``bls.rlc_enabled``, ``proto_array.enabled``, ...) with slightly
# different fallbacks, and some consumers latched the import-time
# module constant instead.  :func:`switch` is the one source of truth:
#
# * variable present in the environment -> live re-read (a CI leg or
#   the supervisor can flip an engine after import and every dispatch
#   sees it on the next call);
# * variable absent -> the cached import-time default, re-snapshotted
#   only by an explicit :func:`refresh` (so deleting the variable
#   mid-process restores the state the process STARTED with instead of
#   whatever the last override happened to be).
#
# The per-call cost is one ``os.environ`` lookup — the same price the
# engines already paid individually.

ENGINE_SWITCHES = (
    "CS_TPU_VECTORIZED_EPOCH",
    "CS_TPU_PROTO_ARRAY",
    "CS_TPU_STATE_ARRAYS",
    "CS_TPU_BLS_RLC",
    "CS_TPU_HASH_FOREST",
    "CS_TPU_SUPERVISOR",
    "CS_TPU_DAS",
    "CS_TPU_MESH",
    "CS_TPU_CHECKPOINT",
    "CS_TPU_SERVING",
    # observability, not an engine (no consensus result depends on it),
    # but it shares the switch contract: the flight recorder
    # (``obs/flight.py``) is on unless CS_TPU_FLIGHT=0.  Ring size is a
    # knob, CS_TPU_FLIGHT_SIZE (default 1024 slots per thread).
    "CS_TPU_FLIGHT",
)

_SWITCH_DEFAULTS = {}


def _snapshot_switches() -> None:
    for name in ENGINE_SWITCHES:
        _SWITCH_DEFAULTS[name] = os.environ.get(name) != "0"


_snapshot_switches()


def switch(name: str) -> bool:
    """Live boolean engine switch (see the block comment above)."""
    raw = os.environ.get(name)
    if raw is None:
        return _SWITCH_DEFAULTS.get(name, True)
    return raw != "0"


def refresh() -> None:
    """Explicitly invalidate the cached import-time defaults (rarely
    needed: only when a harness wants an *unset* variable to mean "the
    environment as it is now" rather than "as it was at import")."""
    _snapshot_switches()


def knob(name: str, default=None):
    """Raw string knob: the sanctioned access point for non-switch
    ``CS_TPU_*`` environment variables.  Engine code must not read
    ``os.environ`` directly (speclint D1003): routing every read
    through this module keeps the full set of environment dependencies
    declarable and auditable in one place — ambient state a consensus
    result may depend on is exactly what the determinism pass
    exists to fence."""
    return os.environ.get(name, default)


def _int_env(name):
    """Optional integer env knob: None when unset or non-numeric."""
    raw = os.environ.get(name, "")
    try:
        return int(raw)
    except ValueError:
        return None


# Merkleization batching floor.  When set, overrides BOTH batching
# thresholds in ``utils/ssz/merkle.py``: the kernel-layer threshold
# (``_BATCH_THRESHOLD``, default 256 — 64-byte inputs above which a full
# layer is dispatched to the batched JAX hasher instead of native C /
# hashlib) and the dirty-pair batching floor (``_PAIR_BATCH_MIN``,
# default 32 — dirty sibling pairs per tree level above which the
# incremental engine gathers the level into one batched dispatch instead
# of a per-pair hashlib loop).  ``CS_TPU_MERKLE_BATCH_MIN=1`` forces the
# batched paths everywhere; a huge value forces the scalar paths.
MERKLE_BATCH_MIN = _int_env("CS_TPU_MERKLE_BATCH_MIN")

# Hash-forest batch scope kill switch: ``CS_TPU_HASH_FOREST=0`` turns
# ``utils/ssz/forest.py`` scopes into no-ops (every tree flushes alone)
# and disables the columnar bulk container-root path.
HASH_FOREST = os.environ.get("CS_TPU_HASH_FOREST") != "0"

# Telemetry span gates (``consensus_specs_tpu/obs``).  PROFILE turns on
# hierarchical tracing spans (wall-clock span tree + flat aggregates,
# ``obs.tracing`` / the ``utils/profiling`` aliases); TRACE additionally
# attaches per-span counter deltas (a registry-wide counter diff on
# every span entry/exit — more detail, more overhead) and implies
# PROFILE.  Both default OFF: the disabled span path is a single
# module-global read.  Metric *counters* are not gated — the
# differential suites assert on them to prove which engine answered.
PROFILE = os.environ.get("CS_TPU_PROFILE") == "1"
TRACE = os.environ.get("CS_TPU_TRACE") == "1"

# Random-linear-combination batch-verification switch:
# ``CS_TPU_BLS_RLC=0`` makes ``utils/bls.DeferredBatch.flush`` run the
# per-lane path (one pairing check per queued item) instead of folding
# the whole batch into 2 MSMs + ONE product pairing check.  Like
# ``CS_TPU_PROTO_ARRAY``, this snapshot is the import-time default and
# the switch re-reads the environment at call time when the variable is
# present (``utils/bls.rlc_enabled``), so a test/CI leg can flip it
# after import.
BLS_RLC = os.environ.get("CS_TPU_BLS_RLC") != "0"

# Copy-on-write columnar state store kill switch:
# ``CS_TPU_STATE_ARRAYS=0`` detaches the per-state ``StateArrays``
# column store (``state/arrays.py``): every engine access re-extracts
# its columns and commits immediately instead of sharing one extraction
# per state lineage with deferred per-epoch commits.  Like
# ``CS_TPU_PROTO_ARRAY``, this snapshot is the import-time default and
# ``state.arrays.enabled()`` re-reads the environment at call time when
# the variable is present, so a test/CI leg can flip it after import.
STATE_ARRAYS = os.environ.get("CS_TPU_STATE_ARRAYS") != "0"

# Proto-array fork-choice kill switch: ``CS_TPU_PROTO_ARRAY=0`` runs the
# spec-loop ``get_head`` / ``get_weight`` / ``get_filtered_block_tree``
# (``forks/fork_choice.py``) instead of the incremental columnar engine
# in ``forkchoice/proto_array.py``, and stores are created without an
# engine attached.  This snapshot is the default
# ``forkchoice.proto_array.enabled()`` answers with; setting the
# variable after import also works (like ``CS_TPU_VECTORIZED_EPOCH``,
# the switch re-reads the environment at call time when it is present).
PROTO_ARRAY = os.environ.get("CS_TPU_PROTO_ARRAY") != "0"

# Data-availability-sampling engine kill switch: ``CS_TPU_DAS=0`` runs
# the spec-loop eip7594 sampling bodies (one pairing per cell,
# per-blob erasure recovery — the markdown algorithms) instead of the
# batched DAS engine (``consensus_specs_tpu/das``: whole-batch
# cell-proof folding into one pairing, columnar multi-blob recovery).
# Live via :func:`switch` like the other engine flags.
# ``CS_TPU_DAS_FFT=limb`` additionally routes the engine's scalar-field
# FFTs through the limb kernels (``ops/jax_bls/fr_fft.py``: JAX device
# kernel, numpy mirror under CS_TPU_NUMPY_KERNELS=1); unset = host
# python-int FFT.
DAS = os.environ.get("CS_TPU_DAS") != "0"

# Mesh-sharded SPMD state engine kill switch: ``CS_TPU_MESH=0`` keeps
# the ``StateArrays`` validator-axis columns on one device — epoch
# sub-transitions and leaf merkleization run the single-device engines
# (``ops/epoch_kernels``, ``utils/ssz/merkle``) instead of the
# ``shard_map`` SPMD programs in ``consensus_specs_tpu/parallel/``.
# Live via :func:`switch`; the engine additionally declines on hosts
# with fewer than two addressable devices, so the switch only matters
# on a mesh (or under ``--xla_force_host_platform_device_count``).
# Engagement floors — registry/leaf sizes below which sharding is pure
# overhead — are the ``CS_TPU_MESH_MIN`` / ``CS_TPU_MESH_MERKLE_MIN``
# knobs read through :func:`knob` (``parallel/mesh_state.py``).
MESH = os.environ.get("CS_TPU_MESH") != "0"

# Durable-replay kill switch: ``CS_TPU_CHECKPOINT=0`` turns the
# recovery subsystem (``consensus_specs_tpu/recovery``) off — durable
# replays neither journal nor checkpoint, and a resume degrades to
# deterministic re-execution from genesis (byte-identical, slower).
# Live via :func:`switch`.  Cadence/retention knobs
# (``CS_TPU_CHECKPOINT_EVERY``, ``CS_TPU_CHECKPOINT_KEEP``) are read
# through :func:`knob` by the sim recovery legs; docs/recovery.md.
CHECKPOINT = os.environ.get("CS_TPU_CHECKPOINT") != "0"

# Block-serving pipeline kill switch: ``CS_TPU_SERVING=0`` makes the
# serving layer (``consensus_specs_tpu/serving``) deliver every block
# through the synchronous per-block ``on_block`` path — no window
# batching, no overlapped RLC flushes, no chunk-level state clones.
# Live via :func:`switch` like the other engine flags (the off-leg CI
# job flips it after import; a latched module constant would miss
# that — the historical import-latched-flag class this registration
# exists to prevent).  The window-depth knob (``CS_TPU_SERVING_WINDOW``)
# is read through :func:`knob` by ``serving/pipeline.py``.
SERVING = os.environ.get("CS_TPU_SERVING") != "0"

# Runtime effect sanitizer: ``CS_TPU_SANITIZER=1`` arms the dynamic
# twin of the speclint E12xx effect contracts
# (``consensus_specs_tpu/sanitizer.py``): the state store and the
# recovery writers feed a shadow effect log, and a violated contract
# (direct SSZ write under a pending deferred column, a checkpoint blob
# after its manifest, an unfsynced STEP marker or final-path rename)
# raises ``EffectViolation`` naming the E12xx rule.  Default OFF — a
# diagnostic arm, not an engine; read live through :func:`knob`
# (``sanitizer.enabled``).  Disabled overhead is bench-asserted <2%
# (``benchmarks/bench_sanitizer.py``).
SANITIZER = os.environ.get("CS_TPU_SANITIZER") == "1"

# Engine supervisor kill switch: ``CS_TPU_SUPERVISOR=0`` turns the
# health-tracking supervision layer (``consensus_specs_tpu/supervisor``)
# into a pass-through — no circuit breakers, no deadline guards, no
# sentinel audits; every dispatch behaves exactly as before PR 9.
# Live via :func:`switch` like the other engine flags.  The supervisor's
# numeric knobs (breaker threshold/window/backoff, audit sampling rate,
# deadline budget) are documented in ``docs/robustness.md`` and read by
# ``supervisor.reset()``.
SUPERVISOR = os.environ.get("CS_TPU_SUPERVISOR") != "0"
