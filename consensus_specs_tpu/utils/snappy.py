"""Raw-snappy codec (pure python).

The cross-client vector corpus stores SSZ bodies as ``.ssz_snappy`` in the
*raw* snappy block format (reference: ``gen_runner.py:421-426`` via
python-snappy/libsnappy, which this image does not ship).  This module
implements the format from scratch:

- ``compress``: greedy LZ77 with a 4-byte-hash match table — the same
  family of scheme libsnappy uses.  Output is valid raw snappy (any
  conforming decoder, including libsnappy, decodes it); byte-for-byte
  output parity with libsnappy is NOT guaranteed (the format permits many
  encodings of the same payload), which is fine because consumers always
  decompress before comparing.
- ``decompress``: full decoder for all tag types (literal, copy-1/2/4).

SSZ states are zero-heavy, so even this simple matcher reaches libsnappy-
class ratios on vector payloads.

A native C implementation (``csrc/snappy.c``, built by ``make native`` into
``csrc/libcsnappy.so``) is preferred when present — the role libsnappy's C
core plays for the reference; these python functions are the fallback and
the differential oracle (``tests/test_snappy.py``).
"""
import ctypes
import os

_MAX_OFFSET = 1 << 15  # keep copies in copy-2 range (offset < 65536)


def _load_native():
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "csrc", "libcsnappy.so")
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.csnappy_compress.restype = ctypes.c_size_t
        lib.csnappy_compress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
        lib.csnappy_max_compressed_length.restype = ctypes.c_size_t
        lib.csnappy_max_compressed_length.argtypes = [ctypes.c_size_t]
        lib.csnappy_uncompressed_length.restype = ctypes.c_size_t
        lib.csnappy_uncompressed_length.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t]
        lib.csnappy_decompress.restype = ctypes.c_size_t
        lib.csnappy_decompress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ctypes.c_size_t]
        return lib
    except OSError:
        return None


_native = _load_native()


def compress(data: bytes) -> bytes:
    data = bytes(data)
    if _native is not None:
        buf = ctypes.create_string_buffer(
            _native.csnappy_max_compressed_length(len(data)))
        n = _native.csnappy_compress(data, len(data), buf)
        if n:
            return buf.raw[:n]
        if len(data) == 0:
            return _py_compress(data)
    return _py_compress(data)


def decompress(data: bytes) -> bytes:
    data = bytes(data)
    if _native is not None:
        length = _native.csnappy_uncompressed_length(data, len(data))
        if length != ctypes.c_size_t(-1).value:
            buf = ctypes.create_string_buffer(max(length, 1))
            n = _native.csnappy_decompress(data, len(data), buf, length)
            if n == length:
                return buf.raw[:length]
        raise ValueError("snappy: malformed input")
    return _py_decompress(data)


def _varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _emit_literal(out: bytearray, data, start: int, end: int) -> None:
    length = end - start
    if length == 0:
        return
    n = length - 1
    if n < 60:
        out.append(n << 2)
    elif n < (1 << 8):
        out.append(60 << 2)
        out.append(n & 0xFF)
    elif n < (1 << 16):
        out.append(61 << 2)
        out += (n).to_bytes(2, "little")
    elif n < (1 << 24):
        out.append(62 << 2)
        out += (n).to_bytes(3, "little")
    else:
        out.append(63 << 2)
        out += (n).to_bytes(4, "little")
    out += data[start:end]


def _emit_copy(out: bytearray, offset: int, length: int) -> None:
    # prefer copy-2 (3-byte tag, len 1..64, offset < 65536)
    while length > 0:
        chunk = min(length, 64)
        if chunk < 4 and length != chunk:
            # avoid leaving a tail shorter than the minimum match
            chunk = length
        out.append(((chunk - 1) << 2) | 0b10)
        out += offset.to_bytes(2, "little")
        length -= chunk


def _py_compress(data: bytes) -> bytes:
    data = bytes(data)
    out = bytearray(_varint(len(data)))
    n = len(data)
    if n == 0:
        return bytes(out)
    if n < 16:
        _emit_literal(out, data, 0, n)
        return bytes(out)

    table = {}
    i = 0
    literal_start = 0
    while i + 4 <= n:
        key = data[i:i + 4]
        cand = table.get(key)
        table[key] = i
        if cand is not None and i - cand < _MAX_OFFSET:
            # extend the match forward
            match_len = 4
            while (i + match_len < n and match_len < 1 << 16
                   and data[cand + match_len] == data[i + match_len]):
                match_len += 1
            _emit_literal(out, data, literal_start, i)
            _emit_copy(out, i - cand, match_len)
            # index a couple of positions inside the match (cheap and
            # keeps the table fresh on runs of zeros)
            for j in range(i + 1, min(i + match_len, n - 4), 7):
                table[data[j:j + 4]] = j
            i += match_len
            literal_start = i
        else:
            i += 1
    _emit_literal(out, data, literal_start, n)
    return bytes(out)


def _py_decompress(data: bytes) -> bytes:
    data = bytes(data)
    # uncompressed length varint
    shift = 0
    length = 0
    pos = 0
    while True:
        b = data[pos]
        length |= (b & 0x7F) << shift
        pos += 1
        if not b & 0x80:
            break
        shift += 7

    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        tag_type = tag & 0b11
        if tag_type == 0b00:  # literal
            ln = tag >> 2
            if ln < 60:
                ln += 1
            else:
                extra = ln - 59
                ln = int.from_bytes(data[pos:pos + extra], "little") + 1
                pos += extra
            out += data[pos:pos + ln]
            pos += ln
        else:
            if tag_type == 0b01:  # copy-1: len 4..11, offset 11 bits
                ln = ((tag >> 2) & 0b111) + 4
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif tag_type == 0b10:  # copy-2
                ln = (tag >> 2) + 1
                offset = int.from_bytes(data[pos:pos + 2], "little")
                pos += 2
            else:  # copy-4
                ln = (tag >> 2) + 1
                offset = int.from_bytes(data[pos:pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise ValueError("snappy: invalid copy offset")
            # overlapping copies are byte-serial by definition
            start = len(out) - offset
            for k in range(ln):
                out.append(out[start + k])
    if len(out) != length:
        raise ValueError(
            f"snappy: length mismatch (expected {length}, got {len(out)})")
    return bytes(out)
