"""SHA-256 ``hash`` primitive.

Reference: ``tests/core/pyspec/eth2spec/utils/hash_function.py`` (the spec's
``hash(data) -> Bytes32`` is plain SHA-256). Single-shot hashing stays on
hashlib (C speed); *batched* layer hashing for merkleization goes through
``consensus_specs_tpu.ops.sha256`` so big trees can use the vectorized
kernel.
"""
from hashlib import sha256 as _sha256


def hash(data: bytes) -> bytes:
    return _sha256(data).digest()
