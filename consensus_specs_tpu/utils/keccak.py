"""Pure-python Keccak-256 (the pre-NIST padding Ethereum uses).

The reference pulls ``eth_hash``/pycryptodome for this
(``test/helpers/execution_payload.py:1``); neither ships in this image
and ``hashlib.sha3_256`` is NIST SHA-3 (domain byte ``0x06``) — Ethereum
keccak pads with ``0x01``, so the permutation is implemented here.
Throughput is irrelevant: the only consumer is execution-block-hash
fabrication for test vectors (a few hundred bytes per payload).

Verified against the two universally-known anchors:
``keccak256(b"") = c5d24601...`` and the empty-trie root
``keccak256(rlp(b"")) = 56e81f17...`` (asserted at import).
"""

_ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

_ROTATIONS = [[0, 36, 3, 41, 18],
              [1, 44, 10, 45, 2],
              [62, 6, 43, 15, 61],
              [28, 55, 25, 21, 56],
              [27, 20, 39, 8, 14]]

_MASK = (1 << 64) - 1


def _rol(x, n):
    n %= 64
    return ((x << n) | (x >> (64 - n))) & _MASK


def _keccak_f(A):
    for rc in _ROUND_CONSTANTS:
        # theta
        C = [A[x][0] ^ A[x][1] ^ A[x][2] ^ A[x][3] ^ A[x][4] for x in range(5)]
        D = [C[(x - 1) % 5] ^ _rol(C[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                A[x][y] ^= D[x]
        # rho + pi
        B = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                B[y][(2 * x + 3 * y) % 5] = _rol(A[x][y], _ROTATIONS[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                A[x][y] = B[x][y] ^ ((~B[(x + 1) % 5][y]) & B[(x + 2) % 5][y])
        # iota
        A[0][0] ^= rc
    return A


def keccak256(data: bytes) -> bytes:
    rate = 136                       # 1600/8 - 2*32
    # pad10*1 with the 0x01 domain byte (NIST SHA-3 would use 0x06)
    padded = bytearray(data)
    pad_len = rate - (len(padded) % rate)
    padded += b"\x00" * pad_len
    padded[len(data)] ^= 0x01
    padded[-1] ^= 0x80

    A = [[0] * 5 for _ in range(5)]
    for off in range(0, len(padded), rate):
        block = padded[off:off + rate]
        for i in range(rate // 8):
            lane = int.from_bytes(block[8 * i:8 * i + 8], "little")
            A[i % 5][i // 5] ^= lane
        A = _keccak_f(A)

    out = bytearray()
    for i in range(4):               # 32 bytes = 4 lanes
        out += A[i % 5][i // 5].to_bytes(8, "little")
    return bytes(out)


assert keccak256(b"").hex() == \
    "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
assert keccak256(b"\x80").hex() == \
    "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
