"""Custody-game test builders.

Reference: ``test/helpers/custody.py`` (get_valid_early_derived_secret_reveal:10,
get_valid_custody_key_reveal:37, get_valid_custody_slashing:64,
get_valid_chunk_challenge:93, get_valid_custody_chunk_response:123,
get_sample_shard_transition:152).
"""
from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, ByteVector, ByteList, Bytes32, uint64, zero_hashes,
)
# Custody secrets are real BLS signatures even when signature
# VERIFICATION is stubbed out (``bls.bls_active = False``):
# ``compute_custody_bit`` decompresses the secret as a G2 point, so a
# stub constant would break the custody-bit math itself. Sign/Aggregate
# therefore bypass the kill-switch and use the oracle directly.
from consensus_specs_tpu.ops.bls12_381.ciphersuite import Sign, Aggregate
from .keys import privkeys







def transition_to(spec, state, slot):
    if state.slot < slot:
        spec.process_slots(state, slot)


def get_valid_early_derived_secret_reveal(spec, state, epoch=None):
    current_epoch = spec.get_current_epoch(state)
    revealed_index = spec.get_active_validator_indices(state, current_epoch)[-1]
    masker_index = spec.get_active_validator_indices(state, current_epoch)[0]

    if epoch is None:
        epoch = current_epoch + spec.CUSTODY_PERIOD_TO_RANDAO_PADDING

    # The derived secret being revealed: sig over the epoch
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch)
    signing_root = spec.compute_signing_root(uint64(epoch), domain)
    reveal = Sign(privkeys[revealed_index], signing_root)
    # Mask hides the revealed secret from theft in the mempool
    mask = Bytes32(hash(reveal))
    signing_root = spec.compute_signing_root(mask, domain)
    masker_signature = Sign(privkeys[masker_index], signing_root)
    masked_reveal = Aggregate([reveal, masker_signature])

    return spec.EarlyDerivedSecretReveal(
        revealed_index=revealed_index,
        epoch=epoch,
        reveal=masked_reveal,
        masker_index=masker_index,
        mask=mask,
    )


def get_valid_custody_key_reveal(spec, state, period=None, validator_index=None):
    current_epoch = spec.get_current_epoch(state)
    revealer_index = (spec.get_active_validator_indices(state, current_epoch)[0]
                      if validator_index is None else validator_index)
    revealer = state.validators[revealer_index]

    if period is None:
        period = revealer.next_custody_secret_to_reveal

    epoch_to_sign = spec.get_randao_epoch_for_custody_period(
        period, revealer_index)

    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch_to_sign)
    signing_root = spec.compute_signing_root(uint64(epoch_to_sign), domain)
    reveal = Sign(privkeys[revealer_index], signing_root)
    return spec.CustodyKeyReveal(revealer_index=revealer_index, reveal=reveal)


def get_custody_secret(spec, state, validator_index, epoch=None):
    """The validator's period secret: sig over the period's RANDAO epoch."""
    period = spec.get_custody_period_for_validator(
        validator_index,
        epoch if epoch is not None else spec.get_current_epoch(state))
    epoch_to_sign = spec.get_randao_epoch_for_custody_period(
        period, validator_index)
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch_to_sign)
    signing_root = spec.compute_signing_root(uint64(epoch_to_sign), domain)
    return Sign(privkeys[validator_index], signing_root)


def get_valid_custody_slashing(spec, state, attestation, shard_transition,
                               custody_secret, data, data_index=0):
    beacon_committee = spec.get_beacon_committee(
        state, attestation.data.slot, attestation.data.index)
    malefactor_index = beacon_committee[0]
    whistleblower_index = beacon_committee[-1]

    slashing = spec.CustodySlashing(
        data_index=data_index,
        malefactor_index=malefactor_index,
        malefactor_secret=custody_secret,
        whistleblower_index=whistleblower_index,
        shard_transition=shard_transition,
        attestation=attestation,
        data=data,
    )
    slashing_domain = spec.get_domain(state, spec.DOMAIN_CUSTODY_BIT_SLASHING)
    slashing_root = spec.compute_signing_root(slashing, slashing_domain)
    return spec.SignedCustodySlashing(
        message=slashing,
        signature=Sign(privkeys[whistleblower_index], slashing_root),
    )


def get_valid_chunk_challenge(spec, state, attestation, shard_transition,
                              data_index=None, chunk_index=None):
    committee = spec.get_beacon_committee(
        state, attestation.data.slot, attestation.data.index)
    responder_index = committee[0]
    data_index = (len(shard_transition.shard_block_lengths) - 1
                  if not data_index else data_index)

    chunk_count = (int(shard_transition.shard_block_lengths[data_index])
                   + spec.BYTES_PER_CUSTODY_CHUNK - 1) \
        // spec.BYTES_PER_CUSTODY_CHUNK
    chunk_index = chunk_count - 1 if not chunk_index else chunk_index

    return spec.CustodyChunkChallenge(
        responder_index=responder_index,
        attestation=attestation,
        chunk_index=chunk_index,
        data_index=data_index,
        shard_transition=shard_transition,
    )


def custody_chunkify(spec, x):
    x = bytes(x)
    chunks = [x[i:i + spec.BYTES_PER_CUSTODY_CHUNK]
              for i in range(0, len(x), spec.BYTES_PER_CUSTODY_CHUNK)]
    chunks[-1] = chunks[-1].ljust(spec.BYTES_PER_CUSTODY_CHUNK, b"\0")
    return [ByteVector[spec.BYTES_PER_CUSTODY_CHUNK](c) for c in chunks]


def _chunk_body_branch(spec, chunks, chunk_index):
    """Sibling path of custody-chunk ``chunk_index``'s subtree root inside
    the ByteList body tree (depth CUSTODY_RESPONSE_DEPTH over
    custody-chunk subtree roots; absent chunks are zero subtrees)."""
    # Each custody chunk (4096 B) is a depth-7 subtree of 32-byte SSZ
    # chunks; its root is hash_tree_root(ByteVector[4096]).
    sub_depth = (spec.BYTES_PER_CUSTODY_CHUNK // 32 - 1).bit_length()
    n_leaves = 2 ** spec.CUSTODY_RESPONSE_DEPTH
    leaves = [hash_tree_root(c) for c in chunks]
    leaves += [zero_hashes[sub_depth]] * (n_leaves - len(leaves))
    branch = []
    idx = chunk_index
    level = leaves
    for _ in range(spec.CUSTODY_RESPONSE_DEPTH):
        branch.append(level[idx ^ 1])
        level = [hash(level[i] + level[i + 1])
                 for i in range(0, len(level), 2)]
        idx //= 2
    return branch


def get_valid_custody_chunk_response(spec, state, chunk_challenge,
                                     challenge_index,
                                     block_length_or_custody_data,
                                     invalid_chunk_data=False):
    if isinstance(block_length_or_custody_data, int):
        custody_data = get_custody_test_vector(block_length_or_custody_data)
    else:
        custody_data = block_length_or_custody_data

    custody_data_block = ByteList[spec.MAX_SHARD_BLOCK_SIZE](custody_data)
    chunks = custody_chunkify(spec, custody_data_block)
    chunk_index = int(chunk_challenge.chunk_index)

    data_branch = _chunk_body_branch(spec, chunks, chunk_index) + [
        len(custody_data_block).to_bytes(32, "little")]

    chunk = chunks[chunk_index]
    if invalid_chunk_data:
        chunk = ByteVector[spec.BYTES_PER_CUSTODY_CHUNK](
            bytes(chunk)[:-1] + bytes([bytes(chunk)[-1] ^ 0xFF]))

    return spec.CustodyChunkResponse(
        challenge_index=challenge_index,
        chunk_index=chunk_index,
        chunk=chunk,
        branch=data_branch,
    )


def get_custody_test_vector(bytelength, offset=0):
    ints = bytelength // 4 + 1
    return (b"".join((i + offset).to_bytes(4, "little")
                     for i in range(ints)))[:bytelength]


def get_sample_shard_transition(spec, start_slot, block_lengths):
    roots = [hash_tree_root(ByteList[spec.MAX_SHARD_BLOCK_SIZE](
        get_custody_test_vector(x))) for x in block_lengths]
    return spec.ShardTransition(
        start_slot=start_slot,
        shard_block_lengths=block_lengths,
        shard_data_roots=roots,
        shard_states=[spec.ShardState() for _ in block_lengths],
        proposer_signature_aggregate=b"\x00" * 96,
    )


def get_custody_slashable_test_vector(spec, custody_secret, length,
                                      slashable=True):
    test_vector = get_custody_test_vector(length)
    offset = 0
    while bool(spec.compute_custody_bit(custody_secret, test_vector)) \
            != slashable:
        offset += 1
        test_vector = get_custody_test_vector(length, offset)
    return test_vector


def get_custody_slashable_shard_transition(spec, start_slot, block_lengths,
                                           custody_secret, slashable=True):
    shard_transition = get_sample_shard_transition(
        spec, start_slot, block_lengths)
    slashable_test_vector = get_custody_slashable_test_vector(
        spec, custody_secret, block_lengths[0], slashable=slashable)
    block_data = ByteList[spec.MAX_SHARD_BLOCK_SIZE](slashable_test_vector)
    shard_transition.shard_data_roots[0] = hash_tree_root(block_data)
    return shard_transition, slashable_test_vector
