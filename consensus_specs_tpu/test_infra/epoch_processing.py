"""Epoch sub-transition isolation runner.

Reference: ``test/helpers/epoch_processing.py:43-63`` — run every epoch
sub-step *before* the one under test, then yield pre/post around it.
"""


def get_process_calls(spec):
    if spec.fork == "custody_game":
        # custody_game/beacon-chain.md "Epoch transition" ordering
        return [
            "process_justification_and_finalization",
            "process_rewards_and_penalties",
            "process_registry_updates",
            "process_reveal_deadlines",
            "process_challenge_deadlines",
            "process_slashings",
            "process_eth1_data_reset",
            "process_effective_balance_updates",
            "process_slashings_reset",
            "process_randao_mixes_reset",
            "process_historical_roots_update",
            "process_participation_record_updates",
            "process_custody_final_updates",
            "process_shard_epoch_increment",
        ]
    if spec.fork in ("phase0", "sharding"):
        return [
            "process_justification_and_finalization",
            "process_rewards_and_penalties",
            "process_registry_updates",
            "process_slashings",
            "process_eth1_data_reset",
            "process_effective_balance_updates",
            "process_slashings_reset",
            "process_randao_mixes_reset",
            "process_historical_roots_update",
            "process_participation_record_updates",
        ] + (["process_shard_epoch_increment"]
             if spec.fork == "sharding" else [])
    # altair+ ordering (specs/altair/beacon-chain.md process_epoch; capella
    # renames historical roots to historical summaries)
    calls = [
        "process_justification_and_finalization",
        "process_inactivity_updates",
        "process_rewards_and_penalties",
        "process_registry_updates",
        "process_slashings",
        "process_eth1_data_reset",
        "process_effective_balance_updates",
        "process_slashings_reset",
        "process_randao_mixes_reset",
        ("process_historical_summaries_update"
         if hasattr(spec, "process_historical_summaries_update")
         else "process_historical_roots_update"),
        "process_participation_flag_updates",
        "process_sync_committee_updates",
    ]
    return calls


def run_epoch_processing_to(spec, state, process_name):
    """Transition to the end of the epoch and run sub-transitions up to
    (but excluding) ``process_name``."""
    slot = state.slot + (spec.SLOTS_PER_EPOCH - state.slot % spec.SLOTS_PER_EPOCH)
    # transition state to slot before epoch state transition
    if state.slot < slot - 1:
        spec.process_slots(state, slot - 1)
    # start transitioning, do one slot update before the epoch itself
    spec.process_slot(state)
    # process components of epoch transition before ``process_name``
    for name in get_process_calls(spec):
        if name == process_name:
            break
        getattr(spec, name)(state)


def run_epoch_processing_with(spec, state, process_name):
    """Run the epoch sub-transition ``process_name``, yielding pre/post.

    Also yields the sub-transition name as a ``sub_transition`` meta
    scalar (lands in ``meta.yaml``): the corpus replayer needs it to
    re-execute pre -> post, since this repo files every epoch case
    under one ``epoch_processing`` handler rather than the reference's
    per-sub-transition handlers.  Hand-rolled cases that drive a
    sub-transition inline (no meta key) are counted replay-skips."""
    run_epoch_processing_to(spec, state, process_name)
    yield "sub_transition", process_name
    yield "pre", state
    getattr(spec, process_name)(state)
    yield "post", state
