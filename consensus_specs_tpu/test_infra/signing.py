"""Memoized test-infra signing.

Sibling cases in a generator suite sign the SAME messages over and
over: every case built from one cached genesis state re-derives
identical randao reveals, proposer signatures, and attestation
signatures (same privkey, same signing root, same deterministic BLS
output).  `bls.Sign` on the pure-python backend costs ~1ms per call —
across a multi-fork corpus that is minutes of redundant scalar
multiplication.  :func:`sign` memoizes on ``(privkey, signing_root)``,
which is sound because BLS signing is deterministic (RFC 9380 hash-to-
curve + fixed scalar mult — no nonce).

Hit/miss traffic is census-booked on ``gen.sign_memo{result=...}`` so
the corpus bench can assert the memo actually engages.  The memo is
bypassed (not consulted, not populated) while ``bls.bls_active`` is
off: stub-mode "signatures" are a constant that must not leak into a
later real-crypto run of the same process, and vice versa.

The cache is plain module state on purpose: the corpus factory
pre-warms the fork-pool parent, so workers inherit every parent-side
entry copy-on-write for free, exactly like ``keys._pubkey_cache``.
"""
from consensus_specs_tpu.obs import registry as _registry
from consensus_specs_tpu.utils import bls

_MEMO_HITS = _registry.counter("gen.sign_memo").labels(result="hit")
_MEMO_MISSES = _registry.counter("gen.sign_memo").labels(result="miss")

_sign_cache = {}


def sign(privkey: int, signing_root) -> bytes:
    """Memoized ``bls.Sign(privkey, signing_root)``."""
    if not bls.bls_active:
        return bls.Sign(privkey, signing_root)
    key = (privkey, bytes(signing_root))
    sig = _sign_cache.get(key)
    if sig is not None:
        _MEMO_HITS.add()
        return sig
    _MEMO_MISSES.add()
    sig = bls.Sign(privkey, signing_root)
    _sign_cache[key] = sig
    return sig


def clear() -> None:
    """Drop every memoized signature (tests; backend switches)."""
    _sign_cache.clear()
