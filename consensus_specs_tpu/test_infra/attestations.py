"""Attestation-building helpers.

Reference: ``test/helpers/attestations.py`` (build_attestation_data:~50,
get_valid_attestation:91, sign_attestation, run_attestation_processing:14).
"""
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.ssz import Bitlist
from .keys import privkeys
from .signing import sign
from .block import build_empty_block_for_next_slot


def build_attestation_data(spec, state, slot, index, beacon_block_root=None,
                           shard_transition=None):
    assert state.slot >= slot

    if beacon_block_root is not None:
        pass
    elif slot == state.slot:
        beacon_block_root = build_empty_block_for_next_slot(spec, state).parent_root
    else:
        beacon_block_root = spec.get_block_root_at_slot(state, slot)

    current_epoch_start_slot = spec.compute_start_slot_at_epoch(
        spec.get_current_epoch(state))
    if slot < current_epoch_start_slot:
        epoch_boundary_root = spec.get_block_root(state, spec.get_previous_epoch(state))
    elif slot == current_epoch_start_slot:
        epoch_boundary_root = beacon_block_root
    else:
        epoch_boundary_root = spec.get_block_root(state, spec.get_current_epoch(state))

    if slot < current_epoch_start_slot:
        source = state.previous_justified_checkpoint
    else:
        source = state.current_justified_checkpoint

    data = spec.AttestationData(
        slot=slot,
        index=index,
        beacon_block_root=beacon_block_root,
        source=spec.Checkpoint(epoch=source.epoch, root=source.root),
        target=spec.Checkpoint(
            epoch=spec.compute_epoch_at_slot(slot), root=epoch_boundary_root),
    )
    if shard_transition is not None:
        # sharding/custody_game lineage: the attestation commits to the
        # shard transition it crosslinks (sharding.py AttestationData)
        from consensus_specs_tpu.utils.ssz import hash_tree_root
        data.shard_transition_root = hash_tree_root(shard_transition)
    return data


def get_valid_attestation(spec, state, slot=None, index=None,
                          filter_participant_set=None, beacon_block_root=None,
                          signed=False, shard_transition=None):
    if slot is None:
        slot = state.slot
    if index is None:
        index = 0

    attestation_data = build_attestation_data(
        spec, state, slot=slot, index=index, beacon_block_root=beacon_block_root,
        shard_transition=shard_transition)
    beacon_committee = spec.get_beacon_committee(
        state, attestation_data.slot, attestation_data.index)
    committee_size = len(beacon_committee)
    attestation = spec.Attestation(
        aggregation_bits=Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE]([0] * committee_size),
        data=attestation_data,
    )
    # set the committee's participation bits (subject to the caller's
    # filter), then sign unless the test wants an unsigned aggregate
    fill_aggregate_attestation(
        spec, state, attestation, signed=signed,
        filter_participant_set=filter_participant_set)
    return attestation


def fill_aggregate_attestation(spec, state, attestation, signed=False,
                               filter_participant_set=None):
    committee = spec.get_beacon_committee(
        state, attestation.data.slot, attestation.data.index)
    participants = set(committee)
    if filter_participant_set is not None:
        participants = filter_participant_set(participants)
    for i in range(len(committee)):
        attestation.aggregation_bits[i] = committee[i] in participants
    if signed and len(participants) > 0:
        sign_attestation(spec, state, attestation)


def participants_filter(committee):
    return committee


def sign_attestation(spec, state, attestation):
    participants = spec.get_attesting_indices(
        state, attestation.data, attestation.aggregation_bits)
    attestation.signature = sign_aggregate_attestation(
        spec, state, attestation.data, participants)


def sign_aggregate_attestation(spec, state, attestation_data, participants):
    signatures = []
    for validator_index in participants:
        privkey = privkeys[validator_index]
        signatures.append(
            get_attestation_signature(spec, state, attestation_data, privkey))
    return bls.Aggregate(signatures)


def get_attestation_signature(spec, state, attestation_data, privkey):
    domain = spec.get_domain(
        state, spec.DOMAIN_BEACON_ATTESTER, attestation_data.target.epoch)
    signing_root = spec.compute_signing_root(attestation_data, domain)
    return sign(privkey, signing_root)


def run_attestation_processing(spec, state, attestation, valid=True):
    """Run ``process_attestation``, yielding (pre, attestation, post) vector
    parts; if ``valid == False`` the op must raise and post is None.
    Reference: test/helpers/attestations.py:14-52.
    """
    yield "pre", state
    yield "attestation", attestation

    if not valid:
        try:
            spec.process_attestation(state, attestation)
        except (AssertionError, IndexError, ValueError):
            yield "post", None
            return
        raise AssertionError("attestation processing should have failed")

    # phase0-family forks (incl. sharding/custody_game) record pending
    # attestations; altair+ uses participation flags
    is_phase0 = hasattr(state, "current_epoch_attestations")
    if is_phase0:
        current_epoch_count = len(state.current_epoch_attestations)
        previous_epoch_count = len(state.previous_epoch_attestations)

    spec.process_attestation(state, attestation)

    if is_phase0:
        # phase0 records pending attestations; altair+ sets flags instead
        if attestation.data.target.epoch == spec.get_current_epoch(state):
            assert len(state.current_epoch_attestations) == current_epoch_count + 1
        else:
            assert len(state.previous_epoch_attestations) == previous_epoch_count + 1
    else:
        participation = (
            state.current_epoch_participation
            if attestation.data.target.epoch == spec.get_current_epoch(state)
            else state.previous_epoch_participation)
        attesting = spec.get_attesting_indices(
            state, attestation.data, attestation.aggregation_bits)
        # the flags the spec says this attestation earns (may be empty:
        # e.g. wrong target root at one-epoch inclusion delay earns none
        # yet the operation is still valid)
        expected = spec.get_attestation_participation_flag_indices(
            state, attestation.data,
            state.slot - attestation.data.slot)
        for flag_index in expected:
            assert all(
                spec.has_flag(participation[i], flag_index)
                for i in attesting)

    yield "post", state


def next_epoch_with_attestations(spec, state, fill_cur_epoch, fill_prev_epoch):
    from .block import build_empty_block_for_next_slot, state_transition_and_sign_block
    assert state.slot % spec.SLOTS_PER_EPOCH == 0

    post_state = state.copy()
    signed_blocks = []
    for _ in range(spec.SLOTS_PER_EPOCH):
        block = build_empty_block_for_next_slot(spec, post_state)
        if fill_cur_epoch and post_state.slot >= spec.MIN_ATTESTATION_INCLUSION_DELAY:
            slot_to_attest = post_state.slot - spec.MIN_ATTESTATION_INCLUSION_DELAY + 1
            committees_per_slot = spec.get_committee_count_per_slot(
                post_state, spec.compute_epoch_at_slot(slot_to_attest))
            if slot_to_attest >= spec.compute_start_slot_at_epoch(
                    spec.get_current_epoch(post_state)):
                for index in range(committees_per_slot):
                    attestation = get_valid_attestation(
                        spec, post_state, slot_to_attest, index=index, signed=True)
                    block.body.attestations.append(attestation)
        if fill_prev_epoch:
            slot_to_attest = post_state.slot - spec.SLOTS_PER_EPOCH + 1
            committees_per_slot = spec.get_committee_count_per_slot(
                post_state, spec.compute_epoch_at_slot(slot_to_attest))
            for index in range(committees_per_slot):
                attestation = get_valid_attestation(
                    spec, post_state, slot_to_attest, index=index, signed=True)
                block.body.attestations.append(attestation)
        signed_block = state_transition_and_sign_block(spec, post_state, block)
        signed_blocks.append(signed_block)

    return state, signed_blocks, post_state
