"""Block-building helpers.

Reference: ``test/helpers/block.py`` (build_empty_block:93, sign_block:69,
transition_unsigned_block:75, state_transition_and_sign_block).
"""
from consensus_specs_tpu.utils.ssz import hash_tree_root
from consensus_specs_tpu.utils import bls
from .keys import privkeys
from .signing import sign


def get_proposer_index_maybe(spec, state, slot, proposer_index=None):
    if proposer_index is None:
        if slot == state.slot:
            proposer_index = spec.get_beacon_proposer_index(state)
        else:
            future_state = state.copy()
            spec.process_slots(future_state, slot)
            proposer_index = spec.get_beacon_proposer_index(future_state)
    return proposer_index


def apply_randao_reveal(spec, state, block, proposer_index):
    assert state.slot <= block.slot
    privkey = privkeys[proposer_index]
    epoch = spec.compute_epoch_at_slot(block.slot)
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch)
    signing_root = spec.compute_signing_root(spec.uint64(epoch), domain)
    block.body.randao_reveal = sign(privkey, signing_root)


def apply_sig(spec, state, signed_block, proposer_index=None):
    if not bls.bls_active:
        return
    block = signed_block.message
    proposer_index = get_proposer_index_maybe(spec, state, block.slot, proposer_index)
    privkey = privkeys[proposer_index]
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER,
                             spec.compute_epoch_at_slot(block.slot))
    signing_root = spec.compute_signing_root(block, domain)
    signed_block.signature = sign(privkey, signing_root)


def sign_block(spec, state, block, proposer_index=None):
    signed_block = spec.SignedBeaconBlock(message=block)
    apply_sig(spec, state, signed_block, proposer_index)
    return signed_block


def get_state_and_beacon_parent_root_at_slot(spec, state, slot):
    if slot < state.slot:
        raise Exception("cannot build blocks for past slots")
    if slot > state.slot:
        state = state.copy()
        spec.process_slots(state, slot)
    previous_block_header = state.latest_block_header.copy()
    if previous_block_header.state_root == spec.Root():
        previous_block_header.state_root = hash_tree_root(state)
    return state, hash_tree_root(previous_block_header)


def build_empty_block(spec, state, slot=None, proposer_index=None):
    """Build an empty block for ``slot`` upon the latest header seen by state."""
    if slot is None:
        slot = state.slot
    state, parent_block_root = get_state_and_beacon_parent_root_at_slot(spec, state, slot)
    if proposer_index is None:
        proposer_index = spec.get_beacon_proposer_index(state)
    block = spec.BeaconBlock()
    block.slot = slot
    block.proposer_index = proposer_index
    block.body.eth1_data.deposit_count = state.eth1_deposit_index
    block.parent_root = parent_block_root
    if hasattr(block.body, "sync_aggregate"):
        # altair+: an empty sync aggregate carries the infinity signature
        block.body.sync_aggregate.sync_committee_signature = \
            spec.G2_POINT_AT_INFINITY
    if hasattr(block.body, "execution_payload"):
        # bellatrix+: a valid (empty) payload for the block's slot
        from .execution_payload import build_empty_execution_payload
        block.body.execution_payload = \
            build_empty_execution_payload(spec, state)
    apply_randao_reveal(spec, state, block, proposer_index)
    return block


def build_empty_block_for_next_slot(spec, state, proposer_index=None):
    return build_empty_block(spec, state, state.slot + 1, proposer_index)


def transition_unsigned_block(spec, state, block):
    assert state.slot < block.slot
    spec.process_slots(state, block.slot)
    assert state.latest_block_header.slot < block.slot
    assert state.slot == block.slot
    spec.process_block(state, block)
    return block


def apply_empty_block(spec, state, slot=None):
    block = build_empty_block(spec, state, slot)
    return transition_unsigned_block(spec, state, block)


def state_transition_and_sign_block(spec, state, block):
    """Transition state to block's slot, process block, set the state root,
    and return the signed block."""
    transition_unsigned_block(spec, state, block)
    block.state_root = hash_tree_root(state)
    return sign_block(spec, state, block, block.proposer_index)


def next_slot(spec, state):
    spec.process_slots(state, state.slot + 1)


def next_slots(spec, state, slots):
    if slots > 0:
        spec.process_slots(state, state.slot + slots)


def next_epoch(spec, state):
    slot = state.slot + spec.SLOTS_PER_EPOCH - (state.slot % spec.SLOTS_PER_EPOCH)
    spec.process_slots(state, slot)


def next_epoch_via_block(spec, state):
    """Transition to the start slot of the next epoch via a (signed) full block."""
    block = build_empty_block(
        spec, state,
        state.slot + spec.SLOTS_PER_EPOCH - state.slot % spec.SLOTS_PER_EPOCH)
    return state_transition_and_sign_block(spec, state, block)
