"""Sync-committee signing helpers.

Reference: ``test/helpers/sync_committee.py`` (compute_aggregate_sync_
committee_signature and the sync-aggregate test runner).
"""
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.ssz import hash_tree_root
from .keys import privkeys
from .signing import sign


def compute_sync_committee_signature(spec, state, slot, privkey,
                                     block_root=None):
    domain = spec.get_domain(state, spec.DOMAIN_SYNC_COMMITTEE,
                             spec.compute_epoch_at_slot(slot))
    if block_root is None:
        if slot == state.slot:
            block_root = build_latest_block_root(spec, state)
        else:
            block_root = spec.get_block_root_at_slot(state, slot)
    signing_root = spec.compute_signing_root(block_root, domain)
    return sign(privkey, signing_root)


def build_latest_block_root(spec, state):
    header = state.latest_block_header.copy()
    if bytes(header.state_root) == b"\x00" * 32:
        header.state_root = hash_tree_root(state)
    return hash_tree_root(header)


def compute_aggregate_sync_committee_signature(spec, state, slot,
                                               participants,
                                               block_root=None):
    """Aggregate signature of the given participant validator indices."""
    if len(participants) == 0:
        return spec.G2_POINT_AT_INFINITY
    signatures = [
        compute_sync_committee_signature(spec, state, slot,
                                         privkeys[validator_index],
                                         block_root)
        for validator_index in participants]
    return bls.Aggregate(signatures)


def compute_committee_indices(state, committee=None):
    """Validator indices of the current sync committee members."""
    if committee is None:
        committee = state.current_sync_committee
    all_pubkeys = [bytes(v.pubkey) for v in state.validators]
    return [all_pubkeys.index(bytes(pubkey)) for pubkey in committee.pubkeys]


def run_sync_committee_processing(spec, state, block, expect_exception=False):
    """Process a block's sync aggregate, yielding vector parts."""
    from .context import expect_assertion_error
    yield "pre", state
    yield "sync_aggregate", block.body.sync_aggregate
    if expect_exception:
        expect_assertion_error(
            lambda: spec.process_sync_aggregate(state,
                                                block.body.sync_aggregate))
        yield "post", None
    else:
        spec.process_sync_aggregate(state, block.body.sync_aggregate)
        yield "post", state
