"""Shared metrics snapshot/diff helper for counter-asserted tests.

The differential suites prove "the engine really answered" by asserting
on telemetry counters.  Before this helper each suite hand-rolled
``pre = stats(); ...; post = stats()`` pairs; now they wrap the probed
region::

    from consensus_specs_tpu.test_infra.metrics import counting

    with counting() as delta:
        head = spec.get_head(store)
    assert delta["forkchoice.head{path=engine}"] == 1
    assert delta["forkchoice.fallbacks{reason=guard}"] == 0

``delta`` maps ``name{label=value,...}`` (label suffix omitted for
unlabeled series) to the counter increase across the block; keys absent
from the delta read as 0, so asserting "nothing fell back" needs no
key-existence dance.  Gauges and histograms are not diffed — counters
are the monotonic ones.

The pytest fixture ``metrics_diff`` (registered in ``tests/conftest.py``)
exposes the same context manager as a fixture argument for tests that
prefer injection over imports.
"""
from consensus_specs_tpu.obs import registry


class MetricsDelta(dict):
    """Counter deltas for a ``counting()`` block; missing keys are 0."""

    def __missing__(self, key):
        return 0

    def nonzero(self) -> dict:
        return {k: v for k, v in self.items() if v}


class counting:
    """Context manager snapshotting every counter series on entry and
    exposing the per-series increase after (and during) the block."""

    def __enter__(self) -> MetricsDelta:
        self._before = registry.counter_values()
        self._delta = MetricsDelta()
        return self._delta

    def __exit__(self, exc_type, exc, tb):
        after = registry.counter_values()
        before = self._before
        self._delta.clear()
        for key, value in after.items():
            self._delta[key] = value - before.get(key, 0)
        return False
