"""Voluntary-exit builders. Reference: ``test/helpers/voluntary_exits.py``."""
from .keys import privkeys
from .signing import sign


def prepare_signed_exits(spec, state, indices, fork_version=None):
    def create_signed_exit(index):
        voluntary_exit = spec.VoluntaryExit(
            epoch=spec.get_current_epoch(state),
            validator_index=index,
        )
        return sign_voluntary_exit(spec, state, voluntary_exit,
                                   privkeys[index], fork_version)
    return [create_signed_exit(index) for index in indices]


def _is_post_deneb(spec) -> bool:
    from .context import ALL_PHASES
    return spec.fork in ALL_PHASES \
        and ALL_PHASES.index(spec.fork) >= ALL_PHASES.index("deneb")


def sign_voluntary_exit(spec, state, voluntary_exit, privkey, fork_version=None):
    if fork_version is None:
        if _is_post_deneb(spec):
            # EIP-7044: deneb onward pins exits to the capella fork domain
            # (specs/deneb/beacon-chain.md:411)
            domain = spec.compute_domain(
                spec.DOMAIN_VOLUNTARY_EXIT, spec.config.CAPELLA_FORK_VERSION,
                state.genesis_validators_root)
        else:
            domain = spec.get_domain(state, spec.DOMAIN_VOLUNTARY_EXIT,
                                     voluntary_exit.epoch)
    else:
        domain = spec.compute_domain(
            spec.DOMAIN_VOLUNTARY_EXIT, fork_version, state.genesis_validators_root)
    signing_root = spec.compute_signing_root(voluntary_exit, domain)
    return spec.SignedVoluntaryExit(
        message=voluntary_exit,
        signature=sign(privkey, signing_root),
    )


def run_voluntary_exit_processing(spec, state, signed_voluntary_exit, valid=True):
    validator_index = signed_voluntary_exit.message.validator_index
    yield "pre", state
    yield "voluntary_exit", signed_voluntary_exit
    if not valid:
        try:
            spec.process_voluntary_exit(state, signed_voluntary_exit)
        except (AssertionError, IndexError, ValueError):
            yield "post", None
            return
        raise AssertionError("voluntary exit should have failed")
    pre_exit_epoch = state.validators[validator_index].exit_epoch
    spec.process_voluntary_exit(state, signed_voluntary_exit)
    yield "post", state
    assert pre_exit_epoch == spec.FAR_FUTURE_EPOCH
    assert state.validators[validator_index].exit_epoch < spec.FAR_FUTURE_EPOCH
