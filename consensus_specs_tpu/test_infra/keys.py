"""Deterministic test keypairs.

Reference: ``test/helpers/keys.py`` (privkeys 1..N, pubkeys precomputed).
Pubkeys are computed lazily through the *real* ciphersuite (never stubbed —
states need unique, valid pubkeys even when signature checks are disabled).
"""
from consensus_specs_tpu.ops.bls12_381 import ciphersuite

privkeys = [i + 1 for i in range(8192)]

_pubkey_cache = {}


def pubkey(privkey: int) -> bytes:
    pk = _pubkey_cache.get(privkey)
    if pk is None:
        pk = ciphersuite.SkToPk(privkey)
        _pubkey_cache[privkey] = pk
    return pk


class _PubkeyList:
    """Lazy list-alike: pubkeys[i] is the pubkey of privkeys[i]."""

    def __getitem__(self, i):
        return pubkey(privkeys[i])

    def __len__(self):
        return len(privkeys)


pubkeys = _PubkeyList()


def pubkey_to_privkey(pk: bytes) -> int:
    pk = bytes(pk)
    for sk, known in _pubkey_cache.items():
        if known == pk:
            return sk
    raise KeyError("unknown pubkey (not generated via this module)")
