"""Fork-boundary transition machinery.

Reference model: ``test/helpers/fork_transition.py`` (do_fork,
transition_until_fork, state_transition_across_slots) - drive a pre-fork
state up to the boundary under the pre spec, upgrade it, and continue
under the post spec, collecting the signed blocks that cross the seam.
"""
from consensus_specs_tpu.test_infra.block import (
    build_empty_block, build_empty_block_for_next_slot,
    state_transition_and_sign_block, next_slots, sign_block,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root

_UPGRADE_FN = {
    "altair": "upgrade_to_altair",
    "bellatrix": "upgrade_to_bellatrix",
    "capella": "upgrade_to_capella",
    "deneb": "upgrade_to_deneb",
    "eip6110": "upgrade_to_eip6110",
    "eip7002": "upgrade_to_eip7002",
    "whisk": "upgrade_to_whisk",
}


def transition_until_fork(spec, state, fork_epoch):
    """Advance (empty slots) to the last slot before the fork epoch."""
    to_slot = fork_epoch * spec.SLOTS_PER_EPOCH - 1
    assert state.slot < to_slot, "state already at/after the fork boundary"
    next_slots(spec, state, int(to_slot) - int(state.slot))


def state_transition_across_slots(spec, state, to_slot):
    """Produce one signed empty block per slot up to ``to_slot``
    (inclusive), returning the signed blocks."""
    blocks = []
    while int(state.slot) < int(to_slot):
        block = build_empty_block_for_next_slot(spec, state)
        blocks.append(state_transition_and_sign_block(spec, state, block))
    return blocks


def do_fork(state, spec, post_spec, fork_epoch, with_block=True):
    """Cross the boundary: pre-spec epoch processing into the fork slot,
    state upgrade, and (optionally) the first post-fork block.

    Returns (post_state, signed_fork_block_or_None).
    """
    fork_slot = fork_epoch * spec.SLOTS_PER_EPOCH
    assert int(state.slot) == int(fork_slot) - 1
    spec.process_slots(state, fork_slot)

    post_state = getattr(post_spec, _UPGRADE_FN[post_spec.fork])(state)
    assert bytes(post_state.fork.current_version) == bytes(getattr(
        post_spec.config, f"{post_spec.fork.upper()}_FORK_VERSION"))

    if not with_block:
        return post_state, None
    # the first post-fork block sits AT the fork slot: the state is already
    # there, so apply process_block directly (no process_slots)
    block = build_empty_block(post_spec, post_state, slot=fork_slot)
    post_spec.process_block(post_state, block)
    block.state_root = hash_tree_root(post_state)
    signed = sign_block(post_spec, post_state, block, block.proposer_index)
    return post_state, signed


def transition_to_next_epoch_and_append_blocks(spec, state, blocks,
                                               epochs=1):
    """Continue block production for ``epochs`` epochs under ``spec``."""
    target = int(state.slot) + epochs * int(spec.SLOTS_PER_EPOCH)
    blocks.extend(state_transition_across_slots(spec, state, target))
    return blocks
