"""Deposit-building helpers with real Merkle proofs.

Reference: ``test/helpers/deposits.py`` — builds the deposit-contract tree
(depth 32) and per-deposit branches, so ``process_deposit``'s
``is_valid_merkle_branch`` check is exercised for real.
"""
from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.utils.ssz import hash_tree_root, uint64
from consensus_specs_tpu.utils.ssz.merkle import zero_hashes
from consensus_specs_tpu.utils import bls
from .keys import privkeys, pubkeys
from .signing import sign


def _merkle_tree(leaves, depth):
    """Layers[0]=leaves padded virtually; returns list of dict layers."""
    layers = [{i: leaf for i, leaf in enumerate(leaves)}]
    for d in range(depth):
        prev = layers[-1]
        nxt = {}
        for i in set(k // 2 for k in prev):
            left = prev.get(2 * i, zero_hashes[d])
            right = prev.get(2 * i + 1, zero_hashes[d])
            nxt[i] = hash(left + right)
        layers.append(nxt)
    return layers


def _merkle_root_and_proof(leaves, depth, index):
    layers = _merkle_tree(leaves, depth)
    proof = []
    for d in range(depth):
        sibling = (index >> d) ^ 1
        proof.append(layers[d].get(sibling, zero_hashes[d]))
    root = layers[depth].get(0, zero_hashes[depth])
    return root, proof


def build_deposit_data(spec, pubkey, privkey, amount, withdrawal_credentials, signed=False):
    deposit_data = spec.DepositData(
        pubkey=pubkey,
        withdrawal_credentials=withdrawal_credentials,
        amount=amount,
    )
    if signed:
        sign_deposit_data(spec, deposit_data, privkey)
    return deposit_data


def sign_deposit_data(spec, deposit_data, privkey):
    deposit_message = spec.DepositMessage(
        pubkey=deposit_data.pubkey,
        withdrawal_credentials=deposit_data.withdrawal_credentials,
        amount=deposit_data.amount)
    domain = spec.compute_domain(spec.DOMAIN_DEPOSIT)
    signing_root = spec.compute_signing_root(deposit_message, domain)
    deposit_data.signature = sign(privkey, signing_root)


def build_deposit(spec, deposit_data_list, pubkey, privkey, amount,
                  withdrawal_credentials, signed):
    deposit_data = build_deposit_data(
        spec, pubkey, privkey, amount, withdrawal_credentials, signed)
    index = len(deposit_data_list)
    deposit_data_list.append(deposit_data)
    return deposit_from_context(spec, deposit_data_list, index)


def deposit_from_context(spec, deposit_data_list, index):
    depth = spec.DEPOSIT_CONTRACT_TREE_DEPTH
    leaves = [hash_tree_root(d) for d in deposit_data_list]
    root, proof = _merkle_root_and_proof(leaves, depth, index)
    # mix in the list length (List merkleization) as the last proof element
    root = hash(root + uint64(len(leaves)).serialize().ljust(32, b"\x00"))
    proof = proof + [uint64(len(leaves)).serialize().ljust(32, b"\x00")]
    deposit = spec.Deposit(
        proof=proof,
        data=deposit_data_list[index],
    )
    return deposit, root, deposit_data_list


def prepare_full_genesis_deposits(spec, amount, deposit_count, signed=False,
                                  duplicate_last=False,
                                  deposit_data_list=None,
                                  min_pubkey_index=0):
    """Build ``deposit_count`` genesis deposits whose proofs verify against
    the incrementally-growing deposit tree, the way
    ``initialize_beacon_state_from_eth1`` consumes them
    (reference helpers/deposits.py prepare_full_genesis_deposits).

    ``deposit_data_list`` continues an existing deposit tree (for mixed
    batches: full-balance then small-balance/top-up deposits);
    ``min_pubkey_index`` offsets into the test key pool so batches can
    target fresh or repeated keys."""
    deposit_data_list = deposit_data_list if deposit_data_list is not None \
        else []
    genesis_deposits = []
    for index in range(deposit_count):
        key_index = min_pubkey_index + (
            index if not (duplicate_last and index == deposit_count - 1)
            else index - 1)
        pubkey = pubkeys[key_index]
        privkey = privkeys[key_index]
        withdrawal_credentials = spec.BLS_WITHDRAWAL_PREFIX + hash(pubkey)[1:]
        deposit_data = build_deposit_data(
            spec, pubkey, privkey, amount, withdrawal_credentials,
            signed=signed)
        deposit_data_list.append(deposit_data)
        # genesis proof: against the tree of deposits seen SO FAR
        # (the list holds exactly len so far).  NOTE: keyed off the
        # 8192-entry test key pool and O(n^2) tree rebuilds — minimal-preset
        # genesis counts only (callers guard with @with_presets).
        deposit, root, _ = deposit_from_context(
            spec, deposit_data_list, len(deposit_data_list) - 1)
        genesis_deposits.append(deposit)
    return genesis_deposits, root, deposit_data_list


def prepare_state_and_deposit(spec, state, validator_index, amount,
                              withdrawal_credentials=None, signed=False):
    """Prepare the state for the deposit, and create a deposit for the given
    validator, depositing the given amount."""
    deposit_data_list = []
    pubkey = pubkeys[validator_index]
    privkey = privkeys[validator_index]
    if withdrawal_credentials is None:
        # insecurely use pubkey as withdrawal key
        withdrawal_credentials = spec.BLS_WITHDRAWAL_PREFIX + hash(pubkey)[1:]
    deposit, root, deposit_data_list = build_deposit(
        spec, deposit_data_list, pubkey, privkey, amount,
        withdrawal_credentials, signed)
    state.eth1_deposit_index = 0
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = len(deposit_data_list)
    return deposit


def run_deposit_processing(spec, state, deposit, validator_index, valid=True,
                           effective=True):
    """Run ``process_deposit``, yielding (pre, deposit, post)."""
    pre_validator_count = len(state.validators)
    pre_balance = 0
    is_top_up = validator_index < pre_validator_count
    if is_top_up:
        pre_balance = state.balances[validator_index]

    yield "pre", state
    yield "deposit", deposit

    if not valid:
        try:
            spec.process_deposit(state, deposit)
        except (AssertionError, IndexError, ValueError):
            yield "post", None
            return
        raise AssertionError("deposit processing should have failed")

    spec.process_deposit(state, deposit)

    yield "post", state

    if not effective or not bls.KeyValidate(deposit.data.pubkey):
        assert len(state.validators) == pre_validator_count
        if is_top_up:
            assert state.balances[validator_index] == pre_balance
    else:
        if is_top_up:
            assert len(state.validators) == pre_validator_count
            assert state.balances[validator_index] == pre_balance + deposit.data.amount
        else:
            assert len(state.validators) == pre_validator_count + 1
            assert state.balances[validator_index] == deposit.data.amount
    assert state.eth1_deposit_index == state.eth1_data.deposit_count
