"""Execution-payload builders for tests.

Reference: ``test/helpers/execution_payload.py`` (build_empty_execution_payload,
compute_el_block_hash).  Divergence: the reference fabricates a realistic
RLP + Merkle-Patricia ``block_hash`` so vectors look like mainnet blocks;
consensus validity never depends on it (the Noop engine accepts any hash,
``pysetup/spec_builders/bellatrix.py:40-65``), so here the hash is a
deterministic SSZ-derived digest instead of an RLP encoding.
"""
from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.utils.ssz import hash_tree_root


def compute_el_block_hash(spec, payload):
    """Deterministic stand-in for the execution block hash: digest of the
    payload with its own block_hash field zeroed."""
    snapshot = payload.copy()
    snapshot.block_hash = spec.Hash32()
    return spec.Hash32(hash(hash_tree_root(snapshot) + b"el-block-hash"))


def build_empty_execution_payload(spec, state, randao_mix=None):
    """A payload that passes process_execution_payload against ``state``
    (already advanced to the block's slot)."""
    latest = state.latest_execution_payload_header
    timestamp = spec.compute_timestamp_at_slot(state, state.slot)
    if randao_mix is None:
        randao_mix = spec.get_randao_mix(state, spec.get_current_epoch(state))
    payload = spec.ExecutionPayload(
        parent_hash=latest.block_hash,
        fee_recipient=spec.ExecutionAddress(),
        state_root=latest.state_root,  # no EL state change for empty payload
        receipts_root=spec.Bytes32(bytes.fromhex(
            "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421")),
        logs_bloom=b"\x00" * spec.BYTES_PER_LOGS_BLOOM,
        prev_randao=randao_mix,
        block_number=latest.block_number + 1,
        gas_limit=latest.gas_limit,
        gas_used=0,
        timestamp=timestamp,
        extra_data=b"",
        base_fee_per_gas=latest.base_fee_per_gas,
    )
    if hasattr(payload, "withdrawals"):
        payload.withdrawals = spec.get_expected_withdrawals(state)
    payload.block_hash = compute_el_block_hash(spec, payload)
    return payload


def build_state_with_incomplete_transition(spec, state):
    """State whose payload header is empty (pre-merge)."""
    return build_state_with_execution_payload_header(
        spec, state, spec.ExecutionPayloadHeader())


def build_state_with_complete_transition(spec, state):
    """State with a non-empty payload header (merge complete)."""
    return build_state_with_execution_payload_header(
        spec, state, spec.default_payload_header())


def build_state_with_execution_payload_header(spec, state, header):
    pre_state = state.copy()
    pre_state.latest_execution_payload_header = header
    return pre_state
