"""Execution-payload builders for tests.

Reference: ``test/helpers/execution_payload.py`` (build_empty_execution_payload,
compute_el_block_hash).  The ``block_hash`` is the REAL execution block
hash — ``keccak256(rlp(header))`` with EIP-2718/4895 indexed tries for
transactions / withdrawals / deposit-receipts / exits — via the in-repo
keccak/RLP/MPT implementations (``utils/keccak.py``, ``utils/el_trie.py``;
the reference uses the external eth_hash/rlp/trie packages), so
bellatrix+ vectors carry reference-corpus-compatible hashes.  Consensus
validity never depends on the value (the Noop engine accepts any hash,
``pysetup/spec_builders/bellatrix.py:40-65``).
"""
from consensus_specs_tpu.utils.keccak import keccak256
from consensus_specs_tpu.utils.el_trie import indexed_trie_root, rlp_encode

# keccak256 of the RLP of an empty ommers list — constant in every
# post-merge header (EIP-3675 fixes ommers to []).
_EMPTY_OMMERS_HASH = bytes.fromhex(
    "1dcc4de8dec75d7aab85b567b6ccd41ad312451b948a7413f0a142fd40d49347")


def _withdrawal_rlp(w) -> bytes:
    # EIP-4895 network encoding
    return rlp_encode([int(w.index), int(w.validator_index),
                       bytes(w.address), int(w.amount)])


def _deposit_receipt_rlp(r) -> bytes:
    return rlp_encode([bytes(r.pubkey), bytes(r.withdrawal_credentials),
                       int(r.amount), bytes(r.signature), int(r.index)])


def _exit_rlp(e) -> bytes:
    return rlp_encode([bytes(e.source_address), bytes(e.validator_pubkey)])


def compute_el_block_hash(spec, payload):
    """keccak256 of the RLP execution header described by ``payload``
    (reference ``compute_el_header_block_hash``; field order per
    EIP-3675/4399/1559/4895/4844)."""
    header = [
        bytes(payload.parent_hash),
        _EMPTY_OMMERS_HASH,
        bytes(payload.fee_recipient),
        bytes(payload.state_root),
        indexed_trie_root(bytes(tx) for tx in payload.transactions),
        bytes(payload.receipts_root),
        bytes(payload.logs_bloom),
        0,                                   # difficulty (EIP-3675)
        int(payload.block_number),
        int(payload.gas_limit),
        int(payload.gas_used),
        int(payload.timestamp),
        bytes(payload.extra_data),
        bytes(payload.prev_randao),          # mixHash (EIP-4399)
        b"\x00" * 8,                         # nonce (EIP-3675)
        int(payload.base_fee_per_gas),       # EIP-1559
    ]
    if hasattr(payload, "withdrawals"):
        header.append(indexed_trie_root(
            _withdrawal_rlp(w) for w in payload.withdrawals))
    if hasattr(payload, "blob_gas_used"):
        # NOTE: the reference generator appends only the two gas fields -
        # no EIP-4788 parent_beacon_block_root - so real Cancun headers
        # differ, but corpus compatibility is defined by the reference's
        # own fabrication (helpers/execution_payload.py:103-107), which
        # this matches field-for-field (including its blob_gas_used-first
        # ordering).
        header.append(int(payload.blob_gas_used))
        header.append(int(payload.excess_blob_gas))
    if hasattr(payload, "deposit_receipts"):
        header.append(indexed_trie_root(
            _deposit_receipt_rlp(r) for r in payload.deposit_receipts))
    if hasattr(payload, "exits"):
        header.append(indexed_trie_root(
            _exit_rlp(e) for e in payload.exits))
    return spec.Hash32(keccak256(rlp_encode(header)))


def build_empty_execution_payload(spec, state, randao_mix=None):
    """A payload that passes process_execution_payload against ``state``
    (already advanced to the block's slot)."""
    latest = state.latest_execution_payload_header
    timestamp = spec.compute_timestamp_at_slot(state, state.slot)
    if randao_mix is None:
        randao_mix = spec.get_randao_mix(state, spec.get_current_epoch(state))
    payload = spec.ExecutionPayload(
        parent_hash=latest.block_hash,
        fee_recipient=spec.ExecutionAddress(),
        state_root=latest.state_root,  # no EL state change for empty payload
        receipts_root=spec.Bytes32(bytes.fromhex(
            "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421")),
        logs_bloom=b"\x00" * spec.BYTES_PER_LOGS_BLOOM,
        prev_randao=randao_mix,
        block_number=latest.block_number + 1,
        gas_limit=latest.gas_limit,
        gas_used=0,
        timestamp=timestamp,
        extra_data=b"",
        base_fee_per_gas=latest.base_fee_per_gas,
    )
    if hasattr(payload, "withdrawals"):
        payload.withdrawals = spec.get_expected_withdrawals(state)
    payload.block_hash = compute_el_block_hash(spec, payload)
    return payload


def build_state_with_incomplete_transition(spec, state):
    """State whose payload header is empty (pre-merge)."""
    return build_state_with_execution_payload_header(
        spec, state, spec.ExecutionPayloadHeader())


def build_state_with_complete_transition(spec, state):
    """State with a non-empty payload header (merge complete)."""
    return build_state_with_execution_payload_header(
        spec, state, spec.default_payload_header())


def build_state_with_execution_payload_header(spec, state, header):
    pre_state = state.copy()
    pre_state.latest_execution_payload_header = header
    return pre_state


# -- blob-transaction fabrication (deneb payload tests) ---------------------
#
# The reference fabricates a mock SSZ "SignedBlobTransaction" carrying
# the blob versioned hashes and prefixes it with the EIP-4844 tx type
# (reference test/helpers/sharding.py get_sample_opaque_tx).  This
# framework's mock wire format (NOT the real EIP-4844 encoding, same as
# the reference's mock is not): 0x03 || uint64-LE count || count x 32-byte
# versioned hashes.  Only the test execution engine parses it.

BLOB_TX_TYPE = 0x03


def tx_with_versioned_hashes(versioned_hashes):
    return (bytes([BLOB_TX_TYPE])
            + len(versioned_hashes).to_bytes(8, "little")
            + b"".join(bytes(h) for h in versioned_hashes))


def parse_blob_tx_versioned_hashes(tx: bytes):
    """Inverse of ``tx_with_versioned_hashes``; raises on malformed tx."""
    tx = bytes(tx)
    if len(tx) < 9 or tx[0] != BLOB_TX_TYPE:
        raise ValueError("not a blob transaction")
    count = int.from_bytes(tx[1:9], "little")
    body = tx[9:]
    if len(body) != 32 * count:
        raise ValueError("blob tx length mismatch")
    return [body[i * 32:(i + 1) * 32] for i in range(count)]


def get_sample_opaque_tx(spec, blob_count=1):
    """(opaque_tx, blobs, blob_kzg_commitments, proofs) for payload tests.

    Deterministic: commitment bytes are fabricated (infinity-point
    commitments with distinct trailing bytes) — versioned-hash
    validation is a pure byte-hashing path, no KZG math needed (the kzg
    test suites cover the real commitment math)."""
    blobs, commitments, proofs = [], [], []
    for i in range(blob_count):
        commitment = spec.KZGCommitment(
            bytes([0xC0]) + b"\x00" * 46 + bytes([i]))
        blobs.append(spec.Blob(b"\x00" * (32 * spec.FIELD_ELEMENTS_PER_BLOB)))
        commitments.append(commitment)
        proofs.append(spec.KZGProof(bytes([0xC0]) + b"\x00" * 47))
    hashes = [spec.kzg_commitment_to_versioned_hash(c) for c in commitments]
    return tx_with_versioned_hashes(hashes), blobs, commitments, proofs


class BlobVersionedHashesExecutionEngine:
    """Test engine implementing ``is_valid_versioned_hashes`` for real:
    parses blob transactions in the payload and compares their hashes
    with the NewPayloadRequest's (the check the NoopExecutionEngine
    stubs to True; role of the reference's test-only engine in
    ``test/deneb/block_processing/test_process_execution_payload.py``)."""

    def __init__(self, spec):
        self.spec = spec

    def notify_new_payload(self, *args, **kwargs) -> bool:
        return True

    def is_valid_block_hash(self, new_payload_request) -> bool:
        payload = new_payload_request.execution_payload
        return payload.block_hash == compute_el_block_hash(
            self.spec, payload)

    def is_valid_versioned_hashes(self, new_payload_request) -> bool:
        try:
            expected = []
            for tx in new_payload_request.execution_payload.transactions:
                tx = bytes(tx)
                if tx[:1] == bytes([BLOB_TX_TYPE]):
                    expected.extend(parse_blob_tx_versioned_hashes(tx))
            return [bytes(h) for h in
                    new_payload_request.versioned_hashes] == expected
        except Exception:
            return False

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        return (self.is_valid_block_hash(new_payload_request)
                and self.is_valid_versioned_hashes(new_payload_request)
                and self.notify_new_payload(new_payload_request))
