"""Rewards-deltas test machinery.

Reference: ``test/helpers/rewards.py`` (the 520-LoC ``run_deltas`` family):
run each reward component in isolation, emit its per-validator deltas as
vector parts, and sanity-check them against spec invariants.
"""
from random import Random

from consensus_specs_tpu.utils.ssz import List, uint64


def _deltas_list(spec, values):
    return List[uint64, spec.VALIDATOR_REGISTRY_LIMIT](
        [uint64(int(v)) for v in values])


def has_enough_for_reward(spec, state, index) -> bool:
    """A validator with a tiny balance may earn a zero reward; exclude
    those from 'must be rewarded' assertions (reference rewards.py)."""
    return (state.validators[index].effective_balance
            * spec.BASE_REWARD_FACTOR
            > spec.integer_squareroot(spec.get_total_active_balance(state))
            // spec.BASE_REWARDS_PER_EPOCH)


def run_deltas(spec, state):
    """Yield deltas for every reward component (phase0: source/target/head/
    inclusion-delay/inactivity; altair+: per-flag + inactivity)."""
    if spec.fork == "phase0":
        yield from run_attestation_component_deltas(
            spec, state, spec.get_source_deltas, "source_deltas",
            spec.get_matching_source_attestations)
        yield from run_attestation_component_deltas(
            spec, state, spec.get_target_deltas, "target_deltas",
            spec.get_matching_target_attestations)
        yield from run_attestation_component_deltas(
            spec, state, spec.get_head_deltas, "head_deltas",
            spec.get_matching_head_attestations)
        yield from run_get_inclusion_delay_deltas(spec, state)
    else:
        for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
            rewards, penalties = spec.get_flag_index_deltas(state, flag_index)
            yield f"flag_index_{flag_index}_deltas", {
                "rewards": _deltas_list(spec, rewards),
                "penalties": _deltas_list(spec, penalties)}
    yield from run_get_inactivity_penalty_deltas(spec, state)


def run_attestation_component_deltas(spec, state, component_delta_fn,
                                     deltas_name, matching_att_fn):
    """One of source/target/head: attesters rewarded, non-attesters
    penalized (reference rewards.py run_attestation_component_deltas)."""
    rewards, penalties = component_delta_fn(state)
    yield deltas_name, {"rewards": _deltas_list(spec, rewards),
                        "penalties": _deltas_list(spec, penalties)}

    matching_attestations = matching_att_fn(
        state, spec.get_previous_epoch(state))
    matching_indices = spec.get_unslashed_attesting_indices(
        state, matching_attestations)
    eligible_indices = set(spec.get_eligible_validator_indices(state))
    for index in range(len(state.validators)):
        if index not in eligible_indices:
            assert rewards[index] == 0 and penalties[index] == 0
            continue
        if index in matching_indices:
            if has_enough_for_reward(spec, state, index) \
                    and not spec.is_in_inactivity_leak(state):
                assert rewards[index] > 0
            assert penalties[index] == 0
        else:
            assert rewards[index] == 0
            if has_enough_for_reward(spec, state, index):
                assert penalties[index] > 0


def run_get_inclusion_delay_deltas(spec, state):
    rewards, penalties = spec.get_inclusion_delay_deltas(state)
    yield "inclusion_delay_deltas", {
        "rewards": _deltas_list(spec, rewards),
        "penalties": _deltas_list(spec, penalties)}
    # inclusion delay never penalizes (beacon-chain.md:1512)
    assert all(p == 0 for p in penalties)


def _altair_inactivity_quotient(spec):
    """Fork-graduated quotient (altair beacon-chain.md Modified
    get_inactivity_penalty_deltas; bellatrix shrinks the quotient, raising the penalty)."""
    if hasattr(spec, "INACTIVITY_PENALTY_QUOTIENT_BELLATRIX") \
            and spec.fork not in ("phase0", "altair"):
        return spec.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
    return spec.INACTIVITY_PENALTY_QUOTIENT_ALTAIR


def run_get_inactivity_penalty_deltas(spec, state):
    rewards, penalties = spec.get_inactivity_penalty_deltas(state)
    yield "inactivity_penalty_deltas", {
        "rewards": _deltas_list(spec, rewards),
        "penalties": _deltas_list(spec, penalties)}
    # inactivity never rewards
    assert all(r == 0 for r in rewards)
    if spec.fork == "phase0":
        # outside a leak, phase0 still charges the base-reward offset;
        # its exact deltas are covered by the phase0 rewards suite
        return
    # altair+: the penalty tracks the inactivity SCORE whether or not a
    # leak is on; target participants and ineligible indices pay nothing
    matching = spec.get_unslashed_participating_indices(
        state, spec.TIMELY_TARGET_FLAG_INDEX, spec.get_previous_epoch(state))
    eligible = set(spec.get_eligible_validator_indices(state))
    denominator = (spec.config.INACTIVITY_SCORE_BIAS
                   * _altair_inactivity_quotient(spec))
    for index in range(len(state.validators)):
        if index not in eligible or index in matching:
            assert penalties[index] == 0
        else:
            expected = (state.validators[index].effective_balance
                        * state.inactivity_scores[index]) // denominator
            assert penalties[index] == expected


# ---------------------------------------------------------------------------
# state preparation
# ---------------------------------------------------------------------------

def prepare_state_with_attestations(spec, state, participation_fn=None):
    """Attest every slot of one full epoch, including each attestation
    after MIN_ATTESTATION_INCLUSION_DELAY (reference rewards.py
    prepare_state_with_attestations)."""
    from .attestations import get_valid_attestation
    from .block import next_slot

    start_epoch = spec.get_current_epoch(state)
    attestations = []
    pending = []  # (creation slot, [attestations])
    for iteration in range(spec.SLOTS_PER_EPOCH
                           + spec.MIN_ATTESTATION_INCLUSION_DELAY):
        if iteration < spec.SLOTS_PER_EPOCH:
            committees = spec.get_committee_count_per_slot(
                state, spec.get_current_epoch(state))
            slot_atts = []
            for index in range(committees):
                def participants(comm):
                    if participation_fn is None:
                        return comm
                    return participation_fn(comm)
                # signed=True keeps generated vectors verifiable under
                # real BLS (generators force bls_active; under pytest's
                # default bls-off the signing is a cheap stub)
                attestation = get_valid_attestation(
                    spec, state, state.slot, index=index,
                    filter_participant_set=participants, signed=True)
                if any(attestation.aggregation_bits):
                    slot_atts.append(attestation)
            pending.append((state.slot, slot_atts))
        next_slot(spec, state)
        while pending and pending[0][0] \
                + spec.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot:
            _, atts = pending.pop(0)
            for attestation in atts:
                spec.process_attestation(state, attestation)
                attestations.append(attestation)
    assert spec.get_current_epoch(state) == start_epoch + 1
    if spec.fork == "phase0" and participation_fn is None:
        assert len(state.previous_epoch_attestations) == len(attestations)
    return attestations


def randomize_participation(rng: Random, fraction=0.7):
    def participation_fn(committee):
        return set(i for i in committee if rng.random() < fraction)
    return participation_fn


def set_state_in_leak(spec, state):
    """Advance far enough past finality to trigger the inactivity leak."""
    from .block import next_epoch
    for _ in range(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY + 2):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)
