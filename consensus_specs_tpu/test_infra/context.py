"""Harness globals set by pytest CLI flags (filled out with the decorator DSL).

Reference: tests/core/pyspec/eth2spec/test/context.py + conftest.py.
"""
DEFAULT_TEST_PRESET = "minimal"
DEFAULT_BLS_ACTIVE = True
DEFAULT_BLS_TYPE = "py"
ONLY_FORK = None
