"""Test decorator DSL.

Reference: ``test/context.py`` — @spec_state_test, @with_all_phases,
@with_phases, @with_presets, @always_bls/@never_bls, @with_custom_state,
@with_config_overrides, expect_assertion_error, plus the genesis-state LRU
cache (context.py:61-81). Tests are written once as generators yielding
(name, value) vector parts; under pytest the parts are consumed and
discarded, under the vector generator they are written to files.
"""
import functools

import pytest

from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.env_flags import HEAVY  # noqa: F401 (re-export)
from consensus_specs_tpu.utils.ssz import serialize, deserialize
from consensus_specs_tpu.forks import build_spec, fork_registry
from .genesis import create_genesis_state

# set by tests/conftest.py from pytest CLI flags
DEFAULT_TEST_PRESET = "minimal"
DEFAULT_BLS_ACTIVE = True
DEFAULT_BLS_TYPE = "py"
ONLY_FORK = None

ALL_PHASES = ("phase0", "altair", "bellatrix", "capella", "deneb")
# feature forks: selectable via with_phases, excluded from with_all_phases
FEATURE_PHASES = ("eip6110", "eip7002", "eip7594", "whisk",
                  "sharding", "custody_game", "eip6914")
MINIMAL = "minimal"
MAINNET = "mainnet"
# HEAVY (the crypto-tier gate) is imported above for harness users


def _available_phases():
    reg = fork_registry()
    return [p for p in ALL_PHASES + FEATURE_PHASES if p in reg]


# ---------------------------------------------------------------------------
# balance profiles (reference context.py:100-196)
# ---------------------------------------------------------------------------

def default_balances(spec):
    num_validators = spec.SLOTS_PER_EPOCH * 8
    return [spec.MAX_EFFECTIVE_BALANCE] * num_validators


def default_activation_threshold(spec):
    return spec.MAX_EFFECTIVE_BALANCE


def zero_activation_threshold(spec):
    return 0


def low_balances(spec):
    num_validators = spec.SLOTS_PER_EPOCH * 8
    low_balance = 18 * 10**9
    return [low_balance] * num_validators


def misc_balances(spec):
    num_validators = spec.SLOTS_PER_EPOCH * 8
    balances = [spec.MAX_EFFECTIVE_BALANCE * 2 * i // num_validators
                for i in range(num_validators)]
    rng = __import__("random").Random(929)
    rng.shuffle(balances)
    return balances


def large_validator_set(spec):
    num_validators = 2 * spec.SLOTS_PER_EPOCH * spec.MAX_COMMITTEES_PER_SLOT \
        * spec.TARGET_COMMITTEE_SIZE
    return [spec.MAX_EFFECTIVE_BALANCE] * num_validators


# ---------------------------------------------------------------------------
# genesis-state cache: immutable serialized snapshot, fresh copy per test
# ---------------------------------------------------------------------------

_state_cache = {}


def _get_genesis_state(spec, balances_fn, threshold_fn):
    # spec instances are cached per (fork, preset, config-overrides) in
    # build_spec, so the instance id discriminates config-overridden specs
    key = (spec.fork, spec.preset_name, id(spec),
           balances_fn.__name__, threshold_fn.__name__)
    blob = _state_cache.get(key)
    if blob is None:
        state = create_genesis_state(spec, balances_fn(spec), threshold_fn(spec))
        blob = serialize(state)
        _state_cache[key] = blob
    return deserialize(spec.BeaconState, blob)


# ---------------------------------------------------------------------------
# core runners
# ---------------------------------------------------------------------------

def expect_assertion_error(fn):
    """reference context.py:299-310 — AssertionError/IndexError mean 'invalid'."""
    bad_success = False
    try:
        fn()
        bad_success = True
    except (AssertionError, IndexError, ValueError):
        pass
    if bad_success:
        raise AssertionError("expected an assertion error, but got none")


# Set by the vector generator (gen/gen_runner.py): a callable receiving
# every yielded (name, value) part.  Under pytest it stays None and parts
# are consumed and discarded — the reference's two-consumption-mode design
# (context.py vector_test + gen_runner is_pytest flag).
VECTOR_COLLECTOR = None


def pytest_only(fn):
    """Mark a test as pytest-only: the vector generators skip it.

    For negatives that assert API behavior without yielding the parts a
    vector format requires - emitting them would produce empty,
    format-violating case directories."""
    fn._pytest_only = True
    return fn


def emit_part(name, value):
    """Push one vector part straight to the active collector (no-op under
    pytest, where VECTOR_COLLECTOR is None).

    The reference's fork-choice helpers are generators that ``yield`` their
    block/attestation parts up through the test (helpers/fork_choice.py:166).
    Ours are plain functions called imperatively, so they emit parts in
    event order through this hook instead; the test itself still yields its
    trailing parts (e.g. the ``steps`` event log)."""
    if VECTOR_COLLECTOR is not None:
        VECTOR_COLLECTOR((name, value))


def _consume(result):
    """Run a test generator to completion (pytest mode discards the parts;
    generator mode forwards them to VECTOR_COLLECTOR).

    Only live generators forward: nested decorators (@always_bls inside
    @spec_test) call _consume twice, and re-forwarding the returned list
    would hand the collector already-mutated state objects."""
    import inspect
    if inspect.isgenerator(result):
        if VECTOR_COLLECTOR is None:
            return list(result)
        out = []
        for part in result:
            # a bare `yield` (None) marks a part-less test, not a part
            if part is not None:
                VECTOR_COLLECTOR(part)
            out.append(part)
        return out
    return result


def _set_bls_backend():
    if DEFAULT_BLS_TYPE == "jax":
        bls.use_jax()
    elif DEFAULT_BLS_TYPE == "native":
        bls.use_native()
    elif DEFAULT_BLS_TYPE == "fastest":
        bls.use_fastest()
    else:
        bls.use_py()


def spec_test(fn):
    """Consume vector yields; apply the session default bls setting."""
    @functools.wraps(fn)
    def entry(*args, **kwargs):
        old_active = bls.bls_active
        bls.bls_active = DEFAULT_BLS_ACTIVE
        _set_bls_backend()
        try:
            return _consume(fn(*args, **kwargs))
        finally:
            bls.bls_active = old_active
    return entry


def always_bls(fn):
    """Force signature checks on for this test regardless of --disable-bls."""
    @functools.wraps(fn)
    def entry(*args, **kwargs):
        old = bls.bls_active
        bls.bls_active = True
        try:
            return _consume(fn(*args, **kwargs))
        finally:
            bls.bls_active = old
    entry._bls_mode = "always"
    return entry


def never_bls(fn):
    @functools.wraps(fn)
    def entry(*args, **kwargs):
        old = bls.bls_active
        bls.bls_active = False
        try:
            return _consume(fn(*args, **kwargs))
        finally:
            bls.bls_active = old
    entry._bls_mode = "never"
    return entry


def disable_process_reveal_deadlines(fn):
    """custody_game: no-op ``process_reveal_deadlines`` so tests can walk
    past custody periods without mass-slashing the registry (reference
    ``context.py`` decorator of the same name)."""
    @functools.wraps(fn)
    def entry(*args, spec, **kwargs):
        if hasattr(spec, "process_reveal_deadlines"):
            # shadow the bound method on the (cached, shared) instance;
            # consume the (lazy) test generator INSIDE the patch scope or
            # the revert would land before the test body ever runs
            spec.process_reveal_deadlines = lambda state: None
            try:
                return _consume(fn(*args, spec=spec, **kwargs))
            finally:
                del spec.process_reveal_deadlines
        return fn(*args, spec=spec, **kwargs)
    return entry


def with_custom_state(balances_fn, threshold_fn):
    def deco(fn):
        @functools.wraps(fn)
        def entry(*args, spec, **kwargs):
            state = _get_genesis_state(spec, balances_fn, threshold_fn)
            return fn(*args, spec=spec, state=state, **kwargs)
        return entry
    return deco


def with_state(fn):
    return with_custom_state(default_balances, default_activation_threshold)(fn)


def single_phase(fn):
    return fn


def spec_state_test(fn):
    """reference context.py:250-251: spec_test + with_state + single_phase"""
    return spec_test(with_state(single_phase(fn)))


def with_config_overrides(config_overrides):
    """Swap the spec for one built with overridden config vars."""
    def deco(fn):
        @functools.wraps(fn)
        def entry(*args, spec, **kwargs):
            overridden = build_spec(spec.fork, spec.preset_name, config_overrides)
            return fn(*args, spec=overridden, **kwargs)
        return entry
    return deco


def with_phases(phases, other_phases=None):
    """Run the test once per fork in ``phases`` (intersected with CLI --fork)."""
    def deco(fn):
        @functools.wraps(fn)
        def entry(*args, **kwargs):
            available = _available_phases()
            ran = False
            for fork in phases:
                if fork not in available:
                    continue
                if ONLY_FORK is not None and fork != ONLY_FORK:
                    continue
                spec = build_spec(fork, DEFAULT_TEST_PRESET)
                fn(*args, spec=spec, **kwargs)
                ran = True
            if not ran:
                pytest.skip("no selected fork supports this test")
        # pytest introspects __wrapped__ for the signature and would treat
        # spec/state as fixtures; the wrapper takes no pytest arguments.
        if hasattr(entry, "__wrapped__"):
            del entry.__wrapped__
        return entry
    return deco


def with_all_phases(fn):
    return with_phases(ALL_PHASES)(fn)


class ForkMeta:
    """One fork-boundary scenario: pre fork, post fork, activation epoch
    (reference context.py:627-664 @with_fork_metas)."""

    def __init__(self, pre_fork_name, post_fork_name, fork_epoch):
        self.pre_fork_name = pre_fork_name
        self.post_fork_name = post_fork_name
        self.fork_epoch = fork_epoch


# adjacent stable-fork pairs, for transition suites
AFTER_FORK_PAIRS = tuple(zip(ALL_PHASES[:-1], ALL_PHASES[1:]))


def with_fork_metas(fork_metas):
    """Run a transition test once per ForkMeta with BOTH specs bound.

    The test receives (state, fork_epoch, spec, post_spec); under the
    generator, cases are filed under the POST fork's directory while
    executing from the PRE fork's genesis (reference runs these with
    pre_tag/post_tag block wrappers; our blocks carry their spec's types
    directly).
    """
    def deco(fn):
        @functools.wraps(fn)
        def entry(*args, **kwargs):
            available = _available_phases()
            ran = False
            for meta in fork_metas:
                if meta.pre_fork_name not in available \
                        or meta.post_fork_name not in available:
                    continue
                if ONLY_FORK is not None \
                        and meta.post_fork_name != ONLY_FORK:
                    continue
                spec = build_spec(meta.pre_fork_name, DEFAULT_TEST_PRESET)
                post_spec = build_spec(meta.post_fork_name,
                                       DEFAULT_TEST_PRESET)
                state = _get_genesis_state(
                    spec, default_balances, default_activation_threshold)
                old_active = bls.bls_active
                bls.bls_active = DEFAULT_BLS_ACTIVE
                _set_bls_backend()
                try:
                    _consume(fn(*args, state=state,
                                fork_epoch=meta.fork_epoch, spec=spec,
                                post_spec=post_spec, **kwargs))
                finally:
                    bls.bls_active = old_active
                ran = True
            if not ran:
                pytest.skip("no selected fork pair supports this test")
        if hasattr(entry, "__wrapped__"):
            del entry.__wrapped__
        return entry
    return deco


def with_all_phases_from(earliest):
    idx = ALL_PHASES.index(earliest)
    return with_phases(ALL_PHASES[idx:])


def with_presets(preset_names, reason=None):
    def deco(fn):
        @functools.wraps(fn)
        def entry(*args, **kwargs):
            if DEFAULT_TEST_PRESET not in preset_names:
                pytest.skip(reason or f"test requires presets {preset_names}")
            return fn(*args, **kwargs)
        return entry
    return deco
