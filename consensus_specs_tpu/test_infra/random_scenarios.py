"""Randomized multi-epoch scenario machine.

Reference: ``test/utils/randomized_block_tests.py`` (randomize_state :60,
random block/epoch transition compositions :239-430) — seeded scenarios
that mutate registry/participation state and then keep producing valid
blocks, catching cross-component interactions single-purpose tests miss.
"""
from random import Random

from consensus_specs_tpu.utils.ssz import hash_tree_root
from .attestations import get_valid_attestation
from .block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
    next_slots, next_epoch,
)
from .voluntary_exits import prepare_signed_exits


def randomize_state(spec, state, rng: Random, exit_fraction=0.1,
                    slash_fraction=0.1):
    """Scatter balances, exits and slashings across the registry
    (reference randomized_block_tests.py:60)."""
    for index in range(len(state.validators)):
        balance = int(state.balances[index])
        offset = rng.randint(-1, 1) * spec.EFFECTIVE_BALANCE_INCREMENT // 4
        state.balances[index] = max(0, balance + offset)
        roll = rng.random()
        if roll < exit_fraction:
            spec.initiate_validator_exit(state, index)
        elif roll < exit_fraction + slash_fraction:
            spec.slash_validator(state, index)
    randomize_participation(spec, state, rng)
    return state


def randomize_participation(spec, state, rng: Random):
    if spec.fork == "phase0":
        return  # pending attestations accumulate naturally
    for index in range(len(state.validators)):
        state.previous_epoch_participation[index] = \
            spec.ParticipationFlags(rng.randint(0, 7))
        state.current_epoch_participation[index] = \
            spec.ParticipationFlags(rng.randint(0, 7))
    if hasattr(state, "inactivity_scores"):
        for index in range(len(state.validators)):
            state.inactivity_scores[index] = rng.randint(0, 10)


def random_block(spec, state, rng: Random):
    """A valid block with a random mix of attestations and occasional
    slashings/exits, built against the current state."""
    block = build_empty_block_for_next_slot(spec, state)

    # attestations for a recent slot (if deep enough into the chain)
    if state.slot >= spec.MIN_ATTESTATION_INCLUSION_DELAY:
        slot_to_attest = state.slot - spec.MIN_ATTESTATION_INCLUSION_DELAY + 1
        if slot_to_attest >= spec.compute_start_slot_at_epoch(
                spec.compute_epoch_at_slot(state.slot)) \
                and slot_to_attest <= state.slot:
            committees = spec.get_committee_count_per_slot(
                state, spec.compute_epoch_at_slot(slot_to_attest))
            for index in range(committees):
                if rng.random() < 0.8:
                    att = get_valid_attestation(
                        spec, state, slot_to_attest, index=index,
                        filter_participant_set=lambda c: set(
                            i for i in c if rng.random() < 0.9),
                        signed=True)
                    if any(att.aggregation_bits):
                        block.body.attestations.append(att)

    # occasional voluntary exit of a never-touched validator
    if rng.random() < 0.15:
        current_epoch = spec.get_current_epoch(state)
        candidates = [
            i for i in spec.get_active_validator_indices(state, current_epoch)
            if state.validators[i].exit_epoch == spec.FAR_FUTURE_EPOCH
            and current_epoch >= state.validators[i].activation_epoch
            + spec.config.SHARD_COMMITTEE_PERIOD]
        if candidates:
            index = rng.choice(candidates)
            block.body.voluntary_exits = prepare_signed_exits(
                spec, state, [index])
    return block


def run_random_scenario(spec, state, seed: int, epochs=2,
                        blocks_per_epoch=4):
    """Seeded scenario: randomize, then alternate empty slots and random
    blocks for several epochs; every block must transition cleanly."""
    rng = Random(seed)
    # warm the chain past genesis so attestations/exits are possible
    next_epoch(spec, state)
    next_epoch(spec, state)
    randomize_state(spec, state, rng, exit_fraction=0.05, slash_fraction=0.05)

    signed_blocks = []
    for _ in range(epochs):
        for _ in range(blocks_per_epoch):
            if rng.random() < 0.3:
                next_slots(spec, state, rng.randint(1, 2))
            block = random_block(spec, state, rng)
            signed = state_transition_and_sign_block(spec, state, block)
            signed_blocks.append(signed)
        # let epoch processing churn through the randomized registry
        next_epoch(spec, state)
    # final sanity: the state merkleizes and keeps processing slots
    assert hash_tree_root(state) is not None
    next_slots(spec, state, 1)
    return signed_blocks
