"""Randomized multi-epoch scenario machine.

Reference: ``test/utils/randomized_block_tests.py`` (randomize_state :60,
random block/epoch transition compositions :239-430) — seeded scenarios
that mutate registry/participation state and then keep producing valid
blocks, catching cross-component interactions single-purpose tests miss.
"""
from random import Random

from consensus_specs_tpu.utils.ssz import hash_tree_root
from .attestations import get_valid_attestation
from .block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
    next_slots, next_epoch,
)
from .voluntary_exits import prepare_signed_exits


def randomize_state(spec, state, rng: Random, exit_fraction=0.1,
                    slash_fraction=0.1):
    """Scatter balances, exits and slashings across the registry
    (reference randomized_block_tests.py:60)."""
    for index in range(len(state.validators)):
        balance = int(state.balances[index])
        offset = rng.randint(-1, 1) * spec.EFFECTIVE_BALANCE_INCREMENT // 4
        state.balances[index] = max(0, balance + offset)
        roll = rng.random()
        if roll < exit_fraction:
            spec.initiate_validator_exit(state, index)
        elif roll < exit_fraction + slash_fraction:
            spec.slash_validator(state, index)
    randomize_participation(spec, state, rng)
    return state


def randomize_participation(spec, state, rng: Random):
    if spec.fork == "phase0":
        return  # pending attestations accumulate naturally
    for index in range(len(state.validators)):
        state.previous_epoch_participation[index] = \
            spec.ParticipationFlags(rng.randint(0, 7))
        state.current_epoch_participation[index] = \
            spec.ParticipationFlags(rng.randint(0, 7))
    if hasattr(state, "inactivity_scores"):
        for index in range(len(state.validators)):
            state.inactivity_scores[index] = rng.randint(0, 10)


def random_block(spec, state, rng: Random):
    """A valid block with a random mix of attestations and occasional
    slashings/exits, built against the current state."""
    block = build_empty_block_for_next_slot(spec, state)

    # attestations for a recent slot (if deep enough into the chain)
    if state.slot >= spec.MIN_ATTESTATION_INCLUSION_DELAY:
        slot_to_attest = state.slot - spec.MIN_ATTESTATION_INCLUSION_DELAY + 1
        if slot_to_attest >= spec.compute_start_slot_at_epoch(
                spec.compute_epoch_at_slot(state.slot)) \
                and slot_to_attest <= state.slot:
            committees = spec.get_committee_count_per_slot(
                state, spec.compute_epoch_at_slot(slot_to_attest))
            for index in range(committees):
                if rng.random() < 0.8:
                    att = get_valid_attestation(
                        spec, state, slot_to_attest, index=index,
                        filter_participant_set=lambda c: set(
                            i for i in c if rng.random() < 0.9),
                        signed=True)
                    if any(att.aggregation_bits):
                        block.body.attestations.append(att)

    # occasional voluntary exit of a never-touched validator
    if rng.random() < 0.15:
        current_epoch = spec.get_current_epoch(state)
        candidates = [
            i for i in spec.get_active_validator_indices(state, current_epoch)
            if state.validators[i].exit_epoch == spec.FAR_FUTURE_EPOCH
            and current_epoch >= state.validators[i].activation_epoch
            + spec.config.SHARD_COMMITTEE_PERIOD]
        if candidates:
            index = rng.choice(candidates)
            block.body.voluntary_exits = prepare_signed_exits(
                spec, state, [index])
    return block


def participation_blocks(spec, state, rng: Random, slots: int,
                         fraction: float):
    """``slots`` full-chain blocks whose attestations carry a thinned
    committee (each member kept with probability ``fraction``): the FFG
    throttle for driving real leak entry/exit through block processing
    instead of state surgery."""
    blocks = []
    for _ in range(slots):
        block = build_empty_block_for_next_slot(spec, state)
        slot_to_attest = block.slot - 1
        committees = spec.get_committee_count_per_slot(
            state, spec.compute_epoch_at_slot(slot_to_attest))
        for index in range(committees):
            att = get_valid_attestation(
                spec, state, slot_to_attest, index=index,
                filter_participant_set=lambda c: set(
                    i for i in c if rng.random() < fraction),
                signed=True)
            if any(att.aggregation_bits):
                block.body.attestations.append(att)
        blocks.append(state_transition_and_sign_block(spec, state, block))
    return blocks


def run_leak_recovery_scenario(spec, state, seed: int, participation=0.5,
                               recovery_epochs=4):
    """Drive the chain into a real inactivity leak and back out to
    finality, asserting each milestone.

    ``randomize_state`` scatters scores but never stalls finality, so
    nothing upstream of this helper ever executed the leak arm of epoch
    processing against organically-built chain state.  Here the leak is
    *entered* the way a live network enters it — sub-2/3 target weight
    over ``MIN_EPOCHS_TO_INACTIVITY_PENALTY`` epochs of otherwise-valid
    blocks — held long enough for the scores to bite (altair+), and
    then exited through full-participation blocks until finalization
    advances again.  Returns all signed blocks (vector-format friendly:
    pre/blocks/post)."""
    rng = Random(seed)
    # warmup past genesis (no attestations: finality stays at epoch 0)
    next_epoch(spec, state)
    next_epoch(spec, state)
    epoch_slots = int(spec.SLOTS_PER_EPOCH)
    blocks = []

    # entry: target weight pinned below 2/3 until the finality delay
    # crosses the leak threshold, plus margin for the scores to grow
    leak_epochs = int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 2
    blocks += participation_blocks(spec, state, rng,
                                   leak_epochs * epoch_slots, participation)
    assert spec.is_in_inactivity_leak(state), \
        "chain never entered the inactivity leak"
    scores_peak = None
    if hasattr(state, "inactivity_scores"):
        scores_peak = [int(s) for s in state.inactivity_scores]
        assert max(scores_peak) > 0, \
            "leak epochs never grew an inactivity score"
    finalized_in_leak = int(state.finalized_checkpoint.epoch)

    # recovery: full participation until finalization snaps forward
    blocks += participation_blocks(spec, state, rng,
                                   recovery_epochs * epoch_slots, 1.0)
    assert not spec.is_in_inactivity_leak(state), \
        "full participation never exited the leak"
    assert int(state.finalized_checkpoint.epoch) > finalized_in_leak, \
        "finality never recovered after the leak"
    if scores_peak is not None:
        scores_now = [int(s) for s in state.inactivity_scores]
        assert all(s >= 0 for s in scores_now)
        assert sum(scores_now) < sum(scores_peak), \
            "recovery epochs never walked the scores back down"
    return blocks


def run_random_scenario(spec, state, seed: int, epochs=2,
                        blocks_per_epoch=4):
    """Seeded scenario: randomize, then alternate empty slots and random
    blocks for several epochs; every block must transition cleanly."""
    rng = Random(seed)
    # warm the chain past genesis so attestations/exits are possible
    next_epoch(spec, state)
    next_epoch(spec, state)
    randomize_state(spec, state, rng, exit_fraction=0.05, slash_fraction=0.05)

    signed_blocks = []
    for _ in range(epochs):
        for _ in range(blocks_per_epoch):
            if rng.random() < 0.3:
                next_slots(spec, state, rng.randint(1, 2))
            block = random_block(spec, state, rng)
            signed = state_transition_and_sign_block(spec, state, block)
            signed_blocks.append(signed)
        # let epoch processing churn through the randomized registry
        next_epoch(spec, state)
    # final sanity: the state merkleizes and keeps processing slots
    assert hash_tree_root(state) is not None
    next_slots(spec, state, 1)
    return signed_blocks
