"""Genesis state builders for tests.

Reference: ``test/helpers/genesis.py`` (build_mock_validator:15,
create_genesis_state:74): states are built directly with mock validators —
no deposit proofs — which is what makes the harness fast.
"""
from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.utils.ssz import hash_tree_root, uint64
from .keys import pubkeys


def build_mock_validator(spec, i: int, balance: int):
    pk = pubkeys[i]
    # insecurely use pubkey as withdrawal key
    withdrawal_credentials = spec.BLS_WITHDRAWAL_PREFIX + hash(pk)[1:]
    validator = spec.Validator(
        pubkey=pk,
        withdrawal_credentials=withdrawal_credentials,
        activation_eligibility_epoch=spec.FAR_FUTURE_EPOCH,
        activation_epoch=spec.FAR_FUTURE_EPOCH,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        effective_balance=min(balance - balance % spec.EFFECTIVE_BALANCE_INCREMENT,
                              spec.MAX_EFFECTIVE_BALANCE),
    )
    # Research forks (custody_game) carry validator fields whose genesis
    # value is not the SSZ zero-default.
    finalize = getattr(spec, "finalize_mock_validator", None)
    if finalize is not None:
        finalize(validator, i)
    return validator


def _genesis_fork_versions(spec):
    """(previous, current) fork versions for a mock genesis at this fork."""
    fork = spec.fork
    versions = {
        "phase0": spec.config.GENESIS_FORK_VERSION,
        "altair": getattr(spec.config, "ALTAIR_FORK_VERSION", None),
        "bellatrix": getattr(spec.config, "BELLATRIX_FORK_VERSION", None),
        "capella": getattr(spec.config, "CAPELLA_FORK_VERSION", None),
        "deneb": getattr(spec.config, "DENEB_FORK_VERSION", None),
        "eip6110": getattr(spec.config, "EIP6110_FORK_VERSION", None),
        "eip7002": getattr(spec.config, "EIP7002_FORK_VERSION", None),
        "eip7594": getattr(spec.config, "EIP7594_FORK_VERSION", None),
        "whisk": getattr(spec.config, "WHISK_FORK_VERSION", None),
        "sharding": getattr(spec.config, "SHARDING_FORK_VERSION", None),
        "custody_game": getattr(spec.config, "CUSTODY_GAME_FORK_VERSION", None),
        "eip6914": getattr(spec.config, "EIP6914_FORK_VERSION", None),
    }
    order = ["phase0", "altair", "bellatrix", "capella", "deneb",
             "eip6110", "eip7002", "eip7594", "whisk",
             "sharding", "custody_game", "eip6914"]
    # feature forks branch off their DAG parent, not list order
    parents = {"eip7002": "capella", "eip7594": "deneb", "whisk": "capella",
               "sharding": "phase0", "custody_game": "sharding",
               "eip6914": "capella"}
    cur = versions[fork]
    prev_name = parents.get(fork, order[max(0, order.index(fork) - 1)])
    prev = versions[prev_name]
    return prev, cur


def create_genesis_state(spec, validator_balances, activation_threshold):
    deposit_root = b"\x42" * 32
    eth1_block_hash = b"\xda" * 32
    previous_version, current_version = _genesis_fork_versions(spec)
    state = spec.BeaconState(
        genesis_time=0,
        eth1_deposit_index=len(validator_balances),
        eth1_data=spec.Eth1Data(
            deposit_root=deposit_root,
            deposit_count=len(validator_balances),
            block_hash=eth1_block_hash,
        ),
        fork=spec.Fork(
            previous_version=previous_version,
            current_version=current_version,
            epoch=spec.GENESIS_EPOCH,
        ),
        latest_block_header=spec.BeaconBlockHeader(
            body_root=hash_tree_root(spec.BeaconBlockBody())),
        randao_mixes=[eth1_block_hash] * spec.EPOCHS_PER_HISTORICAL_VECTOR,
    )
    # "hack" in the initial validators: much faster than processing deposits
    for i, balance in enumerate(validator_balances):
        state.validators.append(build_mock_validator(spec, i, balance))
        state.balances.append(uint64(balance))
    # process genesis activations through the live views (assignment copies)
    for validator in state.validators:
        if validator.effective_balance >= activation_threshold:
            validator.activation_eligibility_epoch = spec.GENESIS_EPOCH
            validator.activation_epoch = spec.GENESIS_EPOCH
    state.genesis_validators_root = hash_tree_root(state.validators)
    # fork-specific genesis fields (participation, sync committees, ...)
    post_hook = getattr(spec, "post_mock_genesis", None)
    if post_hook is not None:
        post_hook(state)
    return state
