"""Proposer/attester slashing builders.

Reference: ``test/helpers/proposer_slashings.py`` + ``attester_slashings.py``.
"""
from .keys import privkeys
from .signing import sign
from .attestations import get_valid_attestation, sign_attestation


def sign_block_header(spec, state, header, privkey):
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER,
                             spec.compute_epoch_at_slot(header.slot))
    signing_root = spec.compute_signing_root(header, domain)
    signature = sign(privkey, signing_root)
    return spec.SignedBeaconBlockHeader(message=header, signature=signature)


def get_valid_proposer_slashing(spec, state, signed_1=True, signed_2=True,
                                proposer_index=None, slot=None):
    if proposer_index is None:
        proposer_index = spec.get_beacon_proposer_index(state)
    if slot is None:
        slot = state.slot
    privkey = privkeys[proposer_index]

    header_1 = spec.BeaconBlockHeader(
        slot=slot,
        proposer_index=proposer_index,
        parent_root=b"\x33" * 32,
        state_root=b"\x44" * 32,
        body_root=b"\x55" * 32,
    )
    header_2 = header_1.copy()
    header_2.parent_root = b"\x99" * 32

    if signed_1:
        signed_header_1 = sign_block_header(spec, state, header_1, privkey)
    else:
        signed_header_1 = spec.SignedBeaconBlockHeader(message=header_1)
    if signed_2:
        signed_header_2 = sign_block_header(spec, state, header_2, privkey)
    else:
        signed_header_2 = spec.SignedBeaconBlockHeader(message=header_2)

    return spec.ProposerSlashing(
        signed_header_1=signed_header_1,
        signed_header_2=signed_header_2,
    )


def get_valid_attester_slashing(spec, state, slot=None, signed_1=False, signed_2=False):
    attestation_1 = get_valid_attestation(spec, state, slot=slot, signed=signed_1)
    attestation_2 = attestation_1.copy()
    attestation_2.data.target.root = b"\x01" * 32
    if signed_2:
        sign_attestation(spec, state, attestation_2)
    return spec.AttesterSlashing(
        attestation_1=spec.get_indexed_attestation(state, attestation_1),
        attestation_2=spec.get_indexed_attestation(state, attestation_2),
    )


def get_indexed_attestation_participants(spec, indexed_att):
    return list(indexed_att.attesting_indices)


def run_proposer_slashing_processing(spec, state, proposer_slashing, valid=True):
    yield "pre", state
    yield "proposer_slashing", proposer_slashing
    if not valid:
        try:
            spec.process_proposer_slashing(state, proposer_slashing)
        except (AssertionError, IndexError, ValueError):
            yield "post", None
            return
        raise AssertionError("proposer slashing should have failed")

    proposer_index = proposer_slashing.signed_header_1.message.proposer_index
    pre_proposer_balance = state.balances[proposer_index]
    spec.process_proposer_slashing(state, proposer_slashing)
    yield "post", state
    assert state.validators[proposer_index].slashed
    assert state.balances[proposer_index] < pre_proposer_balance


def run_attester_slashing_processing(spec, state, attester_slashing, valid=True):
    yield "pre", state
    yield "attester_slashing", attester_slashing
    if not valid:
        try:
            spec.process_attester_slashing(state, attester_slashing)
        except (AssertionError, IndexError, ValueError):
            yield "post", None
            return
        raise AssertionError("attester slashing should have failed")
    slashed_indices = set(attester_slashing.attestation_1.attesting_indices) \
        .intersection(attester_slashing.attestation_2.attesting_indices)
    spec.process_attester_slashing(state, attester_slashing)
    for index in slashed_indices:
        assert state.validators[index].slashed
    yield "post", state
