"""Fork-choice test machinery: event-sourced store simulation.

Mirrors the reference's ``test/helpers/fork_choice.py`` behavior: drive a
``Store`` through on_tick / on_block / on_attestation steps, emitting a
``steps`` event log (the same event-log shape the cross-client
``fork_choice`` vector format uses, ``tests/formats/fork_choice/README.md``)
and asserting store checks along the way.
"""
from consensus_specs_tpu.utils.ssz import hash_tree_root
from consensus_specs_tpu.test_infra.context import (
    expect_assertion_error, emit_part)


def get_genesis_forkchoice_store_and_block(spec, genesis_state):
    assert genesis_state.slot == spec.GENESIS_SLOT
    genesis_block = spec.BeaconBlock(state_root=hash_tree_root(genesis_state))
    emit_part("anchor_state", genesis_state)
    emit_part("anchor_block", genesis_block)
    return spec.get_forkchoice_store(genesis_state, genesis_block), genesis_block


def get_genesis_forkchoice_store(spec, genesis_state):
    return get_genesis_forkchoice_store_and_block(spec, genesis_state)[0]


def on_tick_and_append_step(spec, store, time, test_steps):
    assert time >= store.time
    spec.on_tick(store, time)
    test_steps.append({"tick": int(time)})
    output_store_checks(spec, store, test_steps)


def tick_and_add_block(spec, store, signed_block, test_steps, valid=True,
                       block_not_ticked=False):
    pre_state = store.block_states[bytes(signed_block.message.parent_root)]
    if not block_not_ticked:
        block_time = (pre_state.genesis_time
                      + signed_block.message.slot * spec.config.SECONDS_PER_SLOT)
        if store.time < block_time:
            on_tick_and_append_step(spec, store, block_time, test_steps)
    return add_block(spec, store, signed_block, test_steps, valid=valid)


def add_block(spec, store, signed_block, test_steps, valid=True):
    """Run on_block and (on success) re-check the stored block."""
    block_name = "block_0x" + hash_tree_root(signed_block.message).hex()
    emit_part(block_name, signed_block)
    if not valid:
        expect_assertion_error(lambda: spec.on_block(store, signed_block))
        test_steps.append({"block": block_name, "valid": False})
        return None
    spec.on_block(store, signed_block)
    # an on_block step implies receiving the block's attestations + slashings
    for attestation in signed_block.message.body.attestations:
        spec.on_attestation(store, attestation, is_from_block=True)
    for attester_slashing in signed_block.message.body.attester_slashings:
        spec.on_attester_slashing(store, attester_slashing)
    block_root = hash_tree_root(signed_block.message)
    assert hash_tree_root(store.blocks[block_root]) == block_root
    test_steps.append({"block": block_name})
    output_store_checks(spec, store, test_steps)
    return store.block_states[block_root]


def add_attestation(spec, store, attestation, test_steps, is_from_block=False,
                    valid=True):
    att_name = "attestation_0x" + hash_tree_root(attestation).hex()
    emit_part(att_name, attestation)
    if not valid:
        expect_assertion_error(
            lambda: spec.on_attestation(store, attestation,
                                        is_from_block=is_from_block))
        test_steps.append({"attestation": att_name, "valid": False})
        return
    spec.on_attestation(store, attestation, is_from_block=is_from_block)
    test_steps.append({"attestation": att_name})
    output_store_checks(spec, store, test_steps)


def add_attestations(spec, store, attestations, test_steps, is_from_block=False):
    for a in attestations:
        add_attestation(spec, store, a, test_steps, is_from_block=is_from_block)


def add_attester_slashing(spec, store, slashing, test_steps, valid=True):
    slashing_name = "attester_slashing_0x" + hash_tree_root(slashing).hex()
    emit_part(slashing_name, slashing)
    if not valid:
        expect_assertion_error(lambda: spec.on_attester_slashing(store, slashing))
        test_steps.append({"attester_slashing": slashing_name,
                           "valid": False})
        return
    spec.on_attester_slashing(store, slashing)
    test_steps.append({"attester_slashing": slashing_name})


def get_formatted_head_output(spec, store):
    head = spec.get_head(store)
    return {"slot": int(store.blocks[bytes(head)].slot),
            "root": "0x" + bytes(head).hex()}


def output_store_checks(spec, store, test_steps):
    test_steps.append({"checks": {
        "time": int(store.time),
        "head": get_formatted_head_output(spec, store),
        "justified_checkpoint": {
            "epoch": int(store.justified_checkpoint.epoch),
            "root": "0x" + bytes(store.justified_checkpoint.root).hex(),
        },
        "finalized_checkpoint": {
            "epoch": int(store.finalized_checkpoint.epoch),
            "root": "0x" + bytes(store.finalized_checkpoint.root).hex(),
        },
        "proposer_boost_root": "0x" + bytes(store.proposer_boost_root).hex(),
    }})


def apply_next_epoch_with_attestations(spec, state, store, fill_cur_epoch,
                                       fill_prev_epoch, test_steps):
    """Advance one epoch via attested blocks, feeding each to the store."""
    from consensus_specs_tpu.test_infra.attestations import (
        next_epoch_with_attestations)
    _, new_signed_blocks, post_state = next_epoch_with_attestations(
        spec, state, fill_cur_epoch, fill_prev_epoch)
    last_signed_block = None
    for signed_block in new_signed_blocks:
        block_root = hash_tree_root(signed_block.message)
        tick_and_add_block(spec, store, signed_block, test_steps)
        assert bytes(store.blocks[block_root].parent_root) == \
            bytes(signed_block.message.parent_root)
        last_signed_block = signed_block
    assert hash_tree_root(store.block_states[hash_tree_root(
        last_signed_block.message)]) == hash_tree_root(post_state)
    return post_state, store, last_signed_block
