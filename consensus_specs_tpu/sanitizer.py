"""``CS_TPU_SANITIZER``: the runtime effect sanitizer — the dynamic
twin of the speclint E12xx static passes (docs/static-analysis.md).

The E12xx family proves the effect contracts *statically*: no direct
SSZ write under a pending deferred column (E1201), no fork/checkpoint
inside an open commit scope (E1202/E1203), manifest-written-last
(E1221), journal-record-before-STEP-marker (E1222), fsync-before-
rename (E1223).  The static side is deliberately under-approximate
(linearized control flow, module-local closures), so every contract
also gets a runtime enforcement arm: with ``CS_TPU_SANITIZER=1`` the
instrumented layers (``state/arrays.py``, ``recovery/``) feed a shadow
effect log here, and a violated contract raises
:class:`EffectViolation` NAMING the E12xx rule — the sim sweep's
sanitizer leg and the CI sanitizer job then catch dynamically anything
the linearization cannot see.

Design points:

* **Disabled cost is one mode check per hook**, and the hooks sit on
  per-epoch / per-checkpoint boundaries, not per-element hot loops —
  ``benchmarks/bench_sanitizer.py`` asserts <2% of the 32-slot replay.
* **E1201** upgrades the store's existing fail-loud ``RuntimeError``
  (a direct SSZ write detected under a pending deferred column) to an
  :class:`EffectViolation` naming the rule; the scope ledger
  additionally records which columns are pending so the message can
  say what would have been clobbered.
* **E1202** is *counted, not raised*: ``StateArrays.fork`` commits
  pending writes into the child by design (PR 12's regression pins
  it), so a fork inside an open scope is a legal early commit — the
  ``sanitizer.violations{rule=E1202}`` series surfaces the silent
  contract degradation without breaking the legal path.
* **E1203** books the rule when ``CheckpointRefused`` fires (the
  refusal itself predates the sanitizer and stays on).
* **E1221** keeps a per-generation ledger of blob writes: a manifest
  recording a blob this process never wrote, or a blob landing after
  its generation's manifest, raises.
* **E1222/E1223** arm the journal/rename call sites: the writers
  declare their ordering facts (``fsynced=``) and a regressed caller
  raises.

All state is thread-local (the harness legs run scenarios in one
thread each); ``arm()``/``disarm()`` force the mode for tests, mirroring
the engine-switch convention.
"""
import threading

from consensus_specs_tpu.obs import registry as obs_registry
from consensus_specs_tpu.utils import env_flags

RULES = ("E1201", "E1202", "E1203", "E1221", "E1222", "E1223")

# pre-bound series (speclint O5xx hot-path rule)
_C_CHECKS = {r: obs_registry.counter("sanitizer.checks").labels(rule=r)
             for r in RULES}
_C_VIOLATIONS = {
    r: obs_registry.counter("sanitizer.violations").labels(rule=r)
    for r in RULES}


class EffectViolation(RuntimeError):
    """A runtime effect-contract violation; ``rule`` names the E12xx
    speclint rule whose static proof is the twin of this check."""

    def __init__(self, rule: str, message: str):
        super().__init__(f"{rule}: {message} [CS_TPU_SANITIZER]")
        self.rule = rule


# ---------------------------------------------------------------------------
# Mode (mirrors the engine-switch convention; default OFF — the
# sanitizer is an opt-in diagnostic arm, not an engine)
# ---------------------------------------------------------------------------

_mode = "auto"


def arm() -> None:
    global _mode
    _mode = "on"


def disarm() -> None:
    global _mode
    _mode = "off"


def use_auto() -> None:
    global _mode
    _mode = "auto"


def enabled() -> bool:
    if _mode == "on":
        return True
    if _mode == "off":
        return False
    return env_flags.knob("CS_TPU_SANITIZER") == "1"


# ---------------------------------------------------------------------------
# Shadow effect log (thread-local)
# ---------------------------------------------------------------------------

_state = threading.local()


def _scopes() -> dict:
    got = getattr(_state, "scopes", None)
    if got is None:
        got = _state.scopes = {}
    return got


def _ckpt() -> dict:
    got = getattr(_state, "ckpt", None)
    if got is None:
        got = _state.ckpt = {}
    return got


def reset() -> None:
    """Drop the shadow log (test/harness lifecycle)."""
    _state.scopes = {}
    _state.ckpt = {}


def _violation(rule: str, message: str) -> EffectViolation:
    _C_VIOLATIONS[rule].add()
    return EffectViolation(rule, message)


def effect_error(rule: str, message: str) -> RuntimeError:
    """The exception for a violated effect contract at an instrumented
    site: an :class:`EffectViolation` naming the rule when the
    sanitizer is armed, the layer's plain ``RuntimeError`` otherwise
    (existing callers keep their exception surface)."""
    if enabled():
        return _violation(rule, message)
    return RuntimeError(message)


# -- commit-scope ledger (state/arrays.py) ----------------------------------

def scope_opened(store) -> None:
    if not enabled():
        return
    _C_CHECKS["E1201"].add()
    _scopes()[id(store)] = set()


def deferred_write(store, name: str) -> None:
    if not enabled():
        return
    pending = _scopes().get(id(store))
    if pending is not None:
        pending.add(name)


def pending_columns(store):
    """The scope ledger's view of ``store``'s deferred columns (empty
    when untracked) — used to enrich E1201 messages."""
    return sorted(_scopes().get(id(store), ()))


def scope_closed(store) -> None:
    # pop UNCONDITIONALLY: a scope opened while armed must not leave a
    # ledger entry behind when the sanitizer is disarmed before exit —
    # CPython reuses object ids, so a leaked entry could book a false
    # E1202 against an unrelated later store (the id()-staleness class
    # speclint D1004 polices)
    _scopes().pop(id(store), None)


def fork_event(store, pending: bool) -> None:
    """A store fork/copy observed.  Inside an open scope with pending
    deferred writes this is E1202 — counted, not raised (module
    docstring): the fork legally commits-into-child, but the
    one-commit-per-epoch contract silently degraded."""
    if not enabled():
        return
    _C_CHECKS["E1202"].add()
    if pending and id(store) in _scopes():
        _C_VIOLATIONS["E1202"].add()


def checkpoint_refused() -> None:
    """``CheckpointRefused`` fired: book the E1203 twin."""
    if not enabled():
        return
    _C_VIOLATIONS["E1203"].add()


def checkpoint_scope_check() -> None:
    if not enabled():
        return
    _C_CHECKS["E1203"].add()


# -- checkpoint write-ordering ledger (recovery/checkpoint.py) --------------

def blob_written(owner: str, gen: int, name: str) -> None:
    """``owner`` scopes the ledger to one checkpoint directory — two
    replays reusing generation numbers must not share entries."""
    if not enabled():
        return
    _C_CHECKS["E1221"].add()
    rec = _ckpt().setdefault((owner, gen),
                             {"blobs": set(), "manifest": False})
    if rec["manifest"]:
        raise _violation(
            "E1221", f"checkpoint blob {name!r} written AFTER "
            f"generation {gen}'s manifest — the manifest is the commit "
            "point and must land last")
    rec["blobs"].add(name)


def manifest_written(owner: str, gen: int, blob_names) -> None:
    if not enabled():
        return
    _C_CHECKS["E1221"].add()
    rec = _ckpt().setdefault((owner, gen),
                             {"blobs": set(), "manifest": False})
    missing = set(blob_names) - rec["blobs"]
    if missing:
        raise _violation(
            "E1221", f"generation {gen}'s manifest records blob(s) "
            f"{sorted(missing)} this process never wrote — a manifest "
            "must only ever describe blobs already durable")
    rec["manifest"] = True


def generation_discarded(owner: str, gen: int) -> None:
    if not enabled():
        return
    _ckpt().pop((owner, gen), None)


# -- journal ordering (recovery/journal.py) ---------------------------------

def record_appended(journal) -> None:
    if not enabled():
        return
    _C_CHECKS["E1222"].add()


def step_committed(journal, fsynced: bool) -> None:
    if not enabled():
        return
    _C_CHECKS["E1222"].add()
    if not fsynced:
        raise _violation(
            "E1222", "STEP commit marker written without an fsync — "
            "the durability boundary is the fsynced marker; a crash "
            "could lose a committed step")


# -- rename ordering (recovery/atomic.py) -----------------------------------

def rename_event(path: str, fsynced: bool, exempt: bool = False) -> None:
    """A final-path rename.  ``exempt`` marks the sanctioned
    no-fsync variant (``atomic_replace_bytes``: higher-level fencing)."""
    if not enabled():
        return
    _C_CHECKS["E1223"].add()
    if not fsynced and not exempt:
        raise _violation(
            "E1223", f"final-path rename of {path!r} without a "
            "preceding fsync — the name can become durable before the "
            "data")


def snapshot() -> dict:
    """Check/violation counts per rule (test/report convenience)."""
    checks = obs_registry.counter("sanitizer.checks")
    violations = obs_registry.counter("sanitizer.violations")
    return {r: {"checks": checks.value(rule=r),
                "violations": violations.value(rule=r)} for r in RULES}
