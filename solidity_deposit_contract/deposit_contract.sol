// SPDX-License-Identifier: CC0-1.0
pragma solidity ^0.8.19;

// Beacon-chain deposit contract (capability parity with the artifact the
// reference vendors; specified by specs/phase0/deposit-contract.md).
// Maintains an incremental Merkle accumulator over SSZ DepositData roots
// so get_deposit_root() always equals the SSZ hash_tree_root of the
// deposit list (with length mix-in) that the beacon chain verifies in
// process_deposit.

interface ERC165 {
    function supportsInterface(bytes4 interfaceId)
        external pure returns (bool);
}

interface IDepositContract {
    event DepositEvent(
        bytes pubkey,
        bytes withdrawal_credentials,
        bytes amount,
        bytes signature,
        bytes index
    );

    function deposit(
        bytes calldata pubkey,
        bytes calldata withdrawal_credentials,
        bytes calldata signature,
        bytes32 deposit_data_root
    ) external payable;

    function get_deposit_root() external view returns (bytes32);

    function get_deposit_count() external view returns (bytes memory);
}

contract DepositContract is IDepositContract, ERC165 {
    uint256 private constant DEPOSIT_CONTRACT_TREE_DEPTH = 32;
    // Accumulator cannot overflow before the sun burns out, but cap like
    // the consensus spec's list limit anyway.
    uint256 private constant MAX_DEPOSIT_COUNT =
        2 ** DEPOSIT_CONTRACT_TREE_DEPTH - 1;

    bytes32[DEPOSIT_CONTRACT_TREE_DEPTH] private branch;
    uint256 private deposit_count;
    bytes32[DEPOSIT_CONTRACT_TREE_DEPTH] private zero_hashes;

    constructor() {
        for (uint256 height = 0;
             height < DEPOSIT_CONTRACT_TREE_DEPTH - 1;
             height++)
            zero_hashes[height + 1] = sha256(
                abi.encodePacked(zero_hashes[height], zero_hashes[height]));
    }

    function get_deposit_root() external view override returns (bytes32) {
        bytes32 node;
        uint256 size = deposit_count;
        for (uint256 height = 0;
             height < DEPOSIT_CONTRACT_TREE_DEPTH;
             height++) {
            if ((size & 1) == 1)
                node = sha256(abi.encodePacked(branch[height], node));
            else
                node = sha256(abi.encodePacked(node, zero_hashes[height]));
            size /= 2;
        }
        return sha256(abi.encodePacked(
            node, to_little_endian_64(uint64(deposit_count)),
            bytes24(0)));
    }

    function get_deposit_count() external view override
            returns (bytes memory) {
        return to_little_endian_64(uint64(deposit_count));
    }

    function deposit(
        bytes calldata pubkey,
        bytes calldata withdrawal_credentials,
        bytes calldata signature,
        bytes32 deposit_data_root
    ) external payable override {
        require(pubkey.length == 48, "DepositContract: bad pubkey length");
        require(withdrawal_credentials.length == 32,
                "DepositContract: bad credentials length");
        require(signature.length == 96,
                "DepositContract: bad signature length");

        require(msg.value >= 1 ether,
                "DepositContract: deposit value too low");
        require(msg.value % 1 gwei == 0,
                "DepositContract: deposit not gwei multiple");
        uint256 deposit_amount = msg.value / 1 gwei;
        require(deposit_amount <= type(uint64).max,
                "DepositContract: deposit value too high");

        emit DepositEvent(
            pubkey,
            withdrawal_credentials,
            to_little_endian_64(uint64(deposit_amount)),
            signature,
            to_little_endian_64(uint64(deposit_count)));

        // SSZ hash_tree_root(DepositData) recomputed on-chain so the
        // supplied root cannot lie about the deposit's content.
        bytes32 pubkey_root = sha256(abi.encodePacked(pubkey, bytes16(0)));
        bytes32 signature_root = sha256(abi.encodePacked(
            sha256(abi.encodePacked(signature[:64])),
            sha256(abi.encodePacked(signature[64:], bytes32(0)))));
        bytes32 node = sha256(abi.encodePacked(
            sha256(abi.encodePacked(pubkey_root, withdrawal_credentials)),
            sha256(abi.encodePacked(
                to_little_endian_64(uint64(deposit_amount)), bytes24(0),
                signature_root))));
        require(node == deposit_data_root,
                "DepositContract: reconstructed root mismatch");

        require(deposit_count < MAX_DEPOSIT_COUNT,
                "DepositContract: merkle tree full");
        deposit_count += 1;
        uint256 size = deposit_count;
        for (uint256 height = 0;
             height < DEPOSIT_CONTRACT_TREE_DEPTH;
             height++) {
            if ((size & 1) == 1) {
                branch[height] = node;
                return;
            }
            node = sha256(abi.encodePacked(branch[height], node));
            size /= 2;
        }
        assert(false);
    }

    function supportsInterface(bytes4 interfaceId)
            external pure override returns (bool) {
        return interfaceId == type(ERC165).interfaceId
            || interfaceId == type(IDepositContract).interfaceId;
    }

    function to_little_endian_64(uint64 value) internal pure
            returns (bytes memory ret) {
        ret = new bytes(8);
        bytes8 b = bytes8(value);
        for (uint256 i = 0; i < 8; i++) {
            ret[i] = b[7 - i];
        }
    }
}
