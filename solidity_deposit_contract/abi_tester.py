"""ABI-level harness over the deposit-contract model.

Plays the role of the reference's ``web3_tester``: drives deposits
through the COMMITTED ABI artifact (argument validation, value checks,
event log emission) instead of poking the python model directly, so the
ABI JSON is load-bearing in tests rather than decorative.
"""
import json
import os

from solidity_deposit_contract.contract_model import DepositContractModel

_ABI_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "deposit_contract.json")

GWEI = 10**9
MIN_DEPOSIT_WEI = 10**9 * GWEI  # 1 ether, contract's minimum


def load_abi():
    with open(_ABI_PATH) as f:
        return json.load(f)["abi"]


class AbiError(Exception):
    """Argument/value rejected at the ABI or require() layer."""


class DepositContractTester:
    """In-process 'deployment': the ABI front-end over the model."""

    def __init__(self):
        self._model = DepositContractModel()
        self._abi = {e["name"]: e for e in load_abi()
                     if e["type"] == "function"}
        self.logs = []  # DepositEvent dicts, in emission order

    # -- ABI argument validation ------------------------------------

    @staticmethod
    def _check_bytes(name, value, exact=None):
        if not isinstance(value, (bytes, bytearray)):
            raise AbiError(f"{name}: bytes required")
        if exact is not None and len(value) != exact:
            raise AbiError(f"{name}: length {len(value)} != {exact}")

    # -- calls -------------------------------------------------------

    def deposit(self, pubkey, withdrawal_credentials, signature,
                deposit_data_root, value_wei):
        """`deposit(bytes,bytes,bytes,bytes32)` payable."""
        assert "deposit" in self._abi
        # dynamic-bytes args: the CONTRACT enforces the lengths
        self._check_bytes("pubkey", pubkey)
        self._check_bytes("withdrawal_credentials", withdrawal_credentials)
        self._check_bytes("signature", signature)
        self._check_bytes("deposit_data_root", deposit_data_root, exact=32)
        if len(pubkey) != 48:
            raise AbiError("DepositContract: invalid pubkey length")
        if len(withdrawal_credentials) != 32:
            raise AbiError(
                "DepositContract: invalid withdrawal_credentials length")
        if len(signature) != 96:
            raise AbiError("DepositContract: invalid signature length")
        if value_wei < MIN_DEPOSIT_WEI:
            raise AbiError("DepositContract: deposit value too low")
        if value_wei % GWEI != 0:
            raise AbiError(
                "DepositContract: deposit value not multiple of gwei")
        amount_gwei = value_wei // GWEI
        if amount_gwei > 2**64 - 1:
            raise AbiError("DepositContract: deposit value too high")
        computed = self._model.deposit_data_root(
            bytes(pubkey), bytes(withdrawal_credentials), amount_gwei,
            bytes(signature))
        if computed != bytes(deposit_data_root):
            raise AbiError(
                "DepositContract: reconstructed DepositData does not match "
                "supplied deposit_data_root")
        index = self._model.deposit_count
        self._model.deposit(bytes(pubkey), bytes(withdrawal_credentials),
                            amount_gwei, bytes(signature))
        self.logs.append({
            "event": "DepositEvent",
            "pubkey": bytes(pubkey),
            "withdrawal_credentials": bytes(withdrawal_credentials),
            "amount": amount_gwei.to_bytes(8, "little"),
            "signature": bytes(signature),
            "index": index.to_bytes(8, "little"),
        })

    def get_deposit_root(self) -> bytes:
        return self._model.get_deposit_root()

    def get_deposit_count(self) -> bytes:
        return self._model.get_deposit_count()

    def supportsInterface(self, interface_id: bytes) -> bool:
        self._check_bytes("interfaceId", interface_id, exact=4)
        # ERC165 itself + IDepositContract's computed id
        erc165 = bytes.fromhex("01ffc9a7")
        ideposit = _interface_id()
        return interface_id in (erc165, ideposit)


def _selector(sig: str) -> bytes:
    """4-byte function selector = keccak256(signature)[:4]."""
    from consensus_specs_tpu.utils.keccak import keccak256
    return keccak256(sig.encode())[:4]


def _interface_id() -> bytes:
    """ERC165 interface id = XOR of the interface's selectors."""
    sels = [
        _selector("deposit(bytes,bytes,bytes,bytes32)"),
        _selector("get_deposit_root()"),
        _selector("get_deposit_count()"),
    ]
    out = bytes(4)
    for s in sels:
        out = bytes(a ^ b for a, b in zip(out, s))
    return out
