"""Python model of the deposit contract's incremental Merkle accumulator.

Mirrors ``deposit_contract.sol`` statement for statement so the contract
logic is testable without an EVM (the reference tests its vendored
contract through a web3 tester the same way — ``Makefile:164-181``).
The model's root must equal the SSZ ``hash_tree_root`` of the
``List[DepositData, 2**32]`` the beacon chain verifies against
(``tests/test_deposit_contract.py``).
"""
from hashlib import sha256

DEPOSIT_CONTRACT_TREE_DEPTH = 32


def _sha(data: bytes) -> bytes:
    return sha256(data).digest()


class DepositContractModel:
    def __init__(self):
        self.branch = [b"\x00" * 32] * DEPOSIT_CONTRACT_TREE_DEPTH
        self.deposit_count = 0
        self.zero_hashes = [b"\x00" * 32] * DEPOSIT_CONTRACT_TREE_DEPTH
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH - 1):
            self.zero_hashes[height + 1] = _sha(
                self.zero_hashes[height] + self.zero_hashes[height])

    @staticmethod
    def deposit_data_root(pubkey: bytes, withdrawal_credentials: bytes,
                          amount_gwei: int, signature: bytes) -> bytes:
        """On-chain SSZ hash_tree_root(DepositData) reconstruction."""
        pubkey_root = _sha(pubkey + b"\x00" * 16)
        signature_root = _sha(
            _sha(signature[:64]) + _sha(signature[64:] + b"\x00" * 32))
        return _sha(
            _sha(pubkey_root + withdrawal_credentials)
            + _sha(amount_gwei.to_bytes(8, "little") + b"\x00" * 24
                   + signature_root))

    def deposit(self, pubkey: bytes, withdrawal_credentials: bytes,
                amount_gwei: int, signature: bytes) -> None:
        assert len(pubkey) == 48
        assert len(withdrawal_credentials) == 32
        assert len(signature) == 96
        assert amount_gwei >= 10**9  # 1 ether minimum
        node = self.deposit_data_root(pubkey, withdrawal_credentials,
                                      amount_gwei, signature)
        assert self.deposit_count < 2 ** DEPOSIT_CONTRACT_TREE_DEPTH - 1
        self.deposit_count += 1
        size = self.deposit_count
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if size & 1:
                self.branch[height] = node
                return
            node = _sha(self.branch[height] + node)
            size //= 2
        raise AssertionError("unreachable")

    def get_deposit_root(self) -> bytes:
        node = b"\x00" * 32
        size = self.deposit_count
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if size & 1:
                node = _sha(self.branch[height] + node)
            else:
                node = _sha(node + self.zero_hashes[height])
            size //= 2
        return _sha(node + self.deposit_count.to_bytes(8, "little")
                    + b"\x00" * 24)

    def get_deposit_count(self) -> bytes:
        return self.deposit_count.to_bytes(8, "little")
