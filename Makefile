# Build/test/generate entrypoints (role of the reference's Makefile:108-209).

PYTHON ?= python
OUTPUT_DIR ?= ../consensus-spec-tests
GENERATORS = operations sanity finality rewards random forks epoch_processing \
             genesis ssz_static bls shuffling light_client kzg_4844 \
             kzg_7594 fork_choice merkle_proof ssz_generic sync transition

.PHONY: test citest test-crypto bench bench-all bench-merkle-smoke \
        bench-forkchoice-smoke bench-obs-smoke bench-block-smoke \
        bench-state-smoke bench-supervisor-smoke bench-das-smoke \
        bench-mesh-smoke bench-recovery-smoke bench-sanitizer-smoke \
        bench-serving-smoke bench-corpus-smoke bench-telemetry-smoke \
        sim-smoke sim-heavy \
        obs-report dryrun warm native lint lint-changed lint-verdicts \
        speclint-baseline \
        generate_tests $(addprefix gen_,$(GENERATORS)) clean-vectors pyspec \
        corpus corpus-check

# fast local suite: signature checks off except @always_bls
# (reference `make test`, Makefile:118-120)
test:
	$(PYTHON) -m pytest tests/ -q

# CI tier: every signature verified through the fastest available
# backend — native C when gcc can build it, else jax, else the py
# oracle.  Hosts without gcc degrade (loudly) to a slower backend
# instead of erroring out of the whole tier; when gcc IS present a
# broken native build fails the tier rather than silently falling back
# (a stale .so from an earlier build would otherwise mask the breakage)
# (reference `make citest` with --bls-type=fastest, Makefile:129-137)
citest:
	@if command -v gcc >/dev/null 2>&1; then $(MAKE) native; \
	else echo "citest: gcc not found — skipping native build," \
	          "degrading to the jax/python backends" >&2; fi
	$(PYTHON) benchmarks/bench_merkle_smoke.py
	$(PYTHON) benchmarks/bench_fork_choice.py --smoke
	$(PYTHON) benchmarks/bench_block_verify.py --smoke
	$(PYTHON) benchmarks/bench_state_arrays.py --smoke
	$(PYTHON) benchmarks/bench_supervisor.py
	$(PYTHON) benchmarks/bench_das.py
	$(PYTHON) benchmarks/bench_mesh.py
	$(PYTHON) benchmarks/bench_recovery.py
	$(PYTHON) benchmarks/bench_sanitizer.py
	$(PYTHON) benchmarks/bench_serving.py --smoke
	$(PYTHON) benchmarks/bench_corpus.py --smoke
	$(MAKE) sim-smoke
	$(PYTHON) -m pytest tests/ -q --enable-bls --bls-type fastest

# static checks: syntax gate + the speclint whole-program analyzer
# (style, uint64-hazard + U9xx range proving, jax-tracing,
# ladder-drift, spec-markdown, observability, state-layer,
# counted-fallback, supervision, determinism, engine-coverage) in one
# process — role of the reference `make lint` (Makefile:153-158,
# flake8+mypy; neither ships in this image).  Exits 0 modulo the
# checked-in ratchet file speclint_baseline.json.  Warm reruns serve
# findings from the content-hash incremental store
# (.speclint_cache.json, gitignored; BENCHMARKS round 12 times
# cold vs warm).  The compiled ladder is generated (gitignored):
# build it if absent so fresh clones lint out of the box, but never
# overwrite an existing tree (a drifted or hand-edited one must stay
# visible to the L3xx pass).
lint:
	$(PYTHON) -m compileall -q consensus_specs_tpu tests generators benchmarks
	@test -d consensus_specs_tpu/forks/compiled || $(MAKE) pyspec
	$(PYTHON) -m consensus_specs_tpu.tools.speclint .

# the pre-commit developer loop (docs/static-analysis.md): lint only
# the files dirty vs the git index; the tree passes (ladder,
# determinism, coverage, effects) stay warm through the dependency-
# granular cache unless a file they actually read changed
lint-changed:
	$(PYTHON) -m consensus_specs_tpu.tools.speclint . --changed

# the two CI proof gates on their own (both baseline-zero): the E12xx
# commit-scope/psum/write-ordering verdicts and the N13xx per-dispatch-
# path host-work budget (every mesh path proven O(S) host work —
# docs/static-analysis.md, docs/sharding.md)
lint-verdicts:
	$(PYTHON) -m consensus_specs_tpu.tools.speclint . --effect-verdicts
	$(PYTHON) -m consensus_specs_tpu.tools.speclint . --cost-verdicts

# intentionally re-record the speclint debt (after paying some down, or
# with a written justification for new findings in the PR).
# `make speclint-baseline PASSES=uint64,ranges` re-ratchets only the
# named passes: every other pass's recorded debt is carried over
# untouched (the driver keeps their baseline keys).
speclint-baseline:
	$(PYTHON) -m consensus_specs_tpu.tools.speclint . --write-baseline \
		$(if $(PASSES),--passes $(PASSES))

# crypto kernels incl. the heavy differential tier — one pytest
# process per file: the big XLA programs (pairing, sharded verify,
# batched SHA) each claim gigabytes during compile, and accumulating
# them in one interpreter can exhaust the 1-core host mid-run
CRYPTO_SUITES = tests/test_bls.py tests/test_bls_rlc.py \
	tests/test_native_bls.py \
	tests/test_numpy_kernels.py tests/test_hash_to_curve.py \
	tests/test_sha256_kernel.py tests/test_curdleproofs.py \
	tests/test_jax_bls.py tests/test_multichip.py tests/deneb/kzg

test-crypto:
	@set -e; for s in $(CRYPTO_SUITES); do \
		echo "=== $$s"; CS_TPU_HEAVY=1 $(PYTHON) -m pytest $$s -q; \
	done

bench:
	$(PYTHON) bench.py

bench-all:
	$(PYTHON) benchmarks/bench_all.py

# epoch-engine smoke: loop-vs-vectorized rewards at the small registry
# shape (full matrix: --epoch-shapes 16384,262144,1048576)
bench-epoch:
	$(PYTHON) benchmarks/bench_all.py --configs 5 --epoch-shapes 16384

# merkle-engine dispatch smoke: registry-wide commits must re-hash
# through the batched paths (asserted via the utils/ssz/merkle counters;
# nonzero exit on a per-pair hashlib regression).  Native build is
# best-effort: without it the smoke installs the JAX batched hasher.
bench-merkle-smoke:
	-$(MAKE) native
	$(PYTHON) benchmarks/bench_merkle_smoke.py

# fork-choice dispatch smoke: head recomputes must run through the
# proto-array engine (ZERO spec-loop fallbacks) and match the spec loop
# byte-for-byte on every churn round (asserted via the
# forkchoice/proto_array counters; nonzero exit on regression)
bench-forkchoice-smoke:
	$(PYTHON) benchmarks/bench_fork_choice.py --smoke

# whole-block signature-verification smoke: the deferred flush must
# take the RLC path with EXACTLY one pairing for the block (asserted
# via the bls.flush/bls.pairings counters; nonzero exit on regression),
# agree with the lane path + python oracle on a tampered-item matrix,
# and report lane-vs-RLC and oracle-vs-RLC ratios
bench-block-smoke:
	-$(MAKE) native
	$(PYTHON) benchmarks/bench_block_verify.py --smoke

# state-arrays store smoke: the copy-on-write column store must show
# at most one registry extraction per epoch transition, exactly one
# balance-family commit per transition, and N forked replays sharing
# one base snapshot with byte-identical roots (counter-asserted via the
# state_arrays.* metrics; nonzero exit on regression)
bench-state-smoke:
	$(PYTHON) benchmarks/bench_state_arrays.py --smoke

# adversarial sweep acceptance (docs/simulator.md): >= 200 seeded
# hostile scenarios complete engines-on; every injected fault counted
# on its reason=injected series (zero silent fallbacks), every
# injected/storm/spec-differential leg byte-identical to its
# uninjected replay; nonzero exit + minimized repro artifacts under
# sim_artifacts/ on any violation.  The time budget converts a
# pathological host into a controlled failure instead of a CI hang.
sim-smoke:
	$(PYTHON) -m consensus_specs_tpu.sim.sweep --seeds 200 \
		--min-scenarios 200 --time-budget 2400
	CS_TPU_SANITIZER=1 $(PYTHON) -m consensus_specs_tpu.sim.sweep \
		--seeds 24 --min-scenarios 24 --start 9000 \
		--recovery-seeds 1 --time-budget 600

# the CS_TPU_HEAVY nightly shape: a thousand seeds on a denser
# injection cadence with more real-signature seeds, then the cross-leg
# with proto-array AND the state-arrays store off (spec-loop fork
# choice + detached columns) so the remaining engines are swept against
# the pure-spec composition too
sim-heavy:
	$(PYTHON) -m consensus_specs_tpu.sim.sweep --seeds 1000 \
		--inject-every 4 --max-sites 6 --diff-every 8 --bls-seeds 4
	CS_TPU_PROTO_ARRAY=0 CS_TPU_STATE_ARRAYS=0 \
		$(PYTHON) -m consensus_specs_tpu.sim.sweep --seeds 250 \
		--start 5000 --inject-every 8 --diff-every 10 --bls-seeds 2

# telemetry disabled-path overhead: with CS_TPU_PROFILE/CS_TPU_TRACE
# unset, the span + counter instrumentation across the engine stack
# must cost <2% of the 32-slot replay (exact op census x measured
# per-op cost; nonzero exit above the bound).  Also bounds the flight
# recorder (disarmed record cost x armed-replay census <2%) and
# asserts a flight+trace-armed serving replay byte-identical to the
# synchronous oracle.
bench-obs-smoke:
	$(PYTHON) benchmarks/bench_obs_overhead.py

# live telemetry plane smoke (docs/observability.md): obs.serve must
# answer /metrics, /healthz and /snapshot (schema-checked) WHILE a
# pipelined serving replay runs, without moving a byte of consensus
# state; a forced quarantine must flip /healthz to 503 and a
# supervisor reset must restore it
bench-telemetry-smoke:
	$(PYTHON) benchmarks/bench_telemetry.py

# DAS engine smoke (docs/das.md): a multi-blob cell-proof batch must
# verify in exactly ONE pairing check (ZERO of its own inside an RLC
# scope — the block's single flush pairing carries it), batched
# multi-blob erasure recovery must beat the per-blob spec-markdown
# loop byte-identically, and the CS_TPU_DAS=0 wrapper overhead must
# stay under the 2% bound (counter-asserted; nonzero exit on any
# regression).  Native build is best-effort — the engine folds and the
# spec loop both degrade to the python pairing oracle without it.
bench-das-smoke:
	-$(MAKE) native
	$(PYTHON) benchmarks/bench_das.py

# mesh-engine smoke (docs/sharding.md): on the 8-way host-device mesh
# (XLA_FLAGS below), a full epoch transition must run every
# sub-transition through the SPMD programs with EXACTLY the budgeted
# psum count per sub-transition (mesh.psums counter-asserted against
# mesh_epoch.PSUM_BUDGET, psum census proven structurally on the
# jaxprs), commit byte-identical state roots mesh-on vs mesh-off vs
# spec loop, and show near-linear (>= 6x at 8 shards) per-shard kernel
# scaling on 1M-validator columns; nonzero exit on any regression
bench-mesh-smoke:
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		$(PYTHON) benchmarks/bench_mesh.py

# block-serving pipeline smoke (docs/serving.md): the pipelined lane
# (window batching + overlapped RLC flush + chunk-level clones) must
# replay the captured adversarial load streams byte-identical to the
# synchronous per-block lane (deep store digests + per-block verdicts),
# fold to EXACTLY one pairing per window (bls.pairings ==
# serving.windows, strictly below the sync lane's per-block count),
# keep the one-commit-per-epoch census lane-identical under overlap,
# and sustain strictly more slots/sec; chunk-level clone_state must
# beat state.copy() root-identically.  Native build is best-effort —
# the lanes degrade together to a slower signature backend without it.
bench-serving-smoke:
	-$(MAKE) native
	$(PYTHON) benchmarks/bench_serving.py --smoke

# durable-replay smoke (docs/recovery.md): checkpoint save/restore +
# journal tail replay round-trip byte-identical (counter-asserted:
# restore really served from a checkpoint generation), restore +
# tail-replay cost measured and reported, and the checkpoint-DISABLED
# wrapper overhead bound: with CS_TPU_CHECKPOINT=0 the durable step
# driver must cost <2% over the plain replay (the obs/supervisor
# discipline; nonzero exit above the bound)
bench-recovery-smoke:
	$(PYTHON) benchmarks/bench_recovery.py

# runtime effect-sanitizer smoke (docs/static-analysis.md): the
# disabled hooks must cost <2% of the 32-slot replay (census x per-op
# cost), an ARMED replay must be byte-identical to the disarmed one
# with zero violations, and the armed leg must book nonzero
# sanitizer.checks (non-vacuous); nonzero exit on any violated bound
bench-sanitizer-smoke:
	$(PYTHON) benchmarks/bench_sanitizer.py

# engine-supervisor smoke (docs/robustness.md): counter-asserted
# breaker lifecycle on a real dispatch site (threshold trips ->
# open -> skip -> half-open probe -> closed; corrupt-mode result +
# rate-1 sentinel audit -> quarantine + artifact), then the
# enabled-path overhead bound: supervisor ON must cost <2% of the
# 32-slot replay (exact call census x measured per-op cost, the
# bench_obs_overhead discipline; nonzero exit above the bound)
bench-supervisor-smoke:
	$(PYTHON) benchmarks/bench_supervisor.py

# human telemetry view: 32-slot replay with full tracing, span tree +
# metric catalog (see docs/observability.md; --format json|prom for the
# machine exporters)
obs-report:
	$(PYTHON) -m consensus_specs_tpu.tools.obs_report

dryrun:
	$(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# prewarm the persistent XLA compile cache (fingerprint-keyed) with every
# program bench.py and the multichip dryrun dispatch - run after checkout
# or dependency changes so the driver-facing entry points replay cached
# executables instead of paying cold XLA:CPU compiles
warm:
	$(PYTHON) -m consensus_specs_tpu.tools.warm

# compile the markdown specs into importable modules (reference `make pyspec`)
pyspec:
	$(PYTHON) -m consensus_specs_tpu.compiler

# vector generation (reference `make generate_tests` / `make gen_<name>`)
generate_tests: $(addprefix gen_,$(GENERATORS))

$(addprefix gen_,$(GENERATORS)): gen_%:
	$(PYTHON) generators/$*/main.py -o $(OUTPUT_DIR)

# corpus factory (docs/corpus.md): every generator through ONE shared
# fork-start pool — pre-warmed parent image (spec ladders, genesis
# states, pubkeys inherited copy-on-write), cost-aware longest-first
# schedule from the persisted per-case timing profile, per-case RLC
# signature folds with synchronous replay on any failed fold.
# Byte-identical to `make generate_tests` (bench_corpus asserts the
# tree digests); resume semantics unchanged (INCOMPLETE cases redone,
# complete cases skipped)
corpus:
	$(PYTHON) -m consensus_specs_tpu.gen.corpus -o $(OUTPUT_DIR)

# corpus fidelity replay (docs/corpus.md): re-execute the emitted
# operations/epoch_processing/sanity/finality vectors through the spec
# twice — engines on, then every CS_TPU_* switch forced off — proving
# no engine leaked an optimistic result into a vector; nonzero exit on
# any mismatch in either leg
corpus-check:
	$(PYTHON) -m consensus_specs_tpu.gen.replay -o $(OUTPUT_DIR)
	CS_TPU_VECTORIZED_EPOCH=0 CS_TPU_PROTO_ARRAY=0 \
	CS_TPU_STATE_ARRAYS=0 CS_TPU_BLS_RLC=0 CS_TPU_HASH_FOREST=0 \
	CS_TPU_SUPERVISOR=0 CS_TPU_DAS=0 CS_TPU_MESH=0 \
	CS_TPU_CHECKPOINT=0 CS_TPU_SERVING=0 \
		$(PYTHON) -m consensus_specs_tpu.gen.replay -o $(OUTPUT_DIR)

# corpus factory smoke (docs/corpus.md): bounded subset generated both
# ways — serial per-generator processes vs the one-pool factory — with
# tree digests compared byte-for-byte, plus the counter-asserted
# censuses: sign memo engages (gen.sign_memo hits > 0), folded cases
# collapse to at most one RLC pairing each (bls.flush{path=rlc} <=
# gen.case_batches{path=folded}, total pairings strictly below the
# unfolded run), and expected-invalid cases fall back through
# gen.case_replays; nonzero exit on any violation
bench-corpus-smoke:
	$(PYTHON) benchmarks/bench_corpus.py --smoke

# native C components (raw-snappy codec for vector IO, SHA-256 merkle
# layer hasher for host-side merkleization, BLS12-381 signature backend
# — the reference's milagro/arkworks role; constants generated from the
# python oracle by csrc/gen_bls_consts.py)
native:
	gcc -O2 -shared -fPIC -o csrc/libcsnappy.so csrc/snappy.c
	gcc -O3 -shared -fPIC -o csrc/libcsha256.so csrc/sha256_merkle.c
	gcc -O2 -shared -fPIC -o csrc/libcbls12381.so csrc/bls12_381.c

bls-consts:
	$(PYTHON) csrc/gen_bls_consts.py > csrc/bls12_381_consts.h

clean-vectors:
	rm -rf $(OUTPUT_DIR)/tests
