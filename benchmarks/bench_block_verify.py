"""Whole-block signature-verification benchmark (``make bench-block-smoke``
runs the counter-asserted smoke shape in CI).

The headline crypto number (ROADMAP item 3): a mainnet-shaped block —
up to 128 attestation aggregates over committee-sized pubkey sets, plus
the proposer signature and randao reveal — flushed through the deferred
batch context three ways:

* **rlc**   — the random-linear-combination fold (``CS_TPU_BLS_RLC=1``,
  default): 2 MSMs + ONE product pairing for the whole block
  (``ops/bls_rlc.py``);
* **lanes** — the per-lane batch path (``CS_TPU_BLS_RLC=0``): one full
  pairing check per queued item;
* **python oracle** — the reference-role pure-python backend, one
  ``FastAggregateVerify`` at a time (timed on a subset and extrapolated:
  a full 128-attestation oracle block takes minutes).

Aggregate signatures are built with one ``Sign`` per attestation
(``H(m)^sum(sk_i)`` equals the aggregate of the members' signatures), so
the bench spends its time verifying, not signing.

``--smoke`` also counter-asserts the engine contract: the RLC flush must
report ``bls.flush{path=rlc}`` with EXACTLY one ``bls.pairings`` tick
per block, byte-agree with the lane path and the oracle on a
valid-and-invalid item matrix, and emit a schema-valid obs snapshot.

``--slots N`` appends a sustained full ``state_transition`` loop (BLS
on) on a minimal-preset genesis — slots/sec with per-stage span
attribution (host_pack / hash_to_field / msm / pairing) when
``CS_TPU_PROFILE=1``.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER


def _build_block_items(n_aggregates, committee, n_singles=2):
    """n_aggregates FastAggregateVerify triples (distinct messages,
    ``committee`` pubkeys each, one Sign per aggregate via the privkey
    sum) + n_singles single-pubkey items (proposer / randao stand-ins)."""
    from consensus_specs_tpu.test_infra.keys import privkeys, pubkey
    from consensus_specs_tpu.utils import bls
    items = []
    for a in range(n_aggregates):
        members = [privkeys[(a * committee + j) % len(privkeys)]
                   for j in range(committee)]
        msg = b"block-att-" + a.to_bytes(4, "little") + b"\x00" * 18
        sig = bls.Sign(sum(members) % R_ORDER, msg)
        items.append(([pubkey(sk) for sk in members], msg, sig))
    for s in range(n_singles):
        sk = privkeys[-(s + 1)]
        msg = b"block-hdr-" + s.to_bytes(4, "little") + b"\x00" * 18
        items.append(([pubkey(sk)], msg, bls.Sign(sk, msg)))
    return items


def _flush(items):
    from consensus_specs_tpu.utils import bls
    bls.clear_verify_memo()
    batch = bls.DeferredBatch()
    for pks, msg, sig in items:
        batch.add(pks, msg, sig)
    return batch.flush()


def _time_flush(items, reps):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        ok = _flush(items)
        best = min(best, time.perf_counter() - t0)
        assert ok, "bench items must verify"
    return best


def _time_oracle(items, limit):
    """Per-item pure-python verification, extrapolated per item CLASS:
    committee-size aggregates and single-pubkey items have different
    oracle costs (the decode/aggregation prefix), so each class is
    timed on its own subset and scaled by its own count."""
    from consensus_specs_tpu.ops.bls12_381 import ciphersuite

    def timed(sub):
        t0 = time.perf_counter()
        for pks, msg, sig in sub:
            assert ciphersuite.FastAggregateVerify(pks, msg, sig)
        return (time.perf_counter() - t0) / len(sub) if sub else 0.0

    aggs = [it for it in items if len(it[0]) > 1]
    singles = [it for it in items if len(it[0]) == 1]
    per_agg = timed(aggs[:limit])
    per_single = timed(singles[:max(1, limit // 2)])
    total = per_agg * len(aggs) + per_single * len(singles)
    return total, min(limit, len(aggs)) + min(max(1, limit // 2),
                                              len(singles))


def _pick_backend(name):
    from consensus_specs_tpu.utils import bls
    if name == "fastest":
        bls.use_fastest()
    elif name == "native":
        bls.use_native()
    elif name == "jax":
        bls.use_jax()
    else:
        bls.use_py()
    return bls.backend_name()


def _counter_asserted_smoke(items, metrics):
    """The CI contract: RLC path really answers, with ONE pairing."""
    from consensus_specs_tpu.utils import bls
    pairings = metrics["bls.pairings"]
    flush = metrics["bls.flush"]
    assert bls.rlc_enabled(), \
        "smoke must run with CS_TPU_BLS_RLC unset/1 (the default)"
    p0, r0 = pairings.total(), flush.value(path="rlc")
    assert _flush(items), "valid block failed to verify"
    assert flush.value(path="rlc") - r0 == 1, "flush did not take the RLC path"
    assert pairings.total() - p0 == 1, \
        f"RLC flush used {pairings.total() - p0} pairings, expected 1"
    # invalid matrix: one tampered aggregate -> fallback bisect must
    # blame exactly that item, identically to the oracle's verdicts
    bad = list(items)
    pks0, msg0, _ = bad[0]
    bad[0] = (pks0, msg0, bad[1][2])
    from consensus_specs_tpu.utils.bls import DeferredBatch
    from consensus_specs_tpu.utils import bls as _bls
    _bls.clear_verify_memo()
    batch = DeferredBatch()
    for pks, msg, sig in bad:
        batch.add(pks, msg, sig)
    assert not batch.flush(), "tampered block must fail"
    assert batch.last_results[0] is False \
        and all(batch.last_results[1:]), \
        f"bisect blamed the wrong items: {batch.last_results}"


def _att_prep_smoke():
    """The vmapped message-prep contract (``ops/att_prep.py``): every
    block attestation verified during a real state_transition slice
    must be served from the per-block prepared signing-root table —
    zero misses, one hit per prepared attestation — while the full
    BLS-on transition (which would assert on any wrong signing root)
    stays green."""
    from consensus_specs_tpu.test_infra.metrics import counting
    with counting() as delta:
        _sustained_slots(4)
    assert delta["att_prep.blocks"] > 0, "no blocks prepared"
    assert delta["att_prep.prepared"] > 0, "no attestations prepared"
    assert delta["att_prep.misses"] == 0, \
        f"prepared attestations missed the table: {dict(delta)}"
    assert delta["att_prep.hits"] == delta["att_prep.prepared"], \
        f"hit/prepared census mismatch: {dict(delta)}"


def _sustained_slots(n_slots):
    """Full state_transition loop (BLS on) on a minimal-preset genesis:
    the serving-throughput shape, slots/sec."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.test_infra.context import (
        _get_genesis_state, default_balances, default_activation_threshold)
    from consensus_specs_tpu.test_infra.attestations import (
        get_valid_attestation)
    from consensus_specs_tpu.test_infra.block import (
        build_empty_block_for_next_slot, state_transition_and_sign_block)
    from consensus_specs_tpu.utils import bls

    bls.bls_active = True
    spec = build_spec("phase0", "minimal")
    state = _get_genesis_state(spec, default_balances,
                               default_activation_threshold).copy()
    t0 = time.perf_counter()
    for _ in range(n_slots):
        attestation = get_valid_attestation(spec, state, signed=True) \
            if state.slot > 0 else None
        block = build_empty_block_for_next_slot(spec, state)
        if attestation is not None and int(state.slot) + 1 >= int(
                attestation.data.slot
                + spec.MIN_ATTESTATION_INCLUSION_DELAY):
            block.body.attestations.append(attestation)
        state_transition_and_sign_block(spec, state, block)
    dt = time.perf_counter() - t0
    return n_slots / dt, dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--attestations", type=int, default=128)
    ap.add_argument("--committee", type=int, default=64)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--oracle-items", type=int, default=4,
                    help="items actually timed on the python oracle "
                         "(extrapolated to the full block)")
    ap.add_argument("--backend", default="fastest",
                    choices=["fastest", "native", "jax", "py"])
    ap.add_argument("--slots", type=int, default=0,
                    help="append a sustained state_transition loop of "
                         "this many slots (BLS on)")
    ap.add_argument("--smoke", action="store_true",
                    help="small counter-asserted CI shape")
    args = ap.parse_args()

    if args.smoke:
        args.attestations, args.committee = 8, 8
        args.reps = 2

    from consensus_specs_tpu.obs import export, registry
    from consensus_specs_tpu.utils import bls
    metrics = {name: registry.counter(name)
               for name in ("bls.pairings", "bls.flush")}

    backend = _pick_backend(args.backend)
    bls.bls_active = True
    items = _build_block_items(args.attestations, args.committee)

    if args.smoke:
        _counter_asserted_smoke(items, metrics)
        _att_prep_smoke()

    prior_rlc = os.environ.get("CS_TPU_BLS_RLC")
    try:
        os.environ["CS_TPU_BLS_RLC"] = "1"
        rlc_s = _time_flush(items, args.reps)
        os.environ["CS_TPU_BLS_RLC"] = "0"
        lanes_s = _time_flush(items, args.reps)
    finally:
        if prior_rlc is None:
            del os.environ["CS_TPU_BLS_RLC"]
        else:
            os.environ["CS_TPU_BLS_RLC"] = prior_rlc
    oracle_s, oracle_timed = _time_oracle(items, args.oracle_items)

    out = {
        "metric": f"block verify, {args.attestations} aggregates x "
                  f"{args.committee} keys (+2 singles)",
        "backend": backend,
        "rlc_flush_s": round(rlc_s, 4),
        "lanes_flush_s": round(lanes_s, 4),
        "python_oracle_s": round(oracle_s, 3),
        "oracle_items_timed": oracle_timed,
        "lane_vs_rlc": round(lanes_s / rlc_s, 2),
        "oracle_vs_rlc": round(oracle_s / rlc_s, 1),
    }
    if args.slots:
        slots_per_s, wall = _sustained_slots(args.slots)
        out["sustained_slots"] = args.slots
        out["slots_per_sec"] = round(slots_per_s, 2)
        out["sustained_wall_s"] = round(wall, 2)

    # telemetry snapshot: schema-valid with the bls flush/pairing
    # counters populated (the "one pairing per block" tripwire)
    snap = export.snapshot()
    export.assert_schema(snap, require_nonempty=("bls.",))
    out["obs"] = {"metrics": {k: v for k, v in snap["metrics"].items()
                              if k.startswith("bls.")}}
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
