"""Live telemetry-plane smoke (``make bench-telemetry-smoke``).

Proves the serving-pipeline observability story end to end in one
process:

1. **Scrape under load** — ``obs.serve(0)`` answers ``/metrics``,
   ``/healthz`` and ``/snapshot`` WHILE a pipelined serving replay of a
   ``sim/load`` stream runs on a worker thread, with span tracing and
   the flight recorder armed.  Every ``/snapshot`` answer is re-checked
   against the exporter schema on the client side too.
2. **Effect freedom** — the replay's store digest must be
   byte-identical to the synchronous ``CS_TPU_SERVING=0`` oracle
   (``load.sync_digest``): scraping + tracing + flight never perturb
   consensus state.
3. **Health wiring** — a forced quarantine (artifact hook stubbed out)
   flips ``/healthz`` to 503 naming the site; ``supervisor.reset()``
   restores 200.
4. **Evidence** — the armed replay's flight dump is non-empty on both
   the main and the flush-worker thread, and exports to a Chrome trace
   with events.

Exits nonzero on any violated claim; prints one JSON measurement line.
"""
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the health leg needs a live supervisor no matter the caller's shell
os.environ.setdefault("CS_TPU_SUPERVISOR", "1")

SEED = 3
SCENARIO = "equivocation"
WINDOW = 3


def _get(url: str):
    """(status, body bytes) — 4xx/5xx answered, not raised."""
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


def main() -> int:
    from consensus_specs_tpu import obs, supervisor
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.obs import export, flight
    from consensus_specs_tpu.serving.pipeline import BlockServer
    from consensus_specs_tpu.sim import load
    from consensus_specs_tpu.utils import bls

    bls.bls_active = False
    spec = build_spec("phase0", "minimal")
    stream = load.generate(spec, seed=SEED, name=SCENARIO)
    oracle = load.sync_digest(spec, stream)

    obs.reset_all()
    supervisor.reset()
    flight.enable(True)
    obs.enable(True, counters=False)
    result = {}

    def _replay():
        server = BlockServer(spec, load.anchor_store(spec, stream),
                             window=WINDOW)
        load.serve(server, stream)
        result["digest"] = load.store_digest(spec, server.store)
        result["windows"] = len(server.window_log)

    scrapes = {"metrics": 0, "healthz": 0, "snapshot": 0}
    try:
        with obs.serve(0) as srv:
            worker = threading.Thread(target=_replay,
                                      name="bench-telemetry-replay")
            worker.start()
            # scrape all three endpoints for as long as the replay runs
            while worker.is_alive():
                code, body = _get(srv.url + "/metrics")
                assert code == 200 and b"cs_tpu_" in body, \
                    f"/metrics under load: {code}"
                scrapes["metrics"] += 1
                code, body = _get(srv.url + "/healthz")
                assert code == 200, f"/healthz under load: {code} {body!r}"
                scrapes["healthz"] += 1
                code, body = _get(srv.url + "/snapshot")
                assert code == 200, f"/snapshot under load: {code}"
                snap = json.loads(body)
                problems = export.schema_problems(snap)
                assert not problems, f"/snapshot schema: {problems}"
                scrapes["snapshot"] += 1
                time.sleep(0.01)
            worker.join()
            assert min(scrapes.values()) >= 1, \
                f"no scrape completed during the replay: {scrapes}"
            assert result["digest"] == oracle, (
                "scraped+traced+flight-armed serving replay diverged "
                f"from the synchronous oracle: {result['digest']} != "
                f"{oracle}")

            # health wiring: forced quarantine -> 503 naming the site,
            # reset -> 200 (artifact hook stubbed: no dump side effect)
            site = "bench.telemetry"
            with supervisor.quarantine_hook(lambda s, d: None):
                supervisor.quarantine(site, "forced by bench_telemetry")
            code, body = _get(srv.url + "/healthz")
            health = json.loads(body)
            assert code == 503 and site in health["quarantined"], \
                f"/healthz after quarantine: {code} {health}"
            supervisor.reset()
            code, _ = _get(srv.url + "/healthz")
            assert code == 200, f"/healthz after reset: {code}"

        # evidence: both threads left flight records; the chrome
        # export carries events
        dump = flight.dump(trigger="manual")
        threads = {name: len(recs)
                   for name, recs in dump["threads"].items()}
        assert len(threads) >= 2 and all(threads.values()), \
            f"flight dump missing a thread's tail: {threads}"
        trace = flight.to_chrome_trace(dump)
        assert trace["traceEvents"], "empty chrome trace"
    finally:
        obs.enable(False)
        flight.enable(False)
        obs.reset_all()
        supervisor.reset()

    print(json.dumps({
        "metric": f"telemetry plane smoke, {SCENARIO}[seed={SEED}] "
                  f"window={WINDOW}",
        "windows": result["windows"],
        "scrapes_during_replay": scrapes,
        "digest_identity": True,
        "flight_threads": threads,
        "chrome_trace_events": len(trace["traceEvents"]),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
