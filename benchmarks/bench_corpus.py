"""Corpus factory benchmark (``make bench-corpus-smoke``, CI-wired).

Generates the SAME bounded corpus subset two ways and holds the factory
to its contract:

* **serial baseline** — one ``python generators/<name>/main.py -j 1``
  subprocess per generator, the ``make generate_tests`` shape: every
  process re-imports the spec ladders, rebuilds genesis, re-derives
  pubkeys;
* **factory** — ONE ``python -m consensus_specs_tpu.gen.corpus``
  subprocess: shared fork pool, pre-warmed parent image, cost-aware
  longest-first schedule, per-case RLC folds, sign memo.

Counter-asserted contracts (nonzero exit on any violation):

1. **byte-identity** — both trees reduce to the same content digest
   (every part file of every case compared);
2. **sign memo engages** — ``gen.sign_memo{result=hit}`` > 0 in the
   factory's in-process census leg (sibling cases re-sign the same
   roots);
3. **one pairing per folded case** — ``gen.case_batches{path=folded}``
   > 0, RLC flushes ≤ folded cases, and total ``bls.pairings`` strictly
   below the unfolded run of the same cases; expected-invalid cases
   show up in ``gen.case_replays`` (optimism never ships — they rerun
   on the plain path);
4. **wall-clock** (``--full`` only, the BENCHMARKS Round 17 shape) —
   factory ≥ 3× over the serial baseline on the full multi-fork
   minimal-preset subset when the host has ≥ 4 cores (the pool
   parallelizes case compute AND amortizes the 19 startups).  On
   fewer cores the pool cannot parallelize — both legs run the same
   case compute on the same core — so the gate is strictly-faster:
   the amortization win (one interpreter/jax/spec-ladder startup,
   one genesis build, one pubkey derivation instead of 19) must
   still show.

The smoke reports the wall-clock ratio but does not gate on it: CI
machines are too noisy for a small subset to prove a speedup, and the
censuses (not the stopwatch) are the correctness contract.
"""
import argparse
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SMOKE_GENERATORS = ["sanity", "epoch_processing", "genesis", "shuffling"]
SMOKE_FORKS = ["phase0", "altair"]


def tree_digest(root: str) -> str:
    h = hashlib.sha256()
    base = os.path.join(root, "tests")
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames.sort()
        for name in sorted(filenames):
            path = os.path.join(dirpath, name)
            h.update(os.path.relpath(path, base).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def run_serial(out: str, generators, forks, presets) -> float:
    """The ``make generate_tests`` shape: one process per generator."""
    t0 = time.perf_counter()
    for gen in generators:
        subprocess.run(
            [sys.executable, os.path.join(REPO, "generators", gen,
                                          "main.py"),
             "-o", out, "-j", "1",
             "--preset-list", *presets, "--fork-list", *forks],
            check=True, env=_env(), cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    return time.perf_counter() - t0


def run_factory(out: str, generators, forks, presets, workers) -> float:
    t0 = time.perf_counter()
    subprocess.run(
        [sys.executable, "-m", "consensus_specs_tpu.gen.corpus",
         "-o", out, "-j", str(workers),
         "--generators", *generators,
         "--preset-list", *presets, "--fork-list", *forks],
        check=True, env=_env(), cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    return time.perf_counter() - t0


def census_leg(generators, forks, presets, workdir):
    """In-process fold-vs-plain run of the same cases: the counter
    evidence for the sign-memo and one-pairing-per-case claims."""
    from consensus_specs_tpu.utils.jax_env import force_cpu_platform
    force_cpu_platform()
    from consensus_specs_tpu.gen import corpus as corpus_mod
    from consensus_specs_tpu.gen import gen_runner
    from consensus_specs_tpu.test_infra import context as ctx
    from consensus_specs_tpu.test_infra import signing
    from consensus_specs_tpu.test_infra.metrics import counting
    ctx.DEFAULT_BLS_ACTIVE = True

    cases, _ = corpus_mod.collect_corpus_cases(
        generators, presets, forks, output_dir=workdir)
    legs = {}
    for leg, fold in (("plain", False), ("folded", True)):
        signing.clear()
        out = os.path.join(workdir, leg)
        with counting() as delta:
            outcomes, _ = gen_runner.run_cases(cases, out, workers=1,
                                               fold=fold)
        assert all(r != "error" for _, r, _ in outcomes), \
            f"{leg}: case errors in census leg"
        legs[leg] = {"delta": delta, "digest": tree_digest(out)}
    return legs


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="bounded CI subset; censuses gate, "
                             "wall-clock reported only")
    parser.add_argument("--full", action="store_true",
                        help="all generators, all forks, minimal preset; "
                             "gates the >= 3x wall-clock claim "
                             "(BENCHMARKS Round 17)")
    parser.add_argument("-j", "--workers", type=int,
                        default=min(8, os.cpu_count() or 1))
    args = parser.parse_args()
    if not args.smoke and not args.full:
        args.smoke = True

    if args.full:
        from consensus_specs_tpu.gen.corpus import GENERATORS
        generators = list(GENERATORS)
        forks = ["phase0", "altair", "bellatrix", "capella", "deneb"]
    else:
        generators = SMOKE_GENERATORS
        forks = SMOKE_FORKS
    presets = ["minimal"]

    workdir = tempfile.mkdtemp(prefix="bench_corpus_")
    try:
        serial_out = os.path.join(workdir, "serial")
        factory_out = os.path.join(workdir, "factory")
        serial_s = run_serial(serial_out, generators, forks, presets)
        factory_s = run_factory(factory_out, generators, forks, presets,
                                args.workers)
        serial_digest = tree_digest(serial_out)
        factory_digest = tree_digest(factory_out)

        legs = census_leg(SMOKE_GENERATORS, SMOKE_FORKS, presets,
                          os.path.join(workdir, "census"))
        plain, folded = legs["plain"]["delta"], legs["folded"]["delta"]

        try:
            cores = len(os.sched_getaffinity(0))
        except AttributeError:
            cores = os.cpu_count() or 1
        result = {
            "metric": "corpus factory",
            "mode": "full" if args.full else "smoke",
            "generators": len(generators), "forks": forks,
            "workers": args.workers, "cores": cores,
            "serial_s": round(serial_s, 2),
            "factory_s": round(factory_s, 2),
            "speedup": round(serial_s / factory_s, 2),
            "digest": factory_digest[:16],
            "census": {
                "sign_memo_hits": folded["gen.sign_memo{result=hit}"],
                "sign_memo_misses": folded["gen.sign_memo{result=miss}"],
                "folded_cases": folded["gen.case_batches{path=folded}"],
                "case_replays": folded["gen.case_replays"],
                "pairings_plain": plain["bls.pairings"],
                "pairings_folded": folded["bls.pairings"],
                "rlc_flushes_folded": folded["bls.flush{path=rlc}"],
            },
        }
        print(json.dumps(result), flush=True)

        # the census guarantees (the smoke's reason to exist)
        assert factory_digest == serial_digest, \
            "factory tree differs from the serial baseline"
        assert legs["plain"]["digest"] == legs["folded"]["digest"], \
            "per-case fold changed emitted bytes"
        assert folded["gen.sign_memo{result=hit}"] > 0, \
            "sign memo never hit"
        folded_cases = folded["gen.case_batches{path=folded}"]
        assert folded_cases > 0, "no case ever folded"
        assert folded["bls.flush{path=rlc}"] <= folded_cases, \
            "more RLC flushes than folded cases (fold not one-pairing)"
        assert folded["bls.pairings"] < plain["bls.pairings"], \
            "fold did not reduce pairings"
        assert folded["gen.case_replays"] >= 1, \
            "no expected-invalid case replayed (fold suspiciously lossy)"
        if args.full:
            # with >= 4 cores the pool parallelizes case compute on
            # top of the startup amortization; on fewer cores both
            # legs run the same case compute on the same core, so
            # only the amortization win is measurable
            target = 3.0 if cores >= 4 and args.workers >= 4 else 1.05
            assert serial_s / factory_s >= target, \
                (f"wall-clock {serial_s / factory_s:.2f}x < "
                 f"{target}x target ({cores} cores)")
        return 0
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
