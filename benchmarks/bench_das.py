"""DAS engine smoke: pairing census, recovery margin, disabled
overhead (``make bench-das-smoke``).

Three asserted claims back the DAS engine's shipping default (on):

1. **One pairing per batch** — a multi-cell, multi-blob cell-proof
   batch through the engine must evaluate exactly ONE pairing check
   (``bls.pairings`` census), and the same batch inside an assert-style
   RLC scope must evaluate ZERO of its own — the block's single flush
   pairing carries it.  The spec loop's one-per-cell census is printed
   alongside; a tampered batch must fail on both paths.

2. **Batched recovery margin** — multi-blob erasure recovery through
   ``das.recover_many`` (shared vanishing polynomial + batch inversion
   across blobs missing the same columns) must beat the per-blob
   spec-markdown loop, byte-identically.  The measured ratio is
   recorded in BENCHMARKS.md.

3. **Disabled overhead** — with ``CS_TPU_DAS=0`` the dispatch wrapper
   must add under 2% to the spec loop it falls through to (exact
   per-call decomposition, the ``bench_obs_overhead.py`` discipline).

Exits nonzero on any census mismatch, a lost recovery race, a
divergence, or a >= 2% disabled overhead.
"""
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_BLOBS = 3
REPS = 3


def _spec():
    from consensus_specs_tpu.forks import build_spec
    return build_spec("eip7594", "minimal")


def _material(spec, n_blobs=N_BLOBS, n_proof_cells=3):
    rng = random.Random(0xDA5B)
    width = int(spec.FIELD_ELEMENTS_PER_BLOB)
    blobs = [b"".join(
        rng.randrange(int(spec.BLS_MODULUS)).to_bytes(32, "big")
        for _ in range(width)) for _ in range(n_blobs)]
    cells = [spec.compute_cells(b) for b in blobs]
    from consensus_specs_tpu.ops import kzg as K
    from consensus_specs_tpu.ops import kzg_7594 as K7
    setup = spec.kzg_setup
    commitments, proofs = [], []
    proof_ids = sorted(rng.sample(range(spec.cells_per_blob()),
                                  n_proof_cells))
    for blob, blob_cells in zip(blobs, cells):
        commitments.append(spec.blob_to_kzg_commitment(blob))
        coeff = K7.polynomial_eval_to_coeff(
            K.blob_to_polynomial(blob, width), setup)
        per = {}
        for cid in proof_ids:
            proof, ys = K7.compute_kzg_proof_multi_impl(
                coeff, K7.coset_for_cell(cid, setup), setup)
            assert ys == blob_cells[cid]
            per[cid] = proof
        proofs.append(per)
    return blobs, cells, commitments, proofs, proof_ids


def pairing_census(spec, material) -> int:
    from consensus_specs_tpu.test_infra.metrics import counting
    from consensus_specs_tpu.utils import bls
    _, cells, commitments, proofs, proof_ids = material
    rows, cols, cbs, prs = [], [], [], []
    for b in range(len(commitments)):
        for cid in proof_ids:
            rows.append(b)
            cols.append(cid)
            cbs.append(spec.cell_to_bytes(cells[b][cid]))
            prs.append(proofs[b][cid])
    n = len(cbs)
    failures = 0

    with counting() as delta:
        ok = spec.verify_cell_proof_batch(commitments, rows, cols, cbs,
                                          prs)
    if not ok or delta["bls.pairings"] != 1 \
            or delta["das.verify{path=engine}"] != 1:
        print(f"FAIL: engine batch of {n} cells expected ONE pairing, "
              f"got ok={ok} {delta.nonzero()}")
        failures += 1
    else:
        print(f"engine: {n}-cell batch ({len(commitments)} blobs x "
              f"{len(proof_ids)} columns) = 1 pairing check")

    bls.clear_verify_memo()
    with counting() as delta:
        with bls.batched_verification() as batch:
            assert spec.verify_cell_proof_batch(
                commitments, rows, cols, cbs, prs) is True
            own = delta["bls.pairings"]
            batch.assert_valid()
    if own != 0 or delta["bls.pairings"] != 1 \
            or delta["bls.flush{path=rlc}"] != 1:
        print(f"FAIL: in-scope batch expected 0 own pairings + 1 flush "
              f"pairing, got own={own} {delta.nonzero()}")
        failures += 1
    else:
        print("engine in RLC scope: 0 own pairings, the block's single "
              "flush pairing carries the batch")

    os.environ["CS_TPU_DAS"] = "0"
    try:
        with counting() as delta:
            ok = spec.verify_cell_proof_batch(commitments, rows, cols,
                                              cbs, prs)
        spec_pairings = delta["bls.pairings"]
    finally:
        del os.environ["CS_TPU_DAS"]
    if not ok or spec_pairings != n:
        print(f"FAIL: spec loop expected {n} pairings, got "
              f"ok={ok} pairings={spec_pairings}")
        failures += 1
    else:
        print(f"spec loop: same batch = {spec_pairings} pairing checks "
              f"({spec_pairings}x the engine)")

    # tampered batch must fail on both paths
    bad = list(cbs)
    flip = (int.from_bytes(bad[1][:32], "big") + 1) \
        % int(spec.BLS_MODULUS)
    bad[1] = flip.to_bytes(32, "big") + bad[1][32:]
    got_e = spec.verify_cell_proof_batch(commitments, rows, cols, bad, prs)
    os.environ["CS_TPU_DAS"] = "0"
    try:
        got_s = spec.verify_cell_proof_batch(commitments, rows, cols,
                                             bad, prs)
    finally:
        del os.environ["CS_TPU_DAS"]
    if got_e is not False or got_s is not False:
        print(f"FAIL: tampered batch verdicts engine={got_e} "
              f"spec={got_s}")
        failures += 1
    else:
        print("tampered cell rejected on both paths")
    return failures


def recovery_margin(spec, material) -> int:
    from consensus_specs_tpu.das import recover_many
    from consensus_specs_tpu.test_infra.metrics import counting
    _, cells, _, _, _ = material
    rng = random.Random(0xDA5C)
    n_cells = spec.cells_per_blob()
    keep = sorted(rng.sample(range(n_cells), n_cells // 2))
    requests = [(keep, [spec.cell_to_bytes(c[i]) for i in keep])
                for c in cells]
    fulls = [[x for cell in c for x in cell] for c in cells]

    def engine_run():
        t0 = time.perf_counter()
        outs = recover_many(spec, requests)
        return time.perf_counter() - t0, outs

    def spec_run():
        os.environ["CS_TPU_DAS"] = "0"
        try:
            t0 = time.perf_counter()
            outs = [spec.recover_polynomial(ids, cbs)
                    for ids, cbs in requests]
            return time.perf_counter() - t0, outs
        finally:
            del os.environ["CS_TPU_DAS"]

    with counting() as delta:
        engine_t, engine_out = min(
            (engine_run() for _ in range(REPS)), key=lambda r: r[0])
    spec_t, spec_out = min(
        (spec_run() for _ in range(REPS)), key=lambda r: r[0])
    failures = 0
    if engine_out != spec_out or engine_out != fulls:
        print("FAIL: batched recovery diverged from the spec loop")
        failures += 1
    if delta["das.recover{path=engine}"] != REPS:
        print(f"FAIL: engine recovery census "
              f"{delta['das.recover{path=engine}']} != {REPS}")
        failures += 1
    ratio = spec_t / engine_t if engine_t > 0 else float("inf")
    print(f"recovery ({len(requests)} blobs, {len(keep)}/{n_cells} "
          f"cells): engine {engine_t:.2f}s vs spec loop {spec_t:.2f}s "
          f"= {ratio:.2f}x")
    if ratio <= 1.0:
        print("FAIL: batched recovery must beat the per-blob spec loop")
        failures += 1
    return failures


def disabled_overhead(spec, material) -> int:
    """CS_TPU_DAS=0: wrapper cost per dispatch vs the spec body it
    falls through to (exact per-call decomposition; the workload is a
    cheap custody/structural verify so the wrapper share is visible)."""
    spec_body = type(spec).__dict__[
        "verify_cell_proof_batch"]._das_spec_body
    args = ([], [], [], [], [])
    n = 4000
    os.environ["CS_TPU_DAS"] = "0"
    try:
        def wrapped():
            t0 = time.perf_counter()
            for _ in range(n):
                spec.verify_cell_proof_batch(*args)
            return time.perf_counter() - t0

        def raw():
            t0 = time.perf_counter()
            for _ in range(n):
                spec_body(spec, *args)
            return time.perf_counter() - t0

        t_wrapped = min(wrapped() for _ in range(REPS))
        t_raw = min(raw() for _ in range(REPS))
    finally:
        del os.environ["CS_TPU_DAS"]
    per_call_us = (t_wrapped - t_raw) / n * 1e6
    # a real disabled-path dispatch spends its time in the spec loop's
    # pairings (~ms); bound the wrapper's added cost against a 1ms call
    overhead = max(0.0, per_call_us) / 1e3 / 1.0
    print(f"disabled wrapper cost: {per_call_us:.2f}us/call over the "
          f"empty-batch spec body ({overhead * 100:.3f}% of a 1ms "
          f"dispatch)")
    if overhead >= 0.02:
        print("FAIL: disabled DAS dispatch overhead >= 2%")
        return 1
    return 0


def main() -> int:
    spec = _spec()
    print("preparing material (cells + multiproofs)...")
    material = _material(spec)
    failures = 0
    failures += pairing_census(spec, material)
    failures += recovery_margin(spec, material)
    failures += disabled_overhead(spec, material)
    # telemetry surface sanity: the das.* series exist and are exported
    from consensus_specs_tpu.obs import export
    snap = export.snapshot()
    export.assert_schema(snap, require_nonempty=("das.verify",
                                                 "das.recover"))
    print("obs snapshot: das.* series exported + schema-checked")
    if failures:
        print(f"\nbench-das-smoke: {failures} FAILURE(S)")
        return 1
    print("\nbench-das-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
