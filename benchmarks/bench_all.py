"""Extended benchmark matrix (BASELINE.md configs #1-#5).

``bench.py`` at the repo root stays the driver's single-line entry
(config #1).  This harness measures the full matrix and prints one JSON
line per config.  Python baselines are warmed and repeated (VERDICT r2
methodology fix).

Usage: python benchmarks/bench_all.py [--configs 1,2,3,4,5] [--validators N]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from consensus_specs_tpu.utils.jax_env import (  # noqa: E402
    setup_compile_cache, ensure_working_backend)
setup_compile_cache()
# Never hang the matrix on a dead accelerator tunnel: probe the backend
# in a killable subprocess and fall back to host CPU (same guard as
# bench.py / __graft_entry__; the container's sitecustomize overrides a
# plain JAX_PLATFORMS=cpu, so the forced-CPU path is the only reliable
# opt-out).
ensure_working_backend()


def _timeit(fn, reps=3, warmup=1):
    from consensus_specs_tpu.utils import bls
    for _ in range(warmup):
        fn()
    t0 = time.time()
    for _ in range(reps):
        # time pairings, not dict hits: identical signatures across reps
        # would otherwise be served by the verification memo
        bls.clear_verify_memo()
        fn()
    return (time.time() - t0) / reps


def _want_jax() -> bool:
    """Measure the JAX path only when an accelerator is live (or forced):
    on a bare 1-core CPU it pays minutes of XLA compile for sub-oracle
    throughput, and the native C backend is the production CPU path."""
    if os.environ.get("CS_TPU_BENCH_JAX") == "1":
        return True
    from consensus_specs_tpu.utils.jax_env import accelerator_cached
    return accelerator_cached()


def bench_fast_aggregate_verify(batch=16, n_keys=64):
    """Config #1: batched FastAggregateVerify vs warmed py oracle.
    Measures the native C backend (the CPU production path) and, when an
    accelerator is live, the batched JAX pipeline; reports the faster."""
    from consensus_specs_tpu.utils import bls

    bls.use_py()
    msg = b"bench-attestation-root"
    sks = list(range(1, 1 + n_keys))
    pks = [bls.SkToPk(sk) for sk in sks]
    agg = bls.Aggregate([bls.Sign(sk, msg) for sk in sks])

    py_per_verify = _timeit(
        lambda: bls.FastAggregateVerify(pks, msg, agg), reps=3, warmup=1)

    results = {}
    from consensus_specs_tpu.ops import native_bls
    if native_bls.available():
        bls.use_native()
        dt = _timeit(lambda: bls.FastAggregateVerify(pks, msg, agg), reps=3)
        results["native"] = 1.0 / dt
        bls.use_py()
    if _want_jax():
        from consensus_specs_tpu.ops import bls_jax
        items = [(pks, msg, agg)] * batch
        assert all(bls_jax.verify_aggregates_batch(items))
        dt = _timeit(lambda: bls_jax.verify_aggregates_batch(items), reps=3)
        results["jax"] = batch / dt
    if not results:
        results["py"] = 1.0 / py_per_verify
    best = max(results, key=results.get)
    per_sec = results[best]
    out = {"metric": f"FastAggregateVerify ({n_keys} pubkeys, batch {batch})",
           "value": round(per_sec, 3), "unit": "aggverify/s",
           "vs_baseline": round(per_sec * py_per_verify, 2),
           "backend": best}
    for name, v in results.items():
        out[f"{name}_per_sec"] = round(v, 3)
    return out


def _build_block_with_attestations(spec, state, max_atts):
    from consensus_specs_tpu.test_infra.attestations import (
        get_valid_attestation)
    from consensus_specs_tpu.test_infra.block import build_empty_block
    from consensus_specs_tpu.test_infra import block as blk

    target_slot = state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY
    attestations = []
    # attestations for every committee of the eligible slot, duplicated up
    # to the cap (duplicates are valid blocks-wise and keep the crypto load
    # at MAX_ATTESTATIONS without an epoch-long build-up)
    committees = spec.get_committee_count_per_slot(
        state, spec.get_current_epoch(state))
    base = [get_valid_attestation(spec, state, state.slot, index=i,
                                  signed=True)
            for i in range(committees)]
    while len(attestations) < max_atts:
        attestations.extend(base[:max_atts - len(attestations)])
    block = build_empty_block(spec, state, target_slot)
    for att in attestations:
        block.body.attestations.append(att)
    return blk.state_transition_and_sign_block(spec, state.copy(), block), \
        block


def bench_process_block(n_validators=2048, max_atts=None):
    """Config #2: process_block wall-clock with a full attestation load,
    jax backend vs warmed py backend."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    from consensus_specs_tpu.utils import bls

    spec = build_spec("phase0", "mainnet")
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * n_validators,
        spec.MAX_EFFECTIVE_BALANCE)
    if max_atts is None:
        max_atts = spec.MAX_ATTESTATIONS
    # fixture signing: any backend produces identical (deterministic)
    # signatures; the native library builds the 128-attestation block in
    # seconds where the oracle needs minutes
    from consensus_specs_tpu.ops import native_bls
    bls.use_native() if native_bls.available() else bls.use_py()
    signed_block, _ = _build_block_with_attestations(spec, state, max_atts)
    bls.use_py()

    def run(backend):
        backend()
        work_state = state.copy()
        spec.process_slots(work_state, signed_block.message.slot)
        t0 = time.time()
        spec.process_block(work_state, signed_block.message)
        return time.time() - t0

    py_dt = run(bls.use_py)
    results = {}
    if native_bls.available():
        run(bls.use_native)  # warm decode caches
        results["native"] = min(run(bls.use_native), run(bls.use_native))
    if _want_jax():
        run(bls.use_jax)  # compile
        results["jax"] = min(run(bls.use_jax), run(bls.use_jax))
    if not results:
        results["py"] = py_dt
    best = min(results, key=results.get)
    dt = results[best]
    out = {"metric": f"process_block ({max_atts} attestations, "
                     f"{n_validators} validators)",
           "value": round(dt, 3), "unit": "s/block",
           "vs_baseline": round(py_dt / dt, 2), "backend": best}
    for name, v in results.items():
        out[f"{name}_s"] = round(v, 3)
    return out


def bench_sync_aggregate():
    """Config #3: altair process_sync_aggregate (512 pubkeys, mainnet)."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    from consensus_specs_tpu.test_infra.sync_committee import (
        compute_aggregate_sync_committee_signature, compute_committee_indices)
    from consensus_specs_tpu.test_infra.block import next_slot
    from consensus_specs_tpu.utils import bls

    spec = build_spec("altair", "mainnet")
    bls.use_py()
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 1024,
        spec.MAX_EFFECTIVE_BALANCE)
    next_slot(spec, state)
    committee_indices = compute_committee_indices(state)
    signature = compute_aggregate_sync_committee_signature(
        spec, state, state.slot - 1, committee_indices)
    aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * spec.SYNC_COMMITTEE_SIZE,
        sync_committee_signature=signature)

    def run():
        spec.process_sync_aggregate(state.copy(), aggregate)

    bls.use_py()
    py_dt = _timeit(run, reps=2, warmup=1)
    results = {}
    from consensus_specs_tpu.ops import native_bls
    if native_bls.available():
        bls.use_native()
        results["native"] = _timeit(run, reps=3, warmup=1)
    if _want_jax():
        bls.use_jax()
        results["jax"] = _timeit(run, reps=3, warmup=1)
    if not results:
        results["py"] = py_dt
    bls.use_py()
    best = min(results, key=results.get)
    dt = results[best]
    out = {"metric": "process_sync_aggregate (512 pubkeys, mainnet)",
           "value": round(dt, 3), "unit": "s/op",
           "vs_baseline": round(py_dt / dt, 2), "backend": best}
    for name, v in results.items():
        out[f"{name}_s"] = round(v, 3)
    return out


def bench_epoch_replay(n_validators=4096, slots=8):
    """Config #5 (scaled): slots of state_transition incl. epoch boundary.
    Hash/merkleization bound; BLS disabled like the reference's fastest
    path comparison."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    from consensus_specs_tpu.test_infra.block import (
        build_empty_block_for_next_slot, state_transition_and_sign_block)
    from consensus_specs_tpu.utils import bls

    spec = build_spec("phase0", "minimal")
    bls.bls_active = False
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * n_validators,
        spec.MAX_EFFECTIVE_BALANCE)
    t0 = time.time()
    for _ in range(slots):
        block = build_empty_block_for_next_slot(spec, state)
        state_transition_and_sign_block(spec, state, block)
    dt = time.time() - t0
    bls.bls_active = True
    return {"metric": f"epoch replay ({slots} slots, {n_validators} "
                      "validators, bls off)",
            "value": round(dt, 3), "unit": "s/epoch", "vs_baseline": 1.0}


# validator counts for the config #5 loop-vs-vectorized engine
# comparison (overridden by --epoch-shapes)
_EPOCH_SHAPES = [16384]


def _synthetic_registry_state(spec, n_validators, seed=5):
    """A mainnet-shaped altair state at epoch 3 with ``n_validators``
    active validators: fabricated pubkeys (the epoch path never reads
    them), ~2% slashed, a few low-balance validators, ~75% full
    participation.  Built directly (no deposits, no real keys) so the
    1M-validator shape is constructible in seconds, not hours."""
    import random as _random
    rng = _random.Random(seed)
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    increment = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    far = int(spec.FAR_FUTURE_EPOCH)
    epoch = 3
    Validator = spec.Validator
    filler = b"\xaa" * 42
    validators, balances, participation, scores = [], [], [], []
    for i in range(n_validators):
        slashed = rng.random() < 0.02
        eff = max_eb if rng.random() > 0.05 else max_eb - increment
        validators.append(Validator(
            pubkey=i.to_bytes(6, "little") + filler,
            effective_balance=eff,
            slashed=slashed,
            exit_epoch=far,
            withdrawable_epoch=epoch + rng.randrange(1, 16) if slashed
            else far,
        ))
        balances.append(eff + rng.randrange(0, increment))
        participation.append(7 if rng.random() < 0.75 else rng.randrange(8))
        scores.append(0 if rng.random() < 0.9 else rng.randrange(1, 20))
    state = spec.BeaconState(
        slot=epoch * int(spec.SLOTS_PER_EPOCH),
        validators=validators, balances=balances,
        previous_epoch_participation=participation,
        current_epoch_participation=participation,
        inactivity_scores=scores,
    )
    state.finalized_checkpoint.epoch = 1    # recent finality: no leak
    # warm the registry subtree memo: production merkleizes the state
    # every slot (process_slot state-root caching), so by any epoch
    # boundary the validators root is already cached — a freshly built
    # synthetic registry must not charge that first-ever merkleization
    # to either engine
    from consensus_specs_tpu.utils.ssz import hash_tree_root
    hash_tree_root(state.validators)
    return state


def _bench_epoch_engine_at(n_validators):
    """One shape of the loop-vs-vectorized comparison: altair
    ``process_rewards_and_penalties`` (the participation-flag path that
    carries bellatrix..eip7594 by inheritance) through the per-validator
    spec loop vs the columnar engine.  ``vec_cold_s`` includes the
    once-per-epoch snapshot extraction; ``vec_warm_s`` is the
    steady-state cost with the snapshot amortized across the five epoch
    stages (and unchanged registries)."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.ops import epoch_kernels as ek
    from consensus_specs_tpu.utils.ssz import hash_tree_root

    spec = build_spec("altair", "mainnet")
    state = _synthetic_registry_state(spec, n_validators)
    s_loop = state.copy()

    ek.use_loops()
    t0 = time.time()
    spec.process_rewards_and_penalties(s_loop)
    loop_s = time.time() - t0

    ek.use_vectorized()
    t0 = time.time()
    spec.process_rewards_and_penalties(state)
    vec_cold_s = time.time() - t0
    # differential check rides every bench run: same pre-state, both
    # engines, identical post-balances root
    assert hash_tree_root(state.balances) == hash_tree_root(s_loop.balances)

    warm = []
    for _ in range(3):
        t0 = time.time()
        spec.process_rewards_and_penalties(state)
        warm.append(time.time() - t0)
    vec_warm_s = min(warm)
    ek.use_auto()
    return {"validators": n_validators, "loop_s": round(loop_s, 3),
            "vec_cold_s": round(vec_cold_s, 3),
            "vec_warm_s": round(vec_warm_s, 4),
            "speedup_cold": round(loop_s / vec_cold_s, 1),
            "speedup_warm": round(loop_s / vec_warm_s, 1)}


def bench_epoch_transition():
    """Config #5: the BASELINE epoch-replay metric (now running through
    the vectorized engine by default) plus the explicit loop-vs-
    vectorized ``process_rewards_and_penalties`` comparison at the
    --epoch-shapes registry sizes."""
    out = bench_epoch_replay()
    out["engine"] = [_bench_epoch_engine_at(n) for n in _EPOCH_SHAPES]
    return out


def bench_blob_batch(n_blobs=6):
    """Config #4: deneb ``verify_blob_kzg_proof_batch`` over 6 blobs
    (mainnet setup) vs serial per-blob verification.  The batch path is
    the spec's random-linear-combination optimization - two MSMs and ONE
    pairing check for the whole batch vs one pairing per blob
    (``specs/deneb/polynomial-commitments.md`` verify_blob_kzg_proof_batch)."""
    import random as _random
    from consensus_specs_tpu.ops import kzg as K

    setup = K.trusted_setup("mainnet")
    width = setup.FIELD_ELEMENTS_PER_BLOB
    rng = _random.Random(4)
    blobs = [b"".join((rng.randrange(K.BLS_MODULUS)).to_bytes(32, "big")
                      for _ in range(width)) for _ in range(n_blobs)]
    commitments = [K.blob_to_kzg_commitment(b, setup) for b in blobs]
    proofs = [K.compute_blob_kzg_proof(b, c, setup)
              for b, c in zip(blobs, commitments)]

    def serial():
        assert all(K.verify_blob_kzg_proof(b, c, p, setup)
                   for b, c, p in zip(blobs, commitments, proofs))

    def batched():
        assert K.verify_blob_kzg_proof_batch(
            blobs, commitments, proofs, setup)

    serial_dt = _timeit(serial, reps=2, warmup=1)
    batch_dt = _timeit(batched, reps=2, warmup=1)
    return {"metric": f"verify_blob_kzg_proof_batch ({n_blobs} blobs, "
                      "mainnet)",
            "value": round(batch_dt, 3), "unit": "s/batch",
            "vs_baseline": round(serial_dt / batch_dt, 2)}


CONFIGS = {
    "1": bench_fast_aggregate_verify,
    "2": bench_process_block,
    "3": bench_sync_aggregate,
    "4": bench_blob_batch,
    "5": bench_epoch_transition,
}


def main():
    global _EPOCH_SHAPES
    parser = argparse.ArgumentParser()
    parser.add_argument("--configs", default="1,2,3,4,5")
    parser.add_argument("--epoch-shapes", default="16384",
                        help="comma-separated validator counts for the "
                             "config #5 loop-vs-vectorized epoch-engine "
                             "comparison (e.g. 16384,262144,1048576)")
    parser.add_argument("--profile", action="store_true",
                        help="print the per-stage span breakdown "
                             "(utils/profiling) after each config")
    ns = parser.parse_args()
    _EPOCH_SHAPES = [int(s) for s in ns.epoch_shapes.split(",")]
    if ns.profile:
        from consensus_specs_tpu.utils import profiling
        profiling.enable()
    for key in ns.configs.split(","):
        if ns.profile:
            from consensus_specs_tpu.utils import profiling
            profiling.reset()
        result = CONFIGS[key.strip()]()
        print(json.dumps(result), flush=True)
        if ns.profile:
            print(json.dumps({"config": key.strip(),
                              "stages": profiling.stats()}), flush=True)


if __name__ == "__main__":
    main()
