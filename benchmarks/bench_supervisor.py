"""Supervisor smoke: breaker lifecycle census + enabled-path overhead
(``make bench-supervisor-smoke``).

Two asserted claims back the supervisor's shipping default (on):

1. **Demote / re-promote census** — driving counted fallbacks through a
   real engine entry point (``merkle.hash_rows``) must walk the breaker
   through its full lifecycle with exact counter evidence: threshold
   trips -> ``closed->open`` (one transition, skips while open, the
   skip serving byte-identical scalar digests), backoff expiry ->
   ``open->half_open`` probe, probe success -> ``half_open->closed``.
   A corrupt-mode schedule under rate-1 audits must then quarantine the
   site: one failed audit, one quarantine, one artifact.  The telemetry
   snapshot is schema-checked with ``supervisor.*`` required non-empty.

2. **Enabled overhead** — with the supervisor ON (the default) but no
   faults, audits, or deadlines armed, the added per-dispatch cost
   across the engine stack must stay under 2% of the 32-slot replay —
   the same bound and census-times-per-op-cost discipline as
   ``bench_obs_overhead.py`` (wall-clock A/B of a ~1s python workload
   is noise at this scale; the decomposition is exact).

Exits nonzero on any census mismatch or when the computed overhead
reaches 2%.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLOTS = 32
VALIDATORS = 256
REPS = 3


def _best_of(fn, reps=3) -> float:
    return min(fn() for _ in range(reps))


# ---------------------------------------------------------------------------
# 1. breaker lifecycle census
# ---------------------------------------------------------------------------

def lifecycle_census() -> dict:
    import numpy as np
    from consensus_specs_tpu import faults, supervisor
    from consensus_specs_tpu.obs import registry
    from consensus_specs_tpu.test_infra.metrics import counting
    from consensus_specs_tpu.utils.ssz import merkle

    site = "merkle.dispatch"
    knobs = {"CS_TPU_BREAKER_THRESHOLD": "2",
             "CS_TPU_BREAKER_WINDOW_MS": "60000",
             "CS_TPU_BREAKER_BACKOFF_MS": "5",
             "CS_TPU_BREAKER_BACKOFF_MAX_MS": "5"}
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    rows = np.arange(16 * 64, dtype=np.uint8).reshape(16, 64)
    golden = merkle._hash_rows_scalar(rows)
    try:
        supervisor.reset()
        with counting() as delta:
            # two injected fallbacks = the threshold: breaker opens
            schedule = faults.FaultSchedule({site: [1, 2]})
            with faults.injected(schedule):
                for _ in range(2):
                    out = merkle.hash_rows(rows)
                    assert np.array_equal(out, golden)
            assert schedule.fully_fired(), "injection schedule leaked"
            assert supervisor.states()[site] == "open", \
                f"breaker not open after threshold trips: " \
                f"{supervisor.states()[site]}"
            # demoted: the next dispatch is skipped onto the scalar
            # path, byte-identical
            out = merkle.hash_rows(rows)
            assert np.array_equal(out, golden)
            # backoff expiry: the next call is the half-open probe and
            # its success re-promotes the engine
            time.sleep(0.05)
            out = merkle.hash_rows(rows)
            assert np.array_equal(out, golden)
            assert supervisor.states()[site] == "closed", \
                "probe success did not re-close the breaker"
        demote = {
            "fallbacks_injected": delta[
                "merkle.fallbacks{reason=injected}"],
            "opened": delta[f"supervisor.transitions{{site={site},to=open}}"],
            "skips": delta[f"supervisor.breaker.skips{{site={site}}}"],
            "half_open": delta[
                f"supervisor.transitions{{site={site},to=half_open}}"],
            "closed": delta[
                f"supervisor.transitions{{site={site},to=closed}}"],
        }
        expected = {"fallbacks_injected": 2, "opened": 1, "skips": 1,
                    "half_open": 1, "closed": 1}
        assert demote == expected, f"lifecycle census {demote} != {expected}"

        # quarantine: persistent silent corruption under rate-1 audits
        os.environ["CS_TPU_AUDIT_RATE"] = "1"
        supervisor.reset()
        dumped = []
        try:
            with supervisor.quarantine_hook(
                    lambda s, d: dumped.append((s, d)) or "bench"):
                with counting() as delta:
                    schedule = faults.FaultSchedule(corrupt={site: [1]})
                    with faults.injected(schedule):
                        out = merkle.hash_rows(rows)
            assert np.array_equal(out, golden), \
                "audit did not serve the authoritative scalar digests"
            assert supervisor.states()[site] == "quarantined"
            assert delta[f"supervisor.audits{{result=fail,site={site}}}"] \
                == 1
            assert delta[f"supervisor.quarantines{{site={site}}}"] == 1
            assert dumped and dumped[0][0] == site
        finally:
            os.environ.pop("CS_TPU_AUDIT_RATE", None)

        from consensus_specs_tpu.obs import export
        export.assert_schema(export.snapshot(),
                             require_nonempty=("supervisor.",))
        quarantine = {
            "audit_fails": 1, "quarantines": 1,
            "artifact_hook_fired": bool(dumped),
        }
        registry.reset("supervisor")
        return {"demote_repromote": demote, "quarantine": quarantine}
    finally:
        supervisor.reset()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# 2. enabled-path overhead on the 32-slot replay
# ---------------------------------------------------------------------------

def _per_op_ns(fn, n=200_000) -> float:
    def one():
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n * 1e9
    return _best_of(one)


def _fresh_replay_args():
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.tools.obs_report import build_state
    spec = build_spec("phase0", "minimal")
    return spec, build_state(spec, VALIDATORS)


def _census() -> dict:
    """Supervisor API calls one replay performs, counted by patching
    the module functions (the timed replays run unpatched)."""
    from consensus_specs_tpu import supervisor
    from consensus_specs_tpu.tools.obs_report import replay

    counts = {"admit": 0, "note_success": 0, "audit_due": 0,
              "deadline_scope": 0}
    originals = {name: getattr(supervisor, name) for name in counts}

    def _wrap(name, orig):
        def counted(*args, **kwargs):
            counts[name] += 1
            return orig(*args, **kwargs)
        return counted

    spec, state = _fresh_replay_args()
    supervisor.reset()
    for name, orig in originals.items():
        setattr(supervisor, name, _wrap(name, orig))
    try:
        replay(spec, state, SLOTS)
    finally:
        for name, orig in originals.items():
            setattr(supervisor, name, orig)
        supervisor.reset()
    return counts


def _timed_replay() -> float:
    from consensus_specs_tpu.tools.obs_report import replay
    spec, state = _fresh_replay_args()
    t0 = time.perf_counter()
    replay(spec, state, SLOTS)
    return time.perf_counter() - t0


def overhead() -> dict:
    from consensus_specs_tpu import supervisor
    supervisor.reset()

    admit_ns = _per_op_ns(lambda: supervisor.admit("merkle.dispatch"))
    note_ns = _per_op_ns(lambda: supervisor.note_success("merkle.dispatch"))
    audit_ns = _per_op_ns(lambda: supervisor.audit_due("merkle.dispatch"))

    def _scope():
        with supervisor.deadline_scope("merkle.dispatch"):
            pass
    scope_ns = _per_op_ns(_scope, n=100_000)

    counts = _census()
    replay_s = min(_timed_replay() for _ in range(REPS))

    overhead_s = (counts["admit"] * admit_ns
                  + counts["note_success"] * note_ns
                  + counts["audit_due"] * audit_ns
                  + counts["deadline_scope"] * scope_ns) / 1e9
    return {
        "admit_ns": round(admit_ns, 1),
        "note_success_ns": round(note_ns, 1),
        "audit_due_ns": round(audit_ns, 1),
        "deadline_scope_ns": round(scope_ns, 1),
        "calls_per_replay": counts,
        "replay_s": round(replay_s, 4),
        "computed_overhead_s": round(overhead_s, 6),
        "computed_overhead_pct": round(overhead_s / replay_s * 100.0, 3),
    }


def main() -> int:
    from consensus_specs_tpu.utils import bls
    bls.bls_active = False

    lifecycle = lifecycle_census()
    cost = overhead()

    print(json.dumps({
        "metric": f"supervisor lifecycle census + enabled-path overhead, "
                  f"{SLOTS}-slot replay, {VALIDATORS} validators",
        "lifecycle": lifecycle,
        "overhead": cost,
    }), flush=True)

    pct = cost["computed_overhead_pct"]
    assert pct < 2.0, (
        f"supervisor enabled-path overhead {pct:.2f}% >= 2% of the "
        f"{SLOTS}-slot replay")
    return 0


if __name__ == "__main__":
    sys.exit(main())
