"""Block-serving pipeline benchmark (``make bench-serving-smoke``,
CI-wired).

Replays the SAME captured load streams (``sim/load.py`` — equivocating
siblings, ex-ante reorg races) through two lanes of the serving surface:

* **sync control** — ``BlockServer(window=1)`` under ``CS_TPU_SERVING=0``:
  every event through the per-block spec path, per-block signature
  flushes, whole-state ``copy()`` snapshots;
* **pipelined** — ``CS_TPU_SERVING=1``, configured window: window
  batching + cross-block attestation prep, the window's ONE combined
  RLC flush overlapped on the worker lane, chunk-level state clones.

Counter-asserted contracts (nonzero exit on any violation):

1. **byte-identity** — both lanes reduce to the same deep store digest
   (every block's post-state root, every latest message) and report the
   same per-block accept/reject map;
2. **one pairing per window** — the pipelined lane's ``bls.pairings``
   delta equals its ``serving.windows`` delta (the sibling-dedup fold),
   strictly below the sync lane's per-block pairing count;
3. **full pipelined service** — ``serving.blocks{path=pipelined}``
   covers every block, zero ``serving.fallbacks`` either lane;
4. **epoch-commit census under overlap** — the ``state_arrays.commits``
   delta is lane-identical (the flush overlap never double-commits or
   skips a balance-family flush);
5. **throughput** — sustained slots/sec (best-of-reps, aggregated over
   the stream mix) is strictly higher pipelined than sync;
6. **chunk-level snapshot cost** — on a large registry (mainnet preset,
   1M validators in the BENCHMARKS configuration), ``clone_state``
   beats ``state.copy()`` while staying root-identical, including after
   divergent mutation of both snapshots.

p50/p99 block-ingest latency comes from the ``serving.ingest_latency``
histogram; the pipelined lane trades per-block latency (blocks wait for
their window barrier) for throughput, so latency is reported, not
bounded.  ``--smoke`` is the CI shape; the full shape
(``--clone-validators 1048576`` with ``make warm`` caches) is the
BENCHMARKS.md configuration.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_state_arrays import build_state  # noqa: E402


def _run_lane(spec, stream, serving, window, reps):
    """Best-of-reps replay of one stream through one lane.  Returns the
    lane record: wall time, counter deltas, latency quantiles, digest,
    per-block results (digest/results asserted rep-stable)."""
    from consensus_specs_tpu.obs import registry as obs_registry
    from consensus_specs_tpu.serving import BlockServer
    from consensus_specs_tpu.sim import load
    from consensus_specs_tpu.test_infra.metrics import counting
    from consensus_specs_tpu.utils import bls

    os.environ["CS_TPU_SERVING"] = "1" if serving else "0"
    best = None
    for _ in range(reps):
        bls.clear_verify_memo()         # real pairings every rep
        obs_registry.reset("serving.")
        store = load.anchor_store(spec, stream)
        server = BlockServer(spec, store, window=window)
        t0 = time.perf_counter()
        with counting() as delta:
            results = load.serve(server, stream)
        wall = time.perf_counter() - t0
        hist = obs_registry.metrics()["serving.ingest_latency"].value()
        digest = load.store_digest(spec, store)
        if best is not None:
            assert digest == best["digest"], \
                f"{stream.name}: digest drifted across reps"
            assert results == best["results"], \
                f"{stream.name}: per-block results drifted across reps"
        if best is None or wall < best["wall_s"]:
            best = {"wall_s": wall, "delta": delta, "digest": digest,
                    "results": results,
                    "p50": hist["p50"], "p99": hist["p99"]}
    return best


def _clone_phase(preset, n, reps):
    """Chunk-level snapshot vs whole-state copy on a large registry:
    cost ratio plus a divergent-mutation root differential."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.serving import clone_state
    from consensus_specs_tpu.utils.ssz import hash_tree_root

    spec = build_spec("altair", preset)
    t0 = time.perf_counter()
    state = build_state(spec, n)
    build_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    base_root = bytes(hash_tree_root(state))    # warm tree + root memo
    merkle_s = time.perf_counter() - t0

    t_copy = min(_timed(state.copy) for _ in range(reps))
    t_clone = min(_timed(lambda: clone_state(state)) for _ in range(reps))

    # byte-identity, including off the memoized-root happy path: mutate
    # both snapshots the same way (a fast field and a lazy field) and
    # demand they re-merkleize to the same NEW root, source untouched
    ref, cl = state.copy(), clone_state(state)
    for st in (ref, cl):
        st.balances[1] = st.balances[1] + 7
        st.validators[0].effective_balance = \
            st.validators[0].effective_balance + 1
    ref_root = bytes(hash_tree_root(ref))
    assert bytes(hash_tree_root(cl)) == ref_root, \
        "mutated chunk-level clone diverged from mutated full copy"
    assert ref_root != base_root
    assert bytes(hash_tree_root(state)) == base_root, \
        "cloning/mutating snapshots disturbed the source state"
    return {
        "preset": preset, "validators": n,
        "build_s": round(build_s, 3), "merkle_s": round(merkle_s, 3),
        "copy_s": round(t_copy, 5), "clone_s": round(t_clone, 5),
        "clone_speedup": round(t_copy / t_clone, 1) if t_clone else None,
    }


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--reps", type=int, default=3,
                    help="replays per lane per stream (best-of)")
    ap.add_argument("--window", type=int, default=8,
                    help="pipelined window depth (deeper than the engine "
                         "default: more blocks per fold widens the "
                         "throughput margin; 0 = CS_TPU_SERVING_WINDOW)")
    ap.add_argument("--clone-preset", default="mainnet")
    ap.add_argument("--clone-validators", type=int, default=1 << 20,
                    help="registry size for the snapshot-cost phase")
    ap.add_argument("--smoke", action="store_true",
                    help="small CI shape + counter asserts")
    args = ap.parse_args()
    if args.smoke:
        args.clone_validators = 1 << 16
        args.reps = 3

    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.obs import export
    from consensus_specs_tpu.sim import load
    from consensus_specs_tpu.utils import bls

    # real signatures through the fastest backend: the pairing census
    # (windows == pairings) is the point of the pipeline
    bls.use_fastest()
    bls.bls_active = True
    spec = build_spec("phase0", "minimal")

    streams = [load.generate(spec, seed=args.seed, name=name)
               for name in load.DEFAULT_MIX]
    serving_prev = os.environ.get("CS_TPU_SERVING")

    lanes, total = {}, {}
    try:
        for lane, serving, window in (("sync", False, 1),
                                      ("pipelined", True, args.window)):
            per_stream = []
            for stream in streams:
                rec = _run_lane(spec, stream, serving, window, args.reps)
                rec["stream"] = stream.describe()
                per_stream.append(rec)
            wall = sum(r["wall_s"] for r in per_stream)
            slots = sum(s.result.slots for s in streams)
            lanes[lane] = per_stream
            total[lane] = {
                "wall_s": round(wall, 3),
                "slots_per_s": round(slots / wall, 1) if wall else None,
                "p50_ms": round(max(r["p50"] for r in per_stream) * 1e3, 3),
                "p99_ms": round(max(r["p99"] for r in per_stream) * 1e3, 3),
            }
    finally:
        if serving_prev is None:
            os.environ.pop("CS_TPU_SERVING", None)
        else:
            os.environ["CS_TPU_SERVING"] = serving_prev

    clone = _clone_phase(args.clone_preset, args.clone_validators, args.reps)

    snap = export.snapshot()
    export.assert_schema(snap, require_nonempty=("serving.",))
    result = {
        "metric": "block-serving pipeline",
        "seed": args.seed, "reps": args.reps,
        "streams": [s.describe() for s in streams],
        "blocks": sum(s.n_blocks for s in streams),
        "slots": sum(s.result.slots for s in streams),
        "bls_backend": bls.backend_name(),
        "lanes": {
            lane: [{k: (round(v, 4) if isinstance(v, float) else v)
                    for k, v in r.items()
                    if k in ("stream", "wall_s", "p50", "p99")}
                   for r in recs]
            for lane, recs in lanes.items()},
        "total": total,
        "speedup": round(total["sync"]["wall_s"]
                         / total["pipelined"]["wall_s"], 2),
        "clone": clone,
    }
    print(json.dumps(result), flush=True)

    # the census guarantees (the smoke's reason to exist)
    for i, stream in enumerate(streams):
        sync, pipe = lanes["sync"][i], lanes["pipelined"][i]
        assert sync["digest"] == pipe["digest"], \
            f"{stream.name}: lane stores diverged"
        assert sync["results"] == pipe["results"], \
            f"{stream.name}: per-block verdicts diverged"
        ds, dp = sync["delta"], pipe["delta"]
        assert ds["serving.blocks{path=sync}"] == stream.n_blocks, \
            f"{stream.name}: sync lane missed blocks: {ds.nonzero()}"
        assert ds["serving.windows"] == 0
        assert dp["serving.blocks{path=pipelined}"] == stream.n_blocks, \
            f"{stream.name}: pipelined lane fell back: {dp.nonzero()}"
        assert dp["serving.blocks{path=sync}"] == 0
        for delta, lane in ((ds, "sync"), (dp, "pipelined")):
            fb = sum(v for k, v in delta.items()
                     if k.startswith("serving.fallbacks"))
            assert fb == 0, \
                f"{stream.name}/{lane}: unexpected fallbacks: " \
                f"{delta.nonzero()}"
        # one pairing per window (sibling/cross-block dedup): the sync
        # lane pays one flush pairing per accepted block
        windows = dp["serving.windows"]
        assert windows > 0
        assert dp["bls.pairings"] == windows, \
            f"{stream.name}: pairing census broke: " \
            f"{dp['bls.pairings']} pairings != {windows} windows"
        assert ds["bls.pairings"] > dp["bls.pairings"], \
            f"{stream.name}: window fold saved no pairings " \
            f"({ds['bls.pairings']} vs {dp['bls.pairings']})"
        assert ds["state_arrays.commits"] == dp["state_arrays.commits"], \
            f"{stream.name}: epoch-commit census diverged under overlap"
    assert total["pipelined"]["slots_per_s"] > total["sync"]["slots_per_s"], \
        f"pipelined lane not faster: {total}"
    assert clone["clone_speedup"] and clone["clone_speedup"] > 1.0, \
        f"chunk-level clone slower than state.copy(): {clone}"


if __name__ == "__main__":
    main()
