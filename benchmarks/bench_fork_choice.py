"""Fork-choice engine benchmark (``make bench-forkchoice-smoke`` runs
the counter-asserted smoke shape in CI).

Shape: N validators x M blocks (a deep multi-branch tree) x an
attestation-churn stream.  Each round moves a slice of the validators'
latest messages to new tips and recomputes the head twice — once
through the incremental proto-array engine
(``forkchoice/proto_array.py``), once through the spec loop — asserting
byte-identical heads.  The spec loop pays O(blocks x validators) per
recompute; the engine pays one columnar delta pass + one O(#nodes)
sweep.

Blocks are registered synthetically (no state transitions): this
isolates fork-choice cost, the thing being measured.  The differential
property is still enforced on every verified round, and in ``--smoke``
mode the engine-hit counters must show the proto path really answered
(ZERO fallbacks) or the process exits nonzero.
"""
import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_store(spec, n_validators):
    from consensus_specs_tpu.utils.ssz import hash_tree_root
    state = spec.BeaconState()
    v = spec.Validator(
        effective_balance=spec.MAX_EFFECTIVE_BALANCE,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH)
    for i in range(n_validators):
        v.pubkey = i.to_bytes(8, "little") * 6
        state.validators.append(v)
        state.balances.append(spec.MAX_EFFECTIVE_BALANCE)
    anchor_block = spec.BeaconBlock(state_root=hash_tree_root(state))
    store = spec.get_forkchoice_store(state, anchor_block)
    return store, bytes(hash_tree_root(anchor_block))


def register_block(spec, store, block, root):
    """The on_block bookkeeping without the state transition: the block
    joins blocks/timeliness/unrealized-justifications, the children
    index, and the proto array."""
    store.blocks[root] = block
    store.block_states[root] = store.block_states[
        bytes(store.justified_checkpoint.root)]
    store.block_timeliness[root] = True
    store.unrealized_justifications[root] = \
        store.justified_checkpoint.copy()
    store._fc_children.setdefault(bytes(block.parent_root), []).append(root)
    store._fc_children_n = len(store.blocks)
    eng = getattr(store, "_fc_proto", None)
    if eng is not None:
        eng.note_block(spec, store, root)


def build_tree(spec, store, anchor_root, n_blocks, branches, rng):
    """``branches`` chains forking off the anchor, round-robin extended
    to ``n_blocks`` total — a deep tree with a branching point at the
    base (the worst case for the spec loop's per-level get_weight)."""
    from consensus_specs_tpu.utils.ssz import hash_tree_root
    tips = [(anchor_root, 0)] * branches
    blocks = []
    for i in range(n_blocks):
        b = i % branches
        parent_root, parent_slot = tips[b]
        block = spec.BeaconBlock(
            slot=parent_slot + 1,
            proposer_index=rng.randrange(16),
            parent_root=parent_root,
            state_root=i.to_bytes(32, "little"))
        root = bytes(hash_tree_root(block))
        register_block(spec, store, block, root)
        tips[b] = (root, parent_slot + 1)
        blocks.append(root)
    store.time = (store.genesis_time
                  + int(spec.config.SECONDS_PER_SLOT)
                  * (max(s for _, s in tips) + int(spec.SLOTS_PER_EPOCH)))
    return blocks, [r for r, _ in tips]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--validators", type=int, default=131072)
    ap.add_argument("--blocks", type=int, default=128)
    ap.add_argument("--branches", type=int, default=2)
    ap.add_argument("--rounds", type=int, default=10,
                    help="attestation-churn head recomputes (proto)")
    ap.add_argument("--spec-rounds", type=int, default=2,
                    help="rounds also measured+verified via the spec loop")
    ap.add_argument("--churn", type=int, default=None,
                    help="validators re-voting per round (default N/64)")
    ap.add_argument("--assert-speedup", type=float, default=None,
                    help="fail unless proto is at least this many times "
                         "faster per head recompute")
    ap.add_argument("--smoke", action="store_true",
                    help="small CI shape + engine-hit counter asserts")
    args = ap.parse_args()
    if args.smoke:
        args.validators, args.blocks, args.branches = 4096, 48, 3
        args.rounds, args.spec_rounds = 6, 6
    churn = args.churn or max(1, args.validators // 64)

    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.forkchoice import proto_array
    from consensus_specs_tpu.utils import bls
    bls.bls_active = False
    spec = build_spec("phase0", "minimal")
    rng = random.Random(1337)

    t0 = time.time()
    store, anchor_root = build_store(spec, args.validators)
    assert store._fc_proto is not None, \
        "proto engine not attached (CS_TPU_PROTO_ARRAY=0?)"
    blocks, tips = build_tree(spec, store, anchor_root, args.blocks,
                              args.branches, rng)
    # every validator votes some block in the deeper half of the tree
    vote_pool = blocks[len(blocks) // 2:]
    for i in range(args.validators):
        store.latest_messages[i] = spec.LatestMessage(
            epoch=1, root=rng.choice(vote_pool))
    store._fc_proto.note_votes(range(args.validators))
    setup_s = time.time() - t0

    proto_array.reset_stats()
    # same boundary for the cache counters the result embeds — without
    # this the emitted hit/miss ratios would be dominated by the
    # build_store/build_tree setup traffic, not the measured rounds
    from consensus_specs_tpu.obs import registry as obs_registry
    obs_registry.reset("cache.")
    proto_s = spec_s = 0.0
    spec_measured = 0
    for r in range(args.rounds):
        movers = rng.sample(range(args.validators), churn)
        for i in movers:
            store.latest_messages[i] = spec.LatestMessage(
                epoch=2 + r, root=rng.choice(vote_pool))
        store._fc_proto.note_votes(movers)
        proto_array.use_proto()
        t0 = time.time()
        head_proto = bytes(spec.get_head(store))
        proto_s += time.time() - t0
        if r < args.spec_rounds:
            proto_array.use_spec()
            t0 = time.time()
            head_spec = bytes(spec.get_head(store))
            spec_s += time.time() - t0
            spec_measured += 1
            assert head_proto == head_spec, \
                f"round {r}: engines disagree on the head"
        proto_array.use_auto()

    stats = proto_array.stats()
    # telemetry snapshot: schema-valid with non-empty fork-choice path
    # counters (the labeled engine/spec attribution the smoke certifies)
    from consensus_specs_tpu.obs import export
    snap = export.snapshot()
    export.assert_schema(snap, require_nonempty=("forkchoice.",))
    proto_per_head = proto_s / args.rounds
    spec_per_head = spec_s / max(1, spec_measured)
    speedup = spec_per_head / proto_per_head if proto_per_head else 0.0
    result = {
        "metric": "fork-choice head recompute",
        "validators": args.validators, "blocks": args.blocks,
        "branches": args.branches, "churn_per_round": churn,
        "setup_s": round(setup_s, 3),
        "proto_rounds": args.rounds,
        "proto_per_head_s": round(proto_per_head, 6),
        "spec_rounds": spec_measured,
        "spec_per_head_s": round(spec_per_head, 4),
        "speedup": round(speedup, 1),
        "stats": stats,
        "obs": {"metrics": {k: v for k, v in snap["metrics"].items()
                            if k.startswith(("forkchoice.", "cache."))}},
    }
    print(json.dumps(result), flush=True)

    # differential + dispatch guarantees (the smoke's reason to exist)
    assert stats["proto_heads"] == args.rounds, stats
    assert stats["fallbacks"] == 0, f"engine fell back: {stats}"
    assert stats["vote_deltas"] > 0, f"no vote deltas applied: {stats}"
    assert stats["balance_passes"] >= 1, stats
    if args.assert_speedup is not None:
        assert speedup >= args.assert_speedup, \
            f"speedup {speedup:.1f}x below required {args.assert_speedup}x"


if __name__ == "__main__":
    main()
