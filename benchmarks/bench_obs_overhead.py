"""Telemetry disabled-path overhead micro-bench (``make bench-obs-smoke``).

Proves the satellite claim: with both gates unset, the instrumentation
added across the engine stack costs <2% of the 32-slot replay
wall-clock.  Two measurements back this:

1. **Per-op costs** — tight-loop ns/op of a disabled ``span`` enter/exit
   and of a bound counter ``add`` (the only two operations hot paths
   pay when telemetry is off).
2. **Op census** — one instrumented replay counts how many span
   entries and counter bumps a 32-slot replay actually performs (the
   census run patches the series classes; the timed runs are untouched).

overhead% = (spans x span_cost + bumps x add_cost) / replay_time.  This
deterministic decomposition is the asserted bound (<2%); a direct A/B
of the same replay with spans force-disabled vs enabled is printed for
reference but not asserted (wall-clock A/B of a ~1s python workload is
noise at the 2% scale).

The flight recorder (``obs/flight.py``) gets the same treatment: a
tight-loop ns/op of a DISARMED ``flight.record()`` call, a census of
how many records an armed+traced replay emits, and the asserted bound
flight_records x disarmed_cost / replay_time < 2% (the armed span path
checks one module global before even calling ``record``, so this is
the ceiling, not the typical cost).  A final leg proves the armed
recorder is effect-free where it matters: a pipelined serving replay
with flight + tracing armed must produce a store digest byte-identical
to the synchronous ``CS_TPU_SERVING=0`` oracle (``load.sync_digest``).

Exits nonzero when either computed overhead reaches 2% or the armed
digests diverge.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLOTS = 32
# 256 validators: the hashing/transition work in the denominator scales
# with registry size while the span/bump census stays near-constant, so
# the asserted ratio keeps headroom on faster hosts (and better matches
# the production shapes the <2% claim is about)
VALIDATORS = 256
REPS = 3


def _best_of(fn, reps=3) -> float:
    """Per-op costs are measured best-of-N: scheduler noise only ever
    inflates a tight-loop measurement, so the minimum is the estimator
    of the true cost (and keeps the asserted bound flake-free)."""
    return min(fn() for _ in range(reps))


def _per_op_span_ns(n=200_000) -> float:
    from consensus_specs_tpu.obs.tracing import span

    def one():
        t0 = time.perf_counter()
        for _ in range(n):
            with span("bench.noop"):
                pass
        return (time.perf_counter() - t0) / n * 1e9

    return _best_of(one)


def _per_op_add_ns(n=1_000_000) -> float:
    from consensus_specs_tpu.obs import registry
    series = registry.counter("bench.add").labels()
    add = series.add

    def one():
        t0 = time.perf_counter()
        for _ in range(n):
            add()
        return (time.perf_counter() - t0) / n * 1e9

    return _best_of(one)


def _per_op_flight_ns(n=1_000_000) -> float:
    """Disarmed ``flight.record()`` call: one module-global check and
    return.  This is what every always-on evidence site (fault hook,
    breaker transition, window submit) pays with CS_TPU_FLIGHT=0."""
    from consensus_specs_tpu.obs import flight
    assert not flight.is_enabled()
    record = flight.record

    def one():
        t0 = time.perf_counter()
        for _ in range(n):
            record("bench.noop")
        return (time.perf_counter() - t0) / n * 1e9

    return _best_of(one)


def _flight_census() -> int:
    """Flight records one armed+traced replay emits (span enter/exit
    records dominate; the evidence sites add a handful)."""
    from consensus_specs_tpu import obs
    from consensus_specs_tpu.obs import flight, registry, tracing
    from consensus_specs_tpu.tools.obs_report import replay
    spec, state = _fresh_replay_args()
    obs.reset_all()
    flight.enable(True)
    obs.enable(True, counters=False)
    try:
        replay(spec, state, SLOTS)
        # emitted, not retained: the ring caps what record_count() can
        # see, the cumulative counter does not wrap
        return registry.counter("obs.flight.records").total()
    finally:
        obs.enable(False)
        flight.enable(False)
        tracing.reset()
        obs.reset_all()


def _serving_digest_identity() -> dict:
    """Armed-recorder effect-freedom: a pipelined serving replay with
    flight + span tracing on must land the byte-identical store the
    synchronous oracle lands."""
    from consensus_specs_tpu import obs
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.obs import flight
    from consensus_specs_tpu.serving.pipeline import BlockServer
    from consensus_specs_tpu.sim import load
    spec = build_spec("phase0", "minimal")
    stream = load.generate(spec, seed=3, name="equivocation")
    oracle = load.sync_digest(spec, stream)
    obs.reset_all()
    flight.enable(True)
    obs.enable(True, counters=False)
    try:
        server = BlockServer(spec, load.anchor_store(spec, stream),
                             window=3)
        load.serve(server, stream)
        armed = load.store_digest(spec, server.store)
        records = flight.record_count()
    finally:
        obs.enable(False)
        flight.enable(False)
        obs.reset_all()
    return {"oracle": oracle, "armed": armed, "flight_records": records,
            "windows": len(server.window_log)}


def _fresh_replay_args():
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.tools.obs_report import build_state
    spec = build_spec("phase0", "minimal")
    return spec, build_state(spec, VALIDATORS)


def _timed_replay() -> float:
    from consensus_specs_tpu.tools.obs_report import replay
    spec, state = _fresh_replay_args()
    t0 = time.perf_counter()
    replay(spec, state, SLOTS)
    return time.perf_counter() - t0


def _census() -> tuple:
    """(span entries, counter/gauge bump events) one replay performs —
    exact: every live series object is temporarily reclassed so writes
    to its value slot count, which intercepts both ``.add()`` calls and
    the inline ``series.n += 1`` bumps the hottest sites use."""
    from consensus_specs_tpu import obs
    from consensus_specs_tpu.obs import registry, tracing
    from consensus_specs_tpu.tools.obs_report import replay

    bumps = [0]

    def counting_slot(base_cls, slot_name):
        slot = getattr(base_cls, slot_name)

        def _set(self, v):
            bumps[0] += 1
            slot.__set__(self, v)

        return property(slot.__get__, _set)

    class CountingCounter(registry._CounterSeries):
        __slots__ = ()
        n = counting_slot(registry._CounterSeries, "n")

    class CountingGauge(registry._GaugeSeries):
        __slots__ = ()
        v = counting_slot(registry._GaugeSeries, "v")

    swaps = {registry._CounterSeries: CountingCounter,
             registry._GaugeSeries: CountingGauge}
    spec, state = _fresh_replay_args()    # setup excluded from census

    def _reclass(to_counting: bool) -> None:
        for m in registry.metrics().values():
            for _, s in m.series_items():
                if to_counting:
                    target = swaps.get(type(s))
                else:
                    target = {v: k for k, v in swaps.items()}.get(type(s))
                if target is not None:
                    s.__class__ = target

    obs.reset_all()
    obs.enable(True, counters=False)
    _reclass(True)
    try:
        bumps[0] = 0
        replay(spec, state, SLOTS)
    finally:
        _reclass(False)
        obs.enable(False)
    spans = sum(s["count"] for s in tracing.stats().values())
    tracing.reset()
    return spans, bumps[0]


def main() -> int:
    from consensus_specs_tpu import obs
    from consensus_specs_tpu.obs import flight
    from consensus_specs_tpu.utils import bls
    bls.bls_active = False
    # this bench measures the DISABLED path: force both gates off no
    # matter what CS_TPU_PROFILE/CS_TPU_TRACE the caller's shell exports
    # (otherwise the per-op loops would time the enabled tree-insert
    # path and fail the bound spuriously).  The flight recorder is
    # disarmed too: its per-record counter bump would otherwise inflate
    # the census, and its per-op cost is measured disarmed by design.
    obs.enable(False, counters=False)
    flight.enable(False)

    span_ns = _per_op_span_ns()
    add_ns = _per_op_add_ns()
    flight_ns = _per_op_flight_ns()
    spans, bumps = _census()
    flight_records = _flight_census()

    # timed replays, telemetry fully off (the shipping default)
    disabled_s = min(_timed_replay() for _ in range(REPS))

    # reference A/B: same replay with spans recording
    obs.enable(True, counters=False)
    try:
        enabled_s = min(_timed_replay() for _ in range(REPS))
    finally:
        obs.enable(False)
        obs.reset_all()

    overhead_s = (spans * span_ns + bumps * add_ns) / 1e9
    overhead_pct = overhead_s / disabled_s * 100.0
    # flight ceiling: every record an armed+traced replay would emit,
    # priced at the disarmed call cost (the span-site records are in
    # truth gated behind one module-global read, cheaper still)
    flight_overhead_pct = (flight_records * flight_ns / 1e9
                           / disabled_s * 100.0)

    identity = _serving_digest_identity()

    print(json.dumps({
        "metric": f"obs disabled-path overhead, {SLOTS}-slot replay, "
                  f"{VALIDATORS} validators",
        "span_disabled_ns": round(span_ns, 1),
        "counter_add_ns": round(add_ns, 1),
        "flight_disarmed_ns": round(flight_ns, 1),
        "spans_per_replay": spans,
        "counter_bumps_per_replay": bumps,
        "flight_records_per_replay": flight_records,
        "replay_disabled_s": round(disabled_s, 4),
        "replay_profiled_s": round(enabled_s, 4),
        "computed_overhead_s": round(overhead_s, 6),
        "computed_overhead_pct": round(overhead_pct, 3),
        "flight_overhead_pct": round(flight_overhead_pct, 3),
        "serving_digest_identity": identity["oracle"] == identity["armed"],
        "serving_flight_records": identity["flight_records"],
        "serving_windows": identity["windows"],
    }), flush=True)

    assert overhead_pct < 2.0, (
        f"disabled-path telemetry overhead {overhead_pct:.2f}% >= 2% "
        f"of the {SLOTS}-slot replay")
    assert flight_overhead_pct < 2.0, (
        f"disarmed flight-recorder overhead {flight_overhead_pct:.2f}% "
        f">= 2% of the {SLOTS}-slot replay")
    assert identity["oracle"] == identity["armed"], (
        "armed flight+trace serving replay diverged from the "
        f"synchronous oracle: {identity['armed']} != {identity['oracle']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
