"""Mesh-sharded SPMD engine benchmark (``make bench-mesh-smoke``,
CI-wired).  Runs on an 8-way virtual host-device mesh (forced below,
before jax imports) so the census exercises REAL SPMD partitioning —
shard_map programs, NamedSharding placements, psum collectives —
without TPU hardware.

Four counter-asserted contracts:

1. **psum budget** — a full epoch transition runs every sub-transition
   through the SPMD programs with EXACTLY the budgeted collective count
   per sub-transition (``mesh_epoch.PSUM_BUDGET``); the budget itself
   is proven structurally by a jaxpr census over every reduction and
   elementwise program (a program that silently grew a second
   collective fails here, not in a TPU profile);
2. **byte-identity** — state roots are identical across {mesh on, mesh
   off, spec loops} on the same replay;
3. **per-shard kernel scaling** — on 1M-validator columns, the
   shard-local delta-kernel composition at a full-registry span must
   cost >= 6x its 1/8-registry span (near-linear partition: nothing in
   the per-shard work grows with the GLOBAL registry).  On this 1-core
   host the 8 virtual devices timeshare one core, so wall-clock
   speedup is not measurable — the scaling claim is about the
   per-shard WORK, which is what real 8-device hardware divides;
4. **leaf-span merkleization** — the mesh level build of a 64k-chunk
   buffer is byte-identical to the sequential build, levels included.

Exits nonzero on any violation.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the mesh needs addressable devices BEFORE the first jax import; on a
# TPU host the real topology wins, on CPU hosts we force the 8-way
# virtual mesh the CI legs and the multichip dryrun use
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = \
        (_flags + " --xla_force_host_platform_device_count=8").strip()

from bench_state_arrays import build_state  # noqa: E402


def _psum_census(mesh):
    """Structural proof of the collective budget: count psum equations
    in every compiled program family's jaxpr."""
    import jax
    import numpy as np
    from consensus_specs_tpu.parallel import mesh_epoch, mesh_state

    n_dev = mesh.shape[mesh_state.AXIS]
    n = 4 * n_dev
    u64 = lambda: np.zeros(n, dtype=np.uint64)       # noqa: E731
    u8 = lambda: np.zeros(n, dtype=np.uint8)         # noqa: E731
    bl = lambda: np.zeros(n, dtype=bool)             # noqa: E731
    scal8 = np.zeros(8, dtype=np.uint64)

    def count(prog, *args):
        with mesh_state.x64():
            return str(jax.make_jaxpr(prog)(*args)).count("psum")

    census = {
        "altair_sums": count(
            mesh_epoch._p_altair_sums(mesh, 3),
            u64(), u64(), u64(), bl(), u8(), scal8),
        "masked_sums": count(
            mesh_epoch._p_masked_sums(mesh),
            u64(), np.zeros((4, n), dtype=bool)),
        "active_sums": count(
            mesh_epoch._p_active_sums(mesh, 0),
            u64(), u64(), u64(), scal8),
        "shard_stats": count(
            mesh_epoch._p_shard_stats(mesh, 3),
            u64(), u64(), u64()),
        "registry_scan": count(
            mesh_epoch._p_registry_scan(mesh, (2**64 - 1, 32, 16, 256)),
            u64(), u64(), u64(), u64(), scal8),
        "altair_deltas": count(
            mesh_epoch._p_altair_deltas(
                mesh, (False, (14, 26, 14), 64, 10**9, 2, 1)),
            u64(), u64(), u64(), bl(), u64(), u8(), u64(), u64(), scal8),
        "inactivity": count(
            mesh_epoch._p_inactivity(mesh, (4, 16, False, 1)),
            u64(), u64(), bl(), u64(), u8(), u64(), scal8),
        "slashings": count(
            mesh_epoch._p_slashings(mesh, (10**9,)),
            u64(), bl(), u64(), u64(), scal8),
        "eff_balance": count(
            mesh_epoch._p_eff_balance(
                mesh, (10**9, 10**8, 10**8, 32 * 10**9)),
            u64(), u64()),
    }
    assert census["altair_sums"] == 1, census
    assert census["masked_sums"] == 1, census
    assert census["active_sums"] == 1, census
    assert census["registry_scan"] == 1, census
    for name in ("altair_deltas", "inactivity", "slashings",
                 "eff_balance", "shard_stats"):
        assert census[name] == 0, \
            f"elementwise program {name} grew a collective: {census}"
    return census


def _shard_kernel_time(n, iters=3):
    """Wall time of the shard-local altair delta composition (the same
    shared kernels the SPMD program maps) over an ``n``-lane span."""
    import numpy as np
    from consensus_specs_tpu.ops import epoch_kernels as ek

    rng = np.random.default_rng(11)
    eff = rng.integers(1, 33, n, dtype=np.uint64) * np.uint64(10**9)
    balances = eff.copy()
    scores = rng.integers(0, 50, n, dtype=np.uint64)
    eligible = rng.random(n) < 0.95
    parts = [rng.random(n) < 0.7 for _ in range(3)]
    base_reward = (eff // np.uint64(10**9)) * np.uint64(512)
    best = None
    for _ in range(iters):
        t0 = time.perf_counter()
        acc = balances
        for f, w in enumerate((14, 26, 14)):
            r, p = ek.flag_deltas_kernel(
                np, base_reward, eligible, parts[f], weight=w,
                weight_denominator=64, participating_increments=900,
                active_increments=1000, in_leak=False,
                is_head_flag=f == 2)
            acc = ek.apply_deltas_kernel(np, acc, r, p)
        inact = ek.inactivity_penalty_kernel(
            np, eff, scores, eligible, parts[1],
            denominator=4 * 3 * 10**7)
        acc = ek.apply_deltas_kernel(
            np, acc, np.zeros(n, dtype=np.uint64), inact)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--validators", type=int, default=2048,
                    help="differential-leg registry size")
    ap.add_argument("--census-validators", type=int, default=1 << 20,
                    help="scaling-census column length (1M default)")
    ap.add_argument("--merkle-chunks", type=int, default=1 << 16)
    ap.add_argument("--min-scaling", type=float, default=6.0)
    args = ap.parse_args()

    from consensus_specs_tpu.utils.jax_env import (
        setup_compile_cache, force_cpu_platform)
    setup_compile_cache()
    if not os.environ.get("CS_TPU_BENCH_REAL_DEVICES"):
        force_cpu_platform()

    import numpy as np
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.ops import epoch_kernels as ek
    from consensus_specs_tpu.parallel import mesh_epoch, mesh_merkle, \
        mesh_state
    from consensus_specs_tpu.state import arrays
    from consensus_specs_tpu.test_infra.metrics import counting
    from consensus_specs_tpu.utils import bls
    from consensus_specs_tpu.utils.ssz import hash_tree_root

    bls.bls_active = False
    assert mesh_state.device_count() >= 2, \
        "mesh bench needs a multi-device host (virtual mesh forced " \
        "above — did an ambient XLA_FLAGS override it?)"
    mesh = mesh_state.build_mesh()
    n_dev = mesh_state.device_count()

    # -- 1: structural psum census -----------------------------------------
    census = _psum_census(mesh)

    # -- 2: differential replay, mesh counters ----------------------------
    spec = build_spec("altair", "minimal")
    slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
    state = build_state(spec, args.validators)
    ek.use_vectorized()
    arrays.use_arrays()
    mesh_state.use_fallback()
    spec.process_slots(state, slots_per_epoch)      # genesis no-op epoch
    for i in range(args.validators):
        state.previous_epoch_participation[i] = \
            spec.ParticipationFlags(i % 8)
        state.inactivity_scores[i] = i % 40

    s_loop, s_single, s_mesh = state.copy(), state.copy(), state.copy()
    ek.use_loops()
    spec.process_slots(s_loop, int(s_loop.slot) + slots_per_epoch)
    root_loop = bytes(hash_tree_root(s_loop))
    ek.use_vectorized()
    spec.process_slots(s_single, int(s_single.slot) + slots_per_epoch)
    root_single = bytes(hash_tree_root(s_single))
    mesh_state.use_mesh()
    t0 = time.time()
    with counting() as delta:
        spec.process_slots(s_mesh, int(s_mesh.slot) + slots_per_epoch)
        root_mesh = bytes(hash_tree_root(s_mesh))
    mesh_replay_s = time.time() - t0
    mesh_state.use_auto()

    mesh_subs = delta["mesh.epoch{path=mesh}"]
    psums = {sub: delta[f"mesh.psums{{site={sub}}}"]
             for sub in mesh_epoch.PSUM_BUDGET}
    host_partials = delta["mesh.host_partials"]

    # -- 3: per-shard kernel scaling census at 1M --------------------------
    n_full = args.census_validators
    t_full = _shard_kernel_time(n_full)
    t_shard = _shard_kernel_time(n_full // n_dev)
    scaling = t_full / t_shard if t_shard else float("inf")

    # one real 1M SPMD dispatch for the record (8 shards timeshare this
    # host's core — wall time here is compile+dispatch overhead, the
    # scaling claim above is the hardware-relevant number)
    rng = np.random.default_rng(5)
    cols_1m = rng.integers(0, 2**35, n_full, dtype=np.uint64)
    with mesh_state.x64():
        t0 = time.time()
        dev = mesh_state.place(cols_1m, mesh)
        sums = np.asarray(mesh_epoch._p_masked_sums(mesh)(
            dev, np.ones((1, n_full), dtype=bool)))
        place_reduce_s = time.time() - t0
    assert int(sums[0]) == int(cols_1m.sum(dtype=np.uint64)), \
        "1M psum reduction diverged from the host sum"

    # -- 4: leaf-span merkleization ----------------------------------------
    data = rng.integers(0, 256, args.merkle_chunks * 32,
                        dtype=np.uint8).tobytes()
    mesh_state.use_mesh()
    with counting() as mdelta:
        t0 = time.time()
        levels = mesh_merkle.build_levels(data, 40)
        mesh_merkle_s = time.time() - t0
    mesh_state.use_fallback()
    t0 = time.time()
    golden = mesh_merkle._sequential_levels(data, 40)
    seq_merkle_s = time.time() - t0
    mesh_state.use_auto()
    assert levels is not None, "mesh merkle declined the 64k build"
    assert all(bytes(a) == bytes(b) for a, b in zip(levels, golden)), \
        "mesh leaf-span levels diverged from the sequential build"

    result = {
        "metric": "mesh SPMD engine",
        "devices": n_dev,
        "validators": args.validators,
        "census_validators": n_full,
        "psum_census": census,
        "epoch_psums": psums,
        "host_partial_elements": host_partials,
        "mesh_subtransitions": mesh_subs,
        "mesh_replay_s": round(mesh_replay_s, 3),
        "shard_kernel_full_s": round(t_full, 4),
        "shard_kernel_eighth_s": round(t_shard, 4),
        "per_shard_scaling": round(scaling, 2),
        "place_reduce_1m_s": round(place_reduce_s, 3),
        "mesh_merkle_chunks": args.merkle_chunks,
        "mesh_merkle_s": round(mesh_merkle_s, 3),
        "seq_merkle_s": round(seq_merkle_s, 3),
        "mesh_merkle_builds": mdelta["mesh.merkle{path=mesh}"],
    }
    print(json.dumps(result), flush=True)

    # the census guarantees (the smoke's reason to exist)
    assert root_mesh == root_single == root_loop, \
        "state roots diverge across {mesh, single-device, spec loop}"
    assert mesh_subs == 5, \
        f"expected all 5 altair sub-transitions through the mesh: " \
        f"{mesh_subs}"
    assert psums == mesh_epoch.PSUM_BUDGET, \
        f"psum count off budget: {psums} != {mesh_epoch.PSUM_BUDGET}"
    assert delta["mesh.epoch.fallbacks{reason=guard}"] == 0, \
        "unexpected mesh guard fallback"
    # host-work census: the runtime twin of the speclint N13xx proof —
    # across the whole mesh epoch the host read only per-shard partial
    # stacks (10S elements for the altair composition: 3S rewards
    # maxima + S inactivity + 3S registry candidate counts + S
    # slashings + 2S effective-balance), never an O(n) column
    assert 0 < host_partials <= 16 * n_dev, \
        f"host partial reads off budget: {host_partials} elements " \
        f"for {n_dev} shards (expected ~10S, hard cap 16S)"
    assert scaling >= args.min_scaling, \
        f"per-shard kernel scaling {scaling:.2f}x < " \
        f"{args.min_scaling}x at {n_dev} shards"
    assert mdelta["mesh.merkle{path=mesh}"] == 1


if __name__ == "__main__":
    main()
