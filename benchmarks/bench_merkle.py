"""BASELINE config #5: multi-slot replay wall-clock at large registries.

Measures ``process_slots(state, state.slot + 32)`` (a full epoch of slot
processing: per-slot state-root snapshots, i.e. the merkleization-bound
path) at 10k / 100k / 1M validators, exercising the dirty-subtree root
caching in ``utils/ssz`` (remerkleable's role; reference
``setup.py:549``).  Pubkeys are synthetic — signature checks are off in
this config; the workload is hashing, not crypto.

Also measures the registry-wide balance-commit root (every validator's
balance changes, then the state re-roots) — the merkleization bill of an
epoch transition, which the slot-replay window alone does not capture —
and reports the merkle engine's dispatch counters for it (batched vs
per-pair hashlib; see ``utils/ssz/merkle.stats``).

Prints one JSON line per registry size.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.ssz import merkle
from consensus_specs_tpu.utils.ssz.forest import hash_forest


def build_state(spec, n):
    state = spec.BeaconState(
        genesis_time=0,
        fork=spec.Fork(
            previous_version=spec.config.GENESIS_FORK_VERSION,
            current_version=spec.config.GENESIS_FORK_VERSION,
            epoch=0),
        latest_block_header=spec.BeaconBlockHeader(
            body_root=spec.hash_tree_root(spec.BeaconBlockBody())),
    )
    v = spec.Validator(
        effective_balance=spec.MAX_EFFECTIVE_BALANCE,
        activation_eligibility_epoch=0, activation_epoch=0,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawal_credentials=b"\x00" * 32)
    for i in range(n):
        v.pubkey = i.to_bytes(8, "little") * 6       # unique synthetic key
        state.validators.append(v)
        state.balances.append(spec.MAX_EFFECTIVE_BALANCE)
    return state


def main():
    bls.bls_active = False
    # mainnet preset: SLOTS_PER_EPOCH=32, so slots 1..31 isolate the
    # merkleization-bound per-slot path and the boundary crossing at 32
    # isolates the (python-loop-bound) epoch transition.
    spec = build_spec("phase0", "mainnet")
    sizes = [int(s) for s in (sys.argv[1:] or ["10000", "100000", "1000000"])]
    for n in sizes:
        t0 = time.time()
        state = build_state(spec, n)
        build_s = time.time() - t0
        t0 = time.time()
        state.hash_tree_root()
        first_root_s = time.time() - t0
        state.slot = 1
        n_slots = 30
        t0 = time.time()
        spec.process_slots(state, state.slot + n_slots)   # stays in-epoch
        slots_s = time.time() - t0
        t0 = time.time()
        spec.process_slots(state, state.slot + 1)         # crosses boundary
        epoch_s = time.time() - t0
        # registry-wide balance commit: every balance changes through the
        # public API, then the state re-roots (the epoch transition's
        # merkleization bill, outside the slot-replay window above)
        merkle.reset_stats()
        t0 = time.time()
        for i in range(n):
            state.balances[i] = int(state.balances[i]) - 1
        with hash_forest():
            state.hash_tree_root()
        commit_root_s = time.time() - t0
        stats = merkle.stats()
        print(json.dumps({
            "metric": f"32-slot replay, {n} validators",
            "value": round(slots_s + epoch_s, 3), "unit": "s",
            "build_s": round(build_s, 1),
            "first_full_root_s": round(first_root_s, 2),
            "per_slot_ms": round(slots_s / n_slots * 1000, 1),
            "epoch_transition_s": round(epoch_s, 2),
            "balance_commit_root_s": round(commit_root_s, 3),
            "pair_batch_pairs": stats["pair_batch_pairs"],
            "pair_scalar": stats["pair_scalar"],
            "layer_calls": stats["layer_calls"],
        }), flush=True)


if __name__ == "__main__":
    main()
