"""Merkle-engine dispatch smoke (``make bench-merkle-smoke``, CI-wired).

Drives a tiny registry through the two registry-wide commit paths and
asserts — via the dispatch counters in ``utils/ssz/merkle`` — that the
batched engine actually engaged:

1. the epoch engine's chunk-packed column commit
   (``ops/epoch_kernels._write_u64_list`` -> ``replace_basic_items``
   with a packed buffer) must re-hash entirely through batched layer
   dispatches: ZERO per-pair hashlib calls;
2. a wide ``__setitem__`` commit must route every dirty level at or
   above the pair threshold through a batched dispatch — only
   below-threshold tail levels may hash per pair.

Roots are verified against the no-cache ``decode_bytes(serialize())``
oracle, so a dispatch bug cannot pass as a performance quirk.

Exits nonzero on any violation.  When neither the native C hasher nor a
kernel is installed (no gcc), the JAX batched hasher is installed first —
the smoke then also covers the kernel plug path.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from consensus_specs_tpu.utils.ssz import merkle


def main():
    backend = "native" if merkle._native is not None else "kernel"
    if not merkle.have_fast_backend():
        from consensus_specs_tpu.ops.sha256 import install_merkle_hasher
        install_merkle_hasher()
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.ops import epoch_kernels
    from consensus_specs_tpu.utils import bls
    from consensus_specs_tpu.utils.ssz.forest import hash_forest

    bls.bls_active = False
    n = 2048
    spec = build_spec("phase0", "minimal")
    state = spec.BeaconState()
    v = spec.Validator(
        effective_balance=spec.MAX_EFFECTIVE_BALANCE,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH)
    for i in range(n):
        v.pubkey = i.to_bytes(8, "little") * 6
        state.validators.append(v)
        state.balances.append(spec.MAX_EFFECTIVE_BALANCE)
    state.hash_tree_root()
    # the largest level allowed to hash per pair: below the pair floor
    # always; on a kernel-only backend also below the kernel batch
    # threshold (can_batch_pairs — a gather the kernel won't take would
    # just feed hashlib anyway)
    layer_min, pair_min = merkle.batch_thresholds()
    scalar_limit = pair_min if merkle._native is not None \
        else max(pair_min, layer_min)

    def oracle():
        return type(state).decode_bytes(state.serialize()).hash_tree_root()

    # 1. chunk-packed column commit (the vectorized epoch engine's path)
    old = epoch_kernels.u64_column(state.balances)
    new = old - np.uint64(1)
    merkle.reset_stats()
    t0 = time.time()
    epoch_kernels._write_u64_list(state.balances, spec.Gwei, old, new)
    with hash_forest():
        root = state.hash_tree_root()
    packed_s = time.time() - t0
    packed_stats = merkle.stats()
    assert root == oracle(), "packed commit root mismatch"
    assert packed_stats["pair_scalar"] == 0, \
        f"packed commit used per-pair hashlib: {packed_stats}"
    assert packed_stats["layer_calls"] + packed_stats["pair_batch_calls"] > 0, \
        f"packed commit never dispatched batched: {packed_stats}"

    # 2. wide __setitem__ commit (the incremental dirty-pair engine)
    merkle.reset_stats()
    t0 = time.time()
    for i in range(n):
        state.balances[i] = int(state.balances[i]) - 1
    with hash_forest():
        root = state.hash_tree_root()
    setitem_s = time.time() - t0
    pair_stats = merkle.stats()
    assert root == oracle(), "setitem commit root mismatch"
    assert pair_stats["pair_batch_pairs"] > 0, \
        f"wide update never batched: {pair_stats}"
    assert pair_stats["pair_scalar_max"] < scalar_limit, \
        f"an above-threshold level hashed per pair: {pair_stats}"

    # telemetry snapshot: must be schema-valid with non-empty merkle
    # dispatch counters (the backend-labeled series are the engine's
    # regression tripwire — see docs/observability.md)
    from consensus_specs_tpu.obs import export
    snap = export.snapshot()
    export.assert_schema(snap, require_nonempty=("merkle.",))

    print(json.dumps({
        "metric": f"merkle smoke, {n} validators", "backend": backend,
        "packed_commit_s": round(packed_s, 4),
        "packed_stats": packed_stats,
        "setitem_commit_s": round(setitem_s, 4),
        "setitem_stats": pair_stats,
        "obs": {"metrics": {k: v for k, v in snap["metrics"].items()
                            if k.startswith(("merkle.", "forest."))}},
    }), flush=True)


if __name__ == "__main__":
    main()
