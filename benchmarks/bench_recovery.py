"""Durable-replay smoke: checkpoint/restore round-trip census +
restore/tail-replay cost + the checkpoint-DISABLED overhead bound
(``make bench-recovery-smoke``).

Three asserted claims back the recovery subsystem (docs/recovery.md):

1. **Round-trip census** — a scenario replay under checkpointing must
   actually save generations (``recovery.checkpoints{result=saved}``),
   and a resume after a simulated crash must serve from a checkpoint
   generation with journal records replayed
   (``recovery.restores{path=checkpoint}``,
   ``recovery.journal.records{op=replayed}``) and finish with a digest
   byte-identical to the uninterrupted replay.  A vacuous pass-through
   cannot fake these counters.
2. **Restore + tail-replay cost** — the recovery path's price is
   measured and reported: checkpoint save cost, restore-from-disk cost
   and the journal tail replay, as wall-clock over the smoke scenario.
3. **Disabled overhead** — with ``CS_TPU_CHECKPOINT=0`` the durable
   step driver adds only per-step branch checks and one per-delivery
   ``event_hook is None`` read; the exact census (steps × per-step
   cost + deliveries × per-emit cost) must stay under 2% of the plain
   replay — the ``bench_obs_overhead.py`` discipline (wall-clock A/B
   of a ~1s python workload is noise at this scale).

Exits nonzero on any census mismatch, digest divergence, or when the
computed disabled overhead reaches 2%.
"""
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SEED = 3
EVERY = 8
REPS = 3


def _best_of(fn, reps=REPS) -> float:
    return min(fn() for _ in range(reps))


def _scenario():
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.sim import scenarios
    spec = build_spec("phase0", "minimal")
    epoch = int(spec.SLOTS_PER_EPOCH)
    scenario = scenarios.build(SEED, epoch, epoch * 8)
    if scenario.config_overrides:
        spec = build_spec("phase0", "minimal", scenario.config_overrides)
    return spec, scenario


# ---------------------------------------------------------------------------
# 1 + 2. round-trip census + measured recovery costs
# ---------------------------------------------------------------------------

def roundtrip() -> dict:
    from consensus_specs_tpu import supervisor
    from consensus_specs_tpu.recovery.replay import DurableReplay
    from consensus_specs_tpu.sim import driver
    from consensus_specs_tpu.test_infra.metrics import counting
    from consensus_specs_tpu.utils import bls

    bls.bls_active = False
    os.environ["CS_TPU_BREAKER_THRESHOLD"] = "1000000000"
    supervisor.reset()
    spec, scenario = _scenario()
    baseline = driver.execute(spec, scenario.script, scenario.n_validators)

    work = tempfile.mkdtemp(prefix="bench_recovery_")
    try:
        # full durable run: must checkpoint and stay byte-identical
        with counting() as delta:
            t0 = time.perf_counter()
            result = DurableReplay(spec, scenario, work,
                                   checkpoint_every=EVERY).run()
            durable_s = time.perf_counter() - t0
        saved = delta["recovery.checkpoints{result=saved}"]
        assert saved >= 2, \
            f"durable run saved {saved} generations (expected >= 2)"
        assert result.digest() == baseline.digest(), \
            "durable replay diverged from the plain replay"
        appended = delta["recovery.journal.records{op=appended}"]
        assert appended >= len(scenario.script), \
            f"journal appended only {appended} records"

        # crash + resume: must restore from a generation and replay
        # the journal tail, byte-identical.  The crash point is nudged
        # OFF the checkpoint cadence so a non-empty journal tail
        # exists — otherwise the tail-replay half of the census would
        # pass vacuously
        shutil.rmtree(work)
        stop_at = (2 * len(scenario.script)) // 3
        if stop_at % EVERY == 0:
            stop_at += 1
        DurableReplay(spec, scenario, work,
                      checkpoint_every=EVERY).run(stop_at=stop_at)
        with counting() as delta:
            t0 = time.perf_counter()
            resumed, info = DurableReplay(spec, scenario, work,
                                          checkpoint_every=EVERY).resume()
            resume_s = time.perf_counter() - t0
        assert delta["recovery.restores{path=checkpoint}"] == 1, \
            f"resume did not restore from a checkpoint ({info})"
        replayed = delta["recovery.journal.records{op=replayed}"]
        assert replayed >= 1, \
            f"journal tail replay never ran ({info})"
        assert resumed.digest() == baseline.digest(), \
            "resumed replay diverged from the plain replay"

        # isolate restore + tail replay (no continuation steps)
        from consensus_specs_tpu.recovery.checkpoint import CheckpointStore
        from consensus_specs_tpu.recovery.replay import restore_replay
        shutil.rmtree(work)
        DurableReplay(spec, scenario, work,
                      checkpoint_every=EVERY).run(stop_at=stop_at)
        cs = CheckpointStore(work)

        def timed_restore():
            t0 = time.perf_counter()
            restore_replay(spec, scenario, cs)
            return time.perf_counter() - t0

        restore_s = _best_of(timed_restore)
        return {
            "steps": len(scenario.script),
            "generations_saved": saved,
            "journal_records_appended": appended,
            "journal_records_replayed": replayed,
            "resume_info": info,
            "durable_run_s": round(durable_s, 4),
            "resume_total_s": round(resume_s, 4),
            "restore_plus_tail_replay_s": round(restore_s, 4),
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


# ---------------------------------------------------------------------------
# 3. checkpoint-disabled overhead (exact census x per-op cost)
# ---------------------------------------------------------------------------

def _per_op_ns(fn, n=200_000) -> float:
    def one():
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n * 1e9
    return _best_of(one)


def disabled_overhead() -> dict:
    from consensus_specs_tpu.recovery.replay import DurableReplay
    from consensus_specs_tpu.sim import driver

    os.environ["CS_TPU_CHECKPOINT"] = "0"
    try:
        spec, scenario = _scenario()

        # delivery census: count every event the replay emits
        events = []
        sim = driver.ChainSim(spec, scenario.n_validators)
        sim.event_hook = lambda kind, value: events.append(kind)
        sim.run(scenario.script)
        deliveries = len(events)
        steps = len(scenario.script)

        def timed_plain():
            t0 = time.perf_counter()
            driver.execute(spec, scenario.script, scenario.n_validators)
            return time.perf_counter() - t0

        replay_s = _best_of(timed_plain)
        # off-path adds: per step two kill/stop compares + one journal
        # None check; per delivery one event_hook attribute read
        probe = {"x": None}
        step_ns = _per_op_ns(
            lambda: (probe["x"] == 3, probe["x"] == 4,
                     probe["x"] is not None))
        emit_ns = _per_op_ns(lambda: probe["x"] is not None)
        overhead_s = (steps * step_ns + deliveries * emit_ns) / 1e9

        # sanity: the disabled wrapper really produces the same digest
        work = tempfile.mkdtemp(prefix="bench_recovery_off_")
        try:
            off = DurableReplay(spec, scenario, work).run()
            plain = driver.execute(spec, scenario.script,
                                   scenario.n_validators)
            assert off.digest() == plain.digest(), \
                "disabled durable wrapper diverged"
        finally:
            shutil.rmtree(work, ignore_errors=True)
        return {
            "steps": steps,
            "deliveries": deliveries,
            "per_step_ns": round(step_ns, 2),
            "per_emit_ns": round(emit_ns, 2),
            "replay_s": round(replay_s, 4),
            "computed_overhead_s": round(overhead_s, 6),
            "computed_overhead_pct": round(overhead_s / replay_s * 100.0,
                                           4),
        }
    finally:
        os.environ.pop("CS_TPU_CHECKPOINT", None)


def main() -> int:
    trip = roundtrip()
    cost = disabled_overhead()
    print(json.dumps({
        "metric": "durable-replay round-trip census + restore cost + "
                  "checkpoint-disabled overhead",
        "roundtrip": trip,
        "disabled_overhead": cost,
    }, indent=2))
    pct = cost["computed_overhead_pct"]
    if pct >= 2.0:
        print(f"durable-replay disabled overhead {pct:.2f}% >= 2% of "
              "the replay", file=sys.stderr)
        return 1
    print(f"ok: resumed byte-identical from generation "
          f"{trip['resume_info']['generation']} "
          f"({trip['resume_info']['journal_steps']} journal steps), "
          f"restore+tail-replay {trip['restore_plus_tail_replay_s']}s, "
          f"disabled overhead {pct:.4f}% < 2%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
