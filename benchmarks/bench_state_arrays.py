"""StateArrays store benchmark (``make bench-state-smoke``, CI-wired).

Drives an N-validator altair state through an S-slot replay with the
vectorized engines on, then forks R concurrent replays off one base
snapshot — census-asserting the copy-on-write column store's contracts
via the ``state_arrays.*`` telemetry counters:

1. **extraction census** — the registry is extracted at most once per
   epoch transition (exactly once TOTAL in an empty-slot replay: the
   lineage-attached columns stay structurally valid across epochs);
2. **one commit per epoch transition** — the balance-family columns
   flush to SSZ chunks exactly once per ``process_epoch``, not once per
   sub-transition;
3. **cheap snapshot/fork** — R replays forked from one base produce
   byte-identical state roots vs independent full-copy replays run
   with the store DISABLED (a true differential oracle) while sharing
   the base columns: zero registry re-extractions in the forks and a
   copy-on-write census strictly below columns x replays.

Exits nonzero on any violation.  ``--smoke`` runs the small CI shape;
the full shape (``--validators 1048576 --slots 32``) is the
BENCHMARKS.md configuration.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_state(spec, n):
    state = spec.BeaconState()
    v = spec.Validator(
        effective_balance=spec.MAX_EFFECTIVE_BALANCE,
        activation_epoch=0,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH)
    state.validators = [v] * n
    state.balances = [spec.MAX_EFFECTIVE_BALANCE] * n
    state.inactivity_scores = [0] * n
    state.previous_epoch_participation = [0] * n
    state.current_epoch_participation = [0] * n
    return state


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--validators", type=int, default=262144)
    ap.add_argument("--slots", type=int, default=16,
                    help="replay window (minimal preset: 8 slots/epoch)")
    ap.add_argument("--replays", type=int, default=16,
                    help="concurrent replays forked from one snapshot")
    ap.add_argument("--smoke", action="store_true",
                    help="small CI shape + counter asserts")
    args = ap.parse_args()
    if args.smoke:
        args.validators, args.slots, args.replays = 2048, 16, 16

    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.obs import export
    from consensus_specs_tpu.obs import registry as obs_registry
    from consensus_specs_tpu.state import arrays
    from consensus_specs_tpu.test_infra.metrics import counting
    from consensus_specs_tpu.utils import bls
    from consensus_specs_tpu.utils.ssz import hash_tree_root

    bls.bls_active = False
    spec = build_spec("altair", "minimal")
    slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
    assert arrays.enabled(), \
        "state-arrays store disabled (CS_TPU_STATE_ARRAYS=0?)"

    t0 = time.time()
    state = build_state(spec, args.validators)
    build_s = time.time() - t0

    # warm-up: the genesis-epoch transition no-ops; measure from epoch 1
    spec.process_slots(state, slots_per_epoch)
    obs_registry.reset("state_arrays.")
    obs_registry.reset("epoch.")
    obs_registry.reset("cache.")

    # -- 1+2: S-slot replay, extraction + commit census -------------------
    epochs = args.slots // slots_per_epoch
    t0 = time.time()
    with counting() as replay_delta:
        spec.process_slots(state, int(state.slot) + args.slots)
    replay_s = time.time() - t0
    extracts = replay_delta["state_arrays.extracts{column=registry}"] \
        + replay_delta["state_arrays.adoptions"]
    commits = replay_delta["state_arrays.commits"]

    # -- 3: R concurrent replays off one snapshot --------------------------
    base_root = bytes(hash_tree_root(state))
    arrays.registry_of(state)                  # base columns warm
    arrays.of(state).balances()
    half = int(spec.MAX_EFFECTIVE_BALANCE) // 2
    t0 = time.time()
    forks = [arrays.fork_state(state) for _ in range(args.replays)]
    fork_s = time.time() - t0
    t0 = time.time()
    with counting() as fork_delta:
        forked_roots = []
        for k, st in enumerate(forks):
            # distinct per-replay perturbation; halving a balance forces
            # the effective-balance hysteresis (registry COW) path
            st.balances[k % args.validators] = half + k
            spec.process_slots(st, int(st.slot) + slots_per_epoch)
            forked_roots.append(bytes(hash_tree_root(st)))
    forked_s = time.time() - t0
    cow_copies = fork_delta["state_arrays.cow_copies"]
    fork_extracts = fork_delta["state_arrays.extracts{column=registry}"]

    # independent leg runs with the store OFF (detached single-use
    # stores, no COW, no attach): a genuine differential oracle — a
    # store bug that corrupts a shared column cannot cancel out of the
    # forked-vs-independent root comparison
    arrays.use_fallback()
    t0 = time.time()
    independent_roots = []
    for k in range(args.replays):
        st = state.copy()
        st.balances[k % args.validators] = half + k
        spec.process_slots(st, int(st.slot) + slots_per_epoch)
        independent_roots.append(bytes(hash_tree_root(st)))
    independent_s = time.time() - t0
    arrays.use_auto()

    n_columns = len(arrays._COLUMNS)
    snap = export.snapshot()
    export.assert_schema(snap, require_nonempty=("state_arrays.",))
    result = {
        "metric": "state-arrays store",
        "validators": args.validators, "slots": args.slots,
        "epochs": epochs, "replays": args.replays,
        "build_s": round(build_s, 3),
        "replay_s": round(replay_s, 3),
        "slots_per_s": round(args.slots / replay_s, 1) if replay_s else None,
        "registry_extractions": extracts,
        "commits": commits,
        "fork_total_s": round(fork_s, 5),
        "fork_each_us": round(fork_s / args.replays * 1e6, 1),
        "cow_copies": cow_copies,
        "cow_bound": n_columns * args.replays,
        "forked_replays_s": round(forked_s, 3),
        "independent_replays_s": round(independent_s, 3),
        "obs": {k: v for k, v in snap["metrics"].items()
                if k.startswith(("state_arrays.", "epoch."))},
    }
    print(json.dumps(result), flush=True)

    # the census guarantees (the smoke's reason to exist)
    assert replay_delta["epoch.transition{path=vectorized}"] > 0, \
        "vectorized engine never committed during the replay"
    assert replay_delta["epoch.fallbacks{reason=guard}"] == 0, \
        "unexpected guard fallback"
    assert extracts <= epochs, \
        f"registry re-extracted within an epoch: {extracts} > {epochs}"
    assert commits == epochs, \
        f"expected one balance-family commit per epoch: {commits} != {epochs}"
    assert forked_roots == independent_roots, \
        "forked replays diverged from independent replays"
    assert bytes(hash_tree_root(state)) == base_root, \
        "a forked replay mutated the base snapshot"
    assert fork_extracts == 0, \
        f"forked replays re-extracted shared registry columns: {fork_extracts}"
    assert 0 < cow_copies < n_columns * args.replays, \
        f"copy-on-write census out of bounds: {cow_copies} vs " \
        f"{n_columns * args.replays}"


if __name__ == "__main__":
    main()
