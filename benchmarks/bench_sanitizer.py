"""Runtime effect-sanitizer overhead + integrity smoke
(``make bench-sanitizer-smoke``).

Three asserted claims back the ``CS_TPU_SANITIZER`` acceptance bar
(docs/static-analysis.md):

1. **Disabled overhead <2%** — the ``bench_obs_overhead`` discipline:
   tight-loop ns/op of a DISARMED hook (one mode check) times the exact
   hook census a 32-slot replay performs, over the replay wall-clock.
   The hooks sit on per-epoch / per-commit boundaries, so the census is
   tiny by construction; the bound proves it stays that way.
2. **Armed byte-identity** — the same replay armed and disarmed must
   produce byte-identical state roots (the sanitizer observes effects,
   never changes them) with ZERO violations booked on the clean path.
3. **Arming is live** — the armed replay books ``sanitizer.checks``
   (the scope ledger really ran), so a green leg is non-vacuous.

Exits nonzero on any violated bound.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLOTS = 32
VALIDATORS = 256
REPS = 3


def _best_of(fn, reps=3) -> float:
    return min(fn() for _ in range(reps))


def _per_op_hook_ns(n=500_000) -> float:
    """ns/op of a disarmed hook — the only cost the shipping default
    pays (one mode check + return)."""
    from consensus_specs_tpu import sanitizer
    sanitizer.disarm()
    hook = sanitizer.deferred_write

    def one():
        t0 = time.perf_counter()
        for _ in range(n):
            hook(None, "balances")
        return (time.perf_counter() - t0) / n * 1e9

    try:
        return _best_of(one)
    finally:
        sanitizer.use_auto()


def _fresh_replay_args():
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.tools.obs_report import build_state
    spec = build_spec("phase0", "minimal")
    return spec, build_state(spec, VALIDATORS)


def _replay_root(arm: bool):
    """(state root, seconds, hook census) of one replay."""
    from consensus_specs_tpu import sanitizer
    from consensus_specs_tpu.tools.obs_report import replay
    from consensus_specs_tpu.utils.ssz import hash_tree_root

    hooks = ("scope_opened", "scope_closed", "deferred_write",
             "fork_event", "checkpoint_scope_check", "blob_written",
             "manifest_written", "record_appended", "step_committed",
             "rename_event")
    census = [0]
    originals = {}

    def counting(fn):
        def wrapper(*a, **kw):
            census[0] += 1
            return fn(*a, **kw)
        return wrapper

    for name in hooks:
        originals[name] = getattr(sanitizer, name)
        setattr(sanitizer, name, counting(originals[name]))
    sanitizer.reset()
    if arm:
        sanitizer.arm()
    else:
        sanitizer.disarm()
    spec, state = _fresh_replay_args()
    try:
        t0 = time.perf_counter()
        replay(spec, state, SLOTS)
        took = time.perf_counter() - t0
    finally:
        for name, fn in originals.items():
            setattr(sanitizer, name, fn)
        sanitizer.use_auto()
    return bytes(hash_tree_root(state)), took, census[0]


def main() -> int:
    from consensus_specs_tpu import sanitizer
    from consensus_specs_tpu.utils import bls
    bls.bls_active = False

    hook_ns = _per_op_hook_ns()
    root_off, disabled_s, hook_census = _replay_root(arm=False)
    disabled_s = min(disabled_s,
                     *(_replay_root(arm=False)[1] for _ in range(REPS - 1)))
    root_on, enabled_s, _ = _replay_root(arm=True)
    snap = sanitizer.snapshot()
    checks = sum(v["checks"] for v in snap.values())
    violations = sum(v["violations"] for v in snap.values())

    overhead_s = hook_census * hook_ns / 1e9
    overhead_pct = overhead_s / disabled_s * 100.0

    print(json.dumps({
        "metric": f"sanitizer disabled-path overhead, {SLOTS}-slot "
                  f"replay, {VALIDATORS} validators",
        "hook_disarmed_ns": round(hook_ns, 1),
        "hook_census_per_replay": hook_census,
        "replay_disarmed_s": round(disabled_s, 4),
        "replay_armed_s": round(enabled_s, 4),
        "computed_overhead_s": round(overhead_s, 6),
        "computed_overhead_pct": round(overhead_pct, 4),
        "armed_checks": checks,
        "armed_violations": violations,
        "roots_identical": root_on == root_off,
    }), flush=True)

    assert overhead_pct < 2.0, (
        f"disabled sanitizer overhead {overhead_pct:.3f}% >= 2% of the "
        f"{SLOTS}-slot replay")
    assert root_on == root_off, (
        "sanitizer-armed replay diverged from the disarmed replay — "
        "the sanitizer must observe effects, never change them")
    assert violations == 0, (
        f"clean replay booked {violations} sanitizer violation(s)")
    assert checks > 0, (
        "armed replay booked zero sanitizer checks — the leg is "
        "vacuous (hooks not reached)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
