"""Rewards-deltas suite.

Reference model: ``test/phase0/rewards/test_basic.py`` /
``test_random.py`` / ``test_leak.py`` through the
``helpers/rewards.py`` machinery.
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases,
)
from consensus_specs_tpu.test_infra import rewards as rw


@with_all_phases
@spec_state_test
def test_rewards_full_participation(spec, state):
    rw.prepare_state_with_attestations(spec, state)
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_empty_participation(spec, state):
    rw.prepare_state_with_attestations(spec, state,
                                       participation_fn=lambda c: set())
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_half_participation_random(spec, state):
    rng = Random(5566)
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.5))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_leak_full_participation(spec, state):
    rw.set_state_in_leak(spec, state)
    rw.prepare_state_with_attestations(spec, state)
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_leak_empty_participation(spec, state):
    rw.set_state_in_leak(spec, state)
    rw.prepare_state_with_attestations(spec, state,
                                       participation_fn=lambda c: set())
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_with_slashed_validators(spec, state):
    rng = Random(7788)
    rw.prepare_state_with_attestations(spec, state)
    # slash a handful of validators after the fact
    for index in rng.sample(range(len(state.validators)), 4):
        state.validators[index].slashed = True
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_balance_conservation_applies(spec, state):
    """process_rewards_and_penalties applies exactly the computed deltas
    (component by component, with the zero floor of decrease_balance)."""
    rw.prepare_state_with_attestations(spec, state)
    post = state.copy()
    spec.process_rewards_and_penalties(post)

    balances = [int(b) for b in state.balances]

    def apply(rewards, penalties):
        for i in range(len(balances)):
            balances[i] += int(rewards[i])
            balances[i] = 0 if int(penalties[i]) > balances[i] \
                else balances[i] - int(penalties[i])

    if spec.fork == "phase0":
        apply(*spec.get_attestation_deltas(state))
    else:
        for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
            apply(*spec.get_flag_index_deltas(state, flag_index))
        apply(*spec.get_inactivity_penalty_deltas(state))

    assert [int(b) for b in post.balances] == balances


@with_all_phases
@spec_state_test
def test_rewards_quarter_participation(spec, state):
    rng = Random(11)
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.25))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_two_thirds_participation(spec, state):
    rng = Random(22)
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.67))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_almost_full_participation(spec, state):
    # every committee minus its first member
    rw.prepare_state_with_attestations(
        spec, state,
        participation_fn=lambda comm: set(sorted(comm)[1:]))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_one_attestation_one_participant(spec, state):
    rw.prepare_state_with_attestations(
        spec, state,
        participation_fn=lambda comm: {sorted(comm)[0]})
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_random_seed_2(spec, state):
    rng = Random(7788)
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.7))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_leak_half_participation(spec, state):
    rng = Random(33)
    rw.set_state_in_leak(spec, state)
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.5))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_misc_balances(spec, state):
    # mixed effective balances incl. sub-increment and ejection-level
    rng = Random(44)
    for index, validator in enumerate(state.validators):
        if rng.random() < 0.5:
            eff = rng.randrange(
                int(spec.config.EJECTION_BALANCE),
                int(spec.MAX_EFFECTIVE_BALANCE) + 1,
                int(spec.EFFECTIVE_BALANCE_INCREMENT))
            validator.effective_balance = eff
            state.balances[index] = eff
    rw.prepare_state_with_attestations(spec, state)
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_some_exited_validators(spec, state):
    # a few validators exited (but not slashed) during the epoch
    for index in (1, 3):
        spec.initiate_validator_exit(state, index)
    rw.prepare_state_with_attestations(spec, state)
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_some_very_low_balances(spec, state):
    for index in (0, 2):
        state.balances[index] = 1  # below reward eligibility floor
    rw.prepare_state_with_attestations(spec, state)
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_leak_with_slashed(spec, state):
    rw.set_state_in_leak(spec, state)
    for index in (1, 4):
        state.validators[index].slashed = True
    rw.prepare_state_with_attestations(spec, state)
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_with_not_yet_activated_validators(spec, state):
    """Pending validators are excluded from attestation deltas."""
    rng = Random(1101)
    for index in rng.sample(range(len(state.validators)), 4):
        v = state.validators[index]
        v.activation_eligibility_epoch = spec.get_current_epoch(state) + 3
        v.activation_epoch = spec.FAR_FUTURE_EPOCH
    rw.prepare_state_with_attestations(spec, state)
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_with_zero_balances(spec, state):
    """Zero-balance (but active) validators: penalties floor at zero."""
    rng = Random(1102)
    for index in rng.sample(range(len(state.validators)), 4):
        state.balances[index] = 0
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.5))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_leak_misc_balances(spec, state):
    """Inactivity leak over a registry with scattered effective balances."""
    rng = Random(1103)
    for index in range(len(state.validators)):
        state.validators[index].effective_balance = spec.Gwei(
            rng.randrange(0, int(spec.MAX_EFFECTIVE_BALANCE) + 1,
                          int(spec.EFFECTIVE_BALANCE_INCREMENT)))
    rw.set_state_in_leak(spec, state)
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.6))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_leak_some_exited(spec, state):
    rng = Random(1104)
    current_epoch = spec.get_current_epoch(state)
    for index in rng.sample(range(len(state.validators)), 4):
        state.validators[index].exit_epoch = current_epoch
        state.validators[index].withdrawable_epoch = current_epoch + 1
    rw.set_state_in_leak(spec, state)
    rw.prepare_state_with_attestations(spec, state)
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_random_seed_3(spec, state):
    rng = Random(3033)
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.3))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_random_seed_4(spec, state):
    rng = Random(4044)
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.9))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_leak_random_seed_5(spec, state):
    rng = Random(5055)
    rw.set_state_in_leak(spec, state)
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.4))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_full_but_low_effective_balance(spec, state):
    """Every validator at the minimum nonzero effective balance."""
    for index in range(len(state.validators)):
        state.validators[index].effective_balance = \
            spec.EFFECTIVE_BALANCE_INCREMENT
    rw.prepare_state_with_attestations(spec, state)
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_mixed_slashed_and_exited(spec, state):
    rng = Random(1107)
    current_epoch = spec.get_current_epoch(state)
    indices = rng.sample(range(len(state.validators)), 8)
    for index in indices[:4]:
        state.validators[index].slashed = True
        state.validators[index].withdrawable_epoch = current_epoch + \
            spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    for index in indices[4:]:
        state.validators[index].exit_epoch = current_epoch
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.7))
    yield "pre", state
    yield from rw.run_deltas(spec, state)
