"""Rewards-deltas suite.

Reference model: ``test/phase0/rewards/test_basic.py`` /
``test_random.py`` / ``test_leak.py`` through the
``helpers/rewards.py`` machinery.
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases,
)
from consensus_specs_tpu.test_infra import rewards as rw


@with_all_phases
@spec_state_test
def test_rewards_full_participation(spec, state):
    rw.prepare_state_with_attestations(spec, state)
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_empty_participation(spec, state):
    rw.prepare_state_with_attestations(spec, state,
                                       participation_fn=lambda c: set())
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_half_participation_random(spec, state):
    rng = Random(5566)
    rw.prepare_state_with_attestations(
        spec, state, participation_fn=rw.randomize_participation(rng, 0.5))
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_leak_full_participation(spec, state):
    rw.set_state_in_leak(spec, state)
    rw.prepare_state_with_attestations(spec, state)
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_leak_empty_participation(spec, state):
    rw.set_state_in_leak(spec, state)
    rw.prepare_state_with_attestations(spec, state,
                                       participation_fn=lambda c: set())
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_with_slashed_validators(spec, state):
    rng = Random(7788)
    rw.prepare_state_with_attestations(spec, state)
    # slash a handful of validators after the fact
    for index in rng.sample(range(len(state.validators)), 4):
        state.validators[index].slashed = True
    yield "pre", state
    yield from rw.run_deltas(spec, state)


@with_all_phases
@spec_state_test
def test_rewards_balance_conservation_applies(spec, state):
    """process_rewards_and_penalties applies exactly the computed deltas
    (component by component, with the zero floor of decrease_balance)."""
    rw.prepare_state_with_attestations(spec, state)
    post = state.copy()
    spec.process_rewards_and_penalties(post)

    balances = [int(b) for b in state.balances]

    def apply(rewards, penalties):
        for i in range(len(balances)):
            balances[i] += int(rewards[i])
            balances[i] = 0 if int(penalties[i]) > balances[i] \
                else balances[i] - int(penalties[i])

    if spec.fork == "phase0":
        apply(*spec.get_attestation_deltas(state))
    else:
        for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
            apply(*spec.get_flag_index_deltas(state, flag_index))
        apply(*spec.get_inactivity_penalty_deltas(state))

    assert [int(b) for b in post.balances] == balances
