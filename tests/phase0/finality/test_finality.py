"""Justification/finalization over multi-epoch attestation patterns.

Reference model: ``test/phase0/finality/test_finality.py`` — the
23/123/12-rule scenarios of ``weigh_justification_and_finalization``
(``specs/phase0/beacon-chain.md:1359``).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases,
)
from consensus_specs_tpu.test_infra.attestations import (
    next_epoch_with_attestations,
)


def check_finality(spec, state, prev_state, current_justified_changed,
                   previous_justified_changed, finalized_changed):
    if current_justified_changed:
        assert state.current_justified_checkpoint.epoch > \
            prev_state.current_justified_checkpoint.epoch
        assert state.current_justified_checkpoint.root != \
            prev_state.current_justified_checkpoint.root
    else:
        assert state.current_justified_checkpoint == \
            prev_state.current_justified_checkpoint
    if previous_justified_changed:
        assert state.previous_justified_checkpoint.epoch > \
            prev_state.previous_justified_checkpoint.epoch
    else:
        assert state.previous_justified_checkpoint == \
            prev_state.previous_justified_checkpoint
    if finalized_changed:
        assert state.finalized_checkpoint.epoch > \
            prev_state.finalized_checkpoint.epoch
        assert state.finalized_checkpoint.root != \
            prev_state.finalized_checkpoint.root
    else:
        assert state.finalized_checkpoint == prev_state.finalized_checkpoint


@with_all_phases
@spec_state_test
def test_finality_no_updates_at_genesis(spec, state):
    assert spec.get_current_epoch(state) == spec.GENESIS_EPOCH
    yield "pre", state
    blocks = []
    # genesis and genesis+1 epochs skip FFG updates entirely
    for _ in range(2):
        prev_state, new_blocks, state = next_epoch_with_attestations(
            spec, state, True, False)
        blocks += new_blocks
        check_finality(spec, state, prev_state, False, False, False)
    yield "blocks", blocks
    yield "post", state


@with_all_phases
@spec_state_test
def test_finality_rule_4(spec, state):
    """Two consecutive justified epochs finalize the first (rule 4: bits
    0-1 justified, current source)."""
    yield "pre", state
    blocks = []
    for epoch in range(4):
        prev_state, new_blocks, state = next_epoch_with_attestations(
            spec, state, True, False)
        blocks += new_blocks
        if epoch == 2:
            check_finality(spec, state, prev_state, True, False, False)
        elif epoch == 3:
            # justified from epoch 2, finalized via rule 4
            check_finality(spec, state, prev_state, True, True, True)
            assert state.finalized_checkpoint == \
                prev_state.current_justified_checkpoint
    yield "blocks", blocks
    yield "post", state


@with_all_phases
@spec_state_test
def test_finality_rule_1(spec, state):
    """Finalize with attestations to the previous epoch only (rule 1:
    bits 1-2 justified, previous source)."""
    # pump up to epoch 2 with real blocks (FFG active, distinct roots)
    prev_state, blocks_a, state = next_epoch_with_attestations(
        spec, state, False, False)
    prev_state, blocks_b, state = next_epoch_with_attestations(
        spec, state, False, False)
    yield "pre", state
    blocks = []
    for epoch in range(3):
        prev_state, new_blocks, state = next_epoch_with_attestations(
            spec, state, False, True)
        blocks += new_blocks
        if epoch == 0:
            check_finality(spec, state, prev_state, True, False, False)
        elif epoch == 1:
            check_finality(spec, state, prev_state, True, True, False)
        elif epoch == 2:
            check_finality(spec, state, prev_state, True, True, True)
            assert state.finalized_checkpoint == \
                prev_state.previous_justified_checkpoint
    yield "blocks", blocks
    yield "post", state


@with_all_phases
@spec_state_test
def test_finality_rule_2(spec, state):
    """Skip an epoch of attestations, then finalize via previous-epoch
    attestations (rule 2: bits 1-2, two-epoch gap to current)."""
    prev_state, _, state = next_epoch_with_attestations(
        spec, state, False, False)
    prev_state, _, state = next_epoch_with_attestations(
        spec, state, False, False)
    yield "pre", state
    blocks = []
    for epoch in range(3):
        if epoch == 0:
            prev_state, new_blocks, state = next_epoch_with_attestations(
                spec, state, True, False)
            check_finality(spec, state, prev_state, True, False, False)
        elif epoch == 1:
            prev_state, new_blocks, state = next_epoch_with_attestations(
                spec, state, False, False)
            check_finality(spec, state, prev_state, False, True, False)
        elif epoch == 2:
            prev_state, new_blocks, state = next_epoch_with_attestations(
                spec, state, False, True)
            # finalized old current -> rule 2
            check_finality(spec, state, prev_state, True, False, True)
        blocks += new_blocks
    yield "blocks", blocks
    yield "post", state


@with_all_phases
@spec_state_test
def test_finality_rule_3(spec, state):
    """Reference scenario: justify current, miss one, re-justify both —
    finality via rule 3 (bits 0-2 justified, current source two back)."""
    yield "pre", state
    blocks = []
    # epochs 0..3: full current-epoch attesting until finality flows
    for _ in range(4):
        prev_state, new_blocks, state = next_epoch_with_attestations(
            spec, state, True, False)
        blocks += new_blocks
    check_finality(spec, state, prev_state, True, True, True)

    # skip an epoch of attesting
    prev_state, new_blocks, state = next_epoch_with_attestations(
        spec, state, False, False)
    blocks += new_blocks
    check_finality(spec, state, prev_state, False, True, False)

    # attest previous + current: catches up via rule 3; the previous
    # justified checkpoint re-anchors to the same epoch-3 checkpoint
    prev_state, new_blocks, state = next_epoch_with_attestations(
        spec, state, True, True)
    blocks += new_blocks
    check_finality(spec, state, prev_state, True, False, True)
    yield "blocks", blocks
    yield "post", state
