"""Attestation operation tests.

Reference: ``test/phase0/block_processing/test_process_attestation.py``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, with_phases, always_bls)
from consensus_specs_tpu.test_infra.attestations import (
    get_valid_attestation, run_attestation_processing, sign_attestation,
)
from consensus_specs_tpu.test_infra.block import next_slots, next_epoch
from consensus_specs_tpu.utils.ssz import Bitlist


@with_all_phases
@spec_state_test
def test_one_basic_attestation(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_attestation_signature(spec, state):
    attestation = get_valid_attestation(spec, state)  # unsigned
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_empty_participants_seemingly_valid_sig(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    # remove all participants but keep the signature
    committee_len = len(attestation.aggregation_bits)
    attestation.aggregation_bits = Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](
        [0] * committee_len)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_before_inclusion_delay(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    # do not increment slot to allow for inclusion delay
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_phases(["phase0", "altair", "bellatrix", "capella"])
@spec_state_test
def test_invalid_after_epoch_slots(spec, state):
    # deneb (EIP-7045) removes the upper inclusion bound — see
    # tests/deneb/block_processing test_attestation_included_after_one_epoch
    attestation = get_valid_attestation(spec, state, signed=True)
    # increment past latest inclusion slot
    next_slots(spec, state, spec.SLOTS_PER_EPOCH + 1)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_bad_source_root(spec, state):
    attestation = get_valid_attestation(spec, state)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation.data.source.root = b"\x42" * 32
    sign_attestation(spec, state, attestation)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_wrong_index_for_slot(spec, state):
    while spec.get_committee_count_per_slot(
            state, spec.get_current_epoch(state)) >= spec.MAX_COMMITTEES_PER_SLOT:
        state.validators.pop()
        state.balances.pop()
    index = spec.MAX_COMMITTEES_PER_SLOT - 1
    # sign the honest attestation FIRST: the index corruption is what
    # process_attestation rejects (before any signature check), and
    # signing helpers cannot resolve a committee for the bogus index
    attestation = get_valid_attestation(spec, state, signed=True)
    attestation.data.index = index
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_old_source_epoch(spec, state):
    # advance a few epochs so there is a justified checkpoint mismatch to hit
    next_slots(spec, state, spec.SLOTS_PER_EPOCH * 5)
    state.finalized_checkpoint.epoch = 2
    state.previous_justified_checkpoint.epoch = 3
    state.current_justified_checkpoint.epoch = 4
    attestation = get_valid_attestation(spec, state, slot=state.slot, signed=False)
    # test logic sanity check: attestation source matches current justified
    assert attestation.data.source.epoch == state.current_justified_checkpoint.epoch
    # make the attestation source point at the older checkpoint
    attestation.data.source.epoch = state.previous_justified_checkpoint.epoch
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_extra_aggregation_bit(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    bits = list(attestation.aggregation_bits) + [False]
    attestation.aggregation_bits = Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](bits)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_too_few_aggregation_bits(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    bits = list(attestation.aggregation_bits)[:-1]
    attestation.aggregation_bits = Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](bits)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_previous_epoch_attestation(spec, state):
    next_epoch(spec, state)
    attestation = get_valid_attestation(
        spec, state, slot=state.slot - spec.SLOTS_PER_EPOCH + 1, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)
    if spec.fork == "phase0":
        assert len(state.previous_epoch_attestations) == 1


@with_all_phases
@spec_state_test
def test_multi_proposer_index_iterations(spec, state):
    # start deeper into the epoch structure so proposer-index search
    # iterates (reference scenario of the same name)
    next_slots(spec, state, spec.SLOTS_PER_EPOCH * 2)
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_empty_participants_zeroes_sig(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    committee_len = len(attestation.aggregation_bits)
    attestation.aggregation_bits = Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](
        [0] * committee_len)
    attestation.signature = spec.BLSSignature(b"\x00" * 96)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_phases(["phase0", "altair", "bellatrix", "capella"])
@spec_state_test
def test_at_max_inclusion_slot(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    # exactly data.slot + SLOTS_PER_EPOCH is still includable pre-deneb
    next_slots(spec, state, spec.SLOTS_PER_EPOCH)
    yield from run_attestation_processing(spec, state, attestation)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_wrong_index_for_committee_signature(spec, state):
    # signature is over index 0; flipping the index afterwards must fail
    # the (real) signature check
    attestation = get_valid_attestation(spec, state, signed=True)
    attestation.data.index += 1
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_index(spec, state):
    attestation = get_valid_attestation(spec, state)
    # committee index out of range for the slot
    attestation.data.index = spec.get_committee_count_per_slot(
        state, spec.get_current_epoch(state))
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_mismatched_target_and_slot(spec, state):
    next_epoch(spec, state)
    attestation = get_valid_attestation(
        spec, state, slot=state.slot - spec.SLOTS_PER_EPOCH + 1)
    # slot is in the previous epoch but target says current epoch
    attestation.data.target.epoch += 1
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_old_target_epoch(spec, state):
    next_slots(spec, state, spec.SLOTS_PER_EPOCH * 2)
    attestation = get_valid_attestation(spec, state)
    attestation.data.target.epoch = spec.get_previous_epoch(state) - 1
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_future_target_epoch(spec, state):
    attestation = get_valid_attestation(spec, state)
    attestation.data.target.epoch = spec.get_current_epoch(state) + 1
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_new_source_epoch(spec, state):
    attestation = get_valid_attestation(spec, state)
    attestation.data.source.epoch += 1
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_source_root_is_target_root(spec, state):
    attestation = get_valid_attestation(spec, state)
    attestation.data.source.root = attestation.data.target.root
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_current_source_root(spec, state):
    next_slots(spec, state, spec.SLOTS_PER_EPOCH * 5)
    state.finalized_checkpoint.epoch = 2
    state.previous_justified_checkpoint = spec.Checkpoint(
        epoch=3, root=b"\x01" * 32)
    state.current_justified_checkpoint = spec.Checkpoint(
        epoch=4, root=b"\x32" * 32)
    attestation = get_valid_attestation(spec, state, slot=state.slot)
    # correct epoch but wrong root for the current justified checkpoint
    attestation.data.source.root = b"\x99" * 32
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_previous_source_root(spec, state):
    next_slots(spec, state, spec.SLOTS_PER_EPOCH * 5)
    state.finalized_checkpoint.epoch = 2
    state.previous_justified_checkpoint = spec.Checkpoint(
        epoch=3, root=b"\x01" * 32)
    state.current_justified_checkpoint = spec.Checkpoint(
        epoch=4, root=b"\x32" * 32)
    # attestation for the previous epoch must match the PREVIOUS
    # justified checkpoint; give it the current one's root instead
    attestation = get_valid_attestation(
        spec, state, slot=state.slot - spec.SLOTS_PER_EPOCH)
    assert attestation.data.source.epoch == 3
    attestation.data.source.root = state.current_justified_checkpoint.root
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


def _sqrt_epoch_delay(spec):
    return spec.integer_squareroot(spec.SLOTS_PER_EPOCH)


def _run_delay_matrix_case(spec, state, delay, wrong_head=False,
                           wrong_target=False, valid=True):
    """Correct/incorrect head/target attestations at a given inclusion
    delay.  Wrong head/target roots are NOT operation-invalid (they only
    affect rewards/participation flags), so these cases are valid unless
    the inclusion window is exceeded."""
    attestation = get_valid_attestation(spec, state)
    if wrong_head:
        attestation.data.beacon_block_root = b"\x42" * 32
    if wrong_target:
        attestation.data.target.root = b"\x73" * 32
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, delay)
    yield from run_attestation_processing(spec, state, attestation,
                                          valid=valid)


@with_all_phases
@spec_state_test
def test_correct_attestation_included_at_sqrt_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(spec, state, _sqrt_epoch_delay(spec))


@with_all_phases
@spec_state_test
def test_correct_attestation_included_at_one_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(spec, state, spec.SLOTS_PER_EPOCH)


@with_all_phases
@spec_state_test
def test_incorrect_head_included_at_min_inclusion_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY, wrong_head=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_included_at_sqrt_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, _sqrt_epoch_delay(spec), wrong_head=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_included_at_max_inclusion_slot(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, spec.SLOTS_PER_EPOCH, wrong_head=True)


@with_all_phases
@spec_state_test
def test_incorrect_target_included_at_min_inclusion_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY, wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_target_included_at_sqrt_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, _sqrt_epoch_delay(spec), wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_target_included_at_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, spec.SLOTS_PER_EPOCH, wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_and_target_min_inclusion_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY,
        wrong_head=True, wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_and_target_included_at_sqrt_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, _sqrt_epoch_delay(spec),
        wrong_head=True, wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_and_target_included_at_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, spec.SLOTS_PER_EPOCH,
        wrong_head=True, wrong_target=True)


@with_phases(["phase0", "altair", "bellatrix", "capella"])
@spec_state_test
def test_invalid_incorrect_head_included_after_max_inclusion_slot(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, spec.SLOTS_PER_EPOCH + 1, wrong_head=True, valid=False)


@with_phases(["phase0", "altair", "bellatrix", "capella"])
@spec_state_test
def test_invalid_incorrect_target_included_after_max_inclusion_slot(
        spec, state):
    yield from _run_delay_matrix_case(
        spec, state, spec.SLOTS_PER_EPOCH + 1, wrong_target=True,
        valid=False)


@with_phases(["phase0", "altair", "bellatrix", "capella"])
@spec_state_test
def test_invalid_incorrect_head_and_target_after_max_inclusion_slot(
        spec, state):
    yield from _run_delay_matrix_case(
        spec, state, spec.SLOTS_PER_EPOCH + 1, wrong_head=True,
        wrong_target=True, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_too_many_aggregation_bits(spec, state):
    """A bitlist longer than the committee is rejected by the bit/
    committee length check."""
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    bits = list(attestation.aggregation_bits) + [True]
    committee = spec.get_beacon_committee(
        state, attestation.data.slot, attestation.data.index)
    attestation.aggregation_bits = Bitlist[
        spec.MAX_VALIDATORS_PER_COMMITTEE](bits)
    assert len(attestation.aggregation_bits) != len(committee)
    yield from run_attestation_processing(spec, state, attestation,
                                          valid=False)


@with_all_phases
@spec_state_test
def test_invalid_wrong_committee_index_for_slot(spec, state):
    """data.index >= the slot's committee count is rejected."""
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    committees = spec.get_committee_count_per_slot(
        state, spec.compute_epoch_at_slot(attestation.data.slot))
    attestation.data.index = committees
    yield from run_attestation_processing(spec, state, attestation,
                                          valid=False)


@with_all_phases
@spec_state_test
def test_previous_epoch_attestation(spec, state):
    """An attestation from the previous epoch is includable within its
    window and lands in the previous-epoch accounting."""
    next_epoch(spec, state)
    attestation = get_valid_attestation(
        spec, state, slot=state.slot - 1, signed=True)
    next_slots(spec, state, spec.SLOTS_PER_EPOCH - 1)
    assert spec.compute_epoch_at_slot(attestation.data.slot) == \
        spec.get_previous_epoch(state)
    yield from run_attestation_processing(spec, state, attestation)
