"""Attestation operation tests.

Reference: ``test/phase0/block_processing/test_process_attestation.py``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, with_phases, always_bls)
from consensus_specs_tpu.test_infra.attestations import (
    get_valid_attestation, run_attestation_processing, sign_attestation,
)
from consensus_specs_tpu.test_infra.block import next_slots, next_epoch
from consensus_specs_tpu.utils.ssz import Bitlist


@with_all_phases
@spec_state_test
def test_one_basic_attestation(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_attestation_signature(spec, state):
    attestation = get_valid_attestation(spec, state)  # unsigned
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_empty_participants_seemingly_valid_sig(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    # remove all participants but keep the signature
    committee_len = len(attestation.aggregation_bits)
    attestation.aggregation_bits = Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](
        [0] * committee_len)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_before_inclusion_delay(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    # do not increment slot to allow for inclusion delay
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_phases(["phase0", "altair", "bellatrix", "capella"])
@spec_state_test
def test_invalid_after_epoch_slots(spec, state):
    # deneb (EIP-7045) removes the upper inclusion bound — see
    # tests/deneb/block_processing test_attestation_included_after_one_epoch
    attestation = get_valid_attestation(spec, state, signed=True)
    # increment past latest inclusion slot
    next_slots(spec, state, spec.SLOTS_PER_EPOCH + 1)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_bad_source_root(spec, state):
    attestation = get_valid_attestation(spec, state)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation.data.source.root = b"\x42" * 32
    sign_attestation(spec, state, attestation)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_wrong_index_for_slot(spec, state):
    while spec.get_committee_count_per_slot(
            state, spec.get_current_epoch(state)) >= spec.MAX_COMMITTEES_PER_SLOT:
        state.validators.pop()
        state.balances.pop()
    index = spec.MAX_COMMITTEES_PER_SLOT - 1
    attestation = get_valid_attestation(spec, state)
    attestation.data.index = index
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_old_source_epoch(spec, state):
    # advance a few epochs so there is a justified checkpoint mismatch to hit
    next_slots(spec, state, spec.SLOTS_PER_EPOCH * 5)
    state.finalized_checkpoint.epoch = 2
    state.previous_justified_checkpoint.epoch = 3
    state.current_justified_checkpoint.epoch = 4
    attestation = get_valid_attestation(spec, state, slot=state.slot, signed=False)
    # test logic sanity check: attestation source matches current justified
    assert attestation.data.source.epoch == state.current_justified_checkpoint.epoch
    # make the attestation source point at the older checkpoint
    attestation.data.source.epoch = state.previous_justified_checkpoint.epoch
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_extra_aggregation_bit(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    bits = list(attestation.aggregation_bits) + [False]
    attestation.aggregation_bits = Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](bits)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_too_few_aggregation_bits(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    bits = list(attestation.aggregation_bits)[:-1]
    attestation.aggregation_bits = Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](bits)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_previous_epoch_attestation(spec, state):
    next_epoch(spec, state)
    attestation = get_valid_attestation(
        spec, state, slot=state.slot - spec.SLOTS_PER_EPOCH + 1, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)
    if spec.fork == "phase0":
        assert len(state.previous_epoch_attestations) == 1
