"""Proposer/attester slashing + voluntary exit operation tests.

Reference: ``test/phase0/block_processing/test_process_proposer_slashing.py``,
``test_process_attester_slashing.py``, ``test_process_voluntary_exit.py``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, always_bls,
)
from consensus_specs_tpu.test_infra.slashings import (
    get_valid_proposer_slashing, run_proposer_slashing_processing,
    get_valid_attester_slashing, run_attester_slashing_processing,
)
from consensus_specs_tpu.test_infra.voluntary_exits import (
    prepare_signed_exits, run_voluntary_exit_processing, sign_voluntary_exit,
)
from consensus_specs_tpu.test_infra.keys import privkeys
from consensus_specs_tpu.test_infra.block import next_slots


# --- proposer slashings ---

@with_all_phases
@spec_state_test
def test_proposer_slashing_basic(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_proposer_slashing_sig_1(spec, state):
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, signed_1=False, signed_2=True)
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashing_identical_headers(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    proposer_slashing.signed_header_2 = proposer_slashing.signed_header_1
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashing_slots_mismatch(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    header = proposer_slashing.signed_header_2.message
    header.slot = header.slot + 1
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashing_repeat(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    slashed_index = proposer_slashing.signed_header_1.message.proposer_index
    spec.process_proposer_slashing(state, proposer_slashing)
    assert state.validators[slashed_index].slashed
    # second identical slashing is invalid (validator no longer slashable)
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


# --- attester slashings ---

@with_all_phases
@spec_state_test
def test_attester_slashing_basic_double(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    yield from run_attester_slashing_processing(spec, state, attester_slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_attester_slashing_sig_2(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=False)
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_attester_slashing_same_data(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    attester_slashing.attestation_2 = attester_slashing.attestation_1
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


def _surround_slashing(spec, state):
    """attestation_1 surrounds attestation_2 (source earlier AND target
    later); both independently signed over their final data."""
    from consensus_specs_tpu.test_infra.attestations import (
        get_valid_attestation, sign_attestation)
    next_slots(spec, state, spec.SLOTS_PER_EPOCH * 3)
    attestation_2 = get_valid_attestation(spec, state)
    attestation_2.data.source.epoch = 1
    attestation_1 = attestation_2.copy()
    attestation_1.data.source.epoch = 0
    attestation_1.data.target.epoch = attestation_2.data.target.epoch + 0
    attestation_2.data.target.epoch -= 1
    assert spec.is_slashable_attestation_data(
        attestation_1.data, attestation_2.data)
    sign_attestation(spec, state, attestation_1)
    sign_attestation(spec, state, attestation_2)
    return spec.AttesterSlashing(
        attestation_1=spec.get_indexed_attestation(state, attestation_1),
        attestation_2=spec.get_indexed_attestation(state, attestation_2),
    )


@with_all_phases
@spec_state_test
def test_attester_slashing_basic_surround(spec, state):
    attester_slashing = _surround_slashing(spec, state)
    yield from run_attester_slashing_processing(spec, state, attester_slashing)


@with_all_phases
@spec_state_test
def test_attester_slashing_already_exited_recent(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    slashed_indices = set(
        attester_slashing.attestation_1.attesting_indices).intersection(
        attester_slashing.attestation_2.attesting_indices)
    # an exited-but-not-withdrawn validator is still slashable
    spec.initiate_validator_exit(state, sorted(slashed_indices)[0])
    yield from run_attester_slashing_processing(spec, state, attester_slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_attester_slashing_sig_1(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=False, signed_2=True)
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_attester_slashing_sig_1_and_2(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=False, signed_2=False)
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_attester_slashing_no_double_or_surround(spec, state):
    from consensus_specs_tpu.test_infra.attestations import (
        get_valid_attestation, sign_attestation)
    next_slots(spec, state, spec.SLOTS_PER_EPOCH)
    attestation_1 = get_valid_attestation(spec, state, signed=True)
    attestation_2 = attestation_1.copy()
    # different target epochs, no surround -> not slashable
    attestation_2.data.target.epoch -= 1
    sign_attestation(spec, state, attestation_2)
    slashing = spec.AttesterSlashing(
        attestation_1=spec.get_indexed_attestation(state, attestation_1),
        attestation_2=spec.get_indexed_attestation(state, attestation_2),
    )
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_attester_slashing_participants_already_slashed(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    slashed_indices = set(
        attester_slashing.attestation_1.attesting_indices).intersection(
        attester_slashing.attestation_2.attesting_indices)
    for index in slashed_indices:
        state.validators[index].slashed = True
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_attester_slashing_att1_high_index(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    indices = list(attester_slashing.attestation_1.attesting_indices)
    indices.append(len(state.validators))  # out of range
    attester_slashing.attestation_1.attesting_indices = type(
        attester_slashing.attestation_1.attesting_indices)(*sorted(indices))
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_attester_slashing_att2_high_index(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    indices = list(attester_slashing.attestation_2.attesting_indices)
    indices.append(len(state.validators))
    attester_slashing.attestation_2.attesting_indices = type(
        attester_slashing.attestation_2.attesting_indices)(*sorted(indices))
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_attester_slashing_att1_empty_indices(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    attester_slashing.attestation_1.attesting_indices = type(
        attester_slashing.attestation_1.attesting_indices)()
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_attester_slashing_all_empty_indices(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    attester_slashing.attestation_1.attesting_indices = type(
        attester_slashing.attestation_1.attesting_indices)()
    attester_slashing.attestation_2.attesting_indices = type(
        attester_slashing.attestation_2.attesting_indices)()
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_attester_slashing_att1_bad_extra_index(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    indices = list(attester_slashing.attestation_1.attesting_indices)
    # valid registry index that did not sign: aggregate pubkey mismatch
    options = [i for i in range(len(state.validators)) if i not in indices]
    indices.append(options[0])
    attester_slashing.attestation_1.attesting_indices = type(
        attester_slashing.attestation_1.attesting_indices)(*sorted(indices))
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_attester_slashing_att1_duplicate_index(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    indices = list(attester_slashing.attestation_1.attesting_indices)
    indices.append(indices[0])  # duplicate breaks sorted-unique rule
    attester_slashing.attestation_1.attesting_indices = type(
        attester_slashing.attestation_1.attesting_indices)(*sorted(indices))
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_attester_slashing_unsorted_att_1(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    indices = list(attester_slashing.attestation_1.attesting_indices)
    if len(indices) < 2:
        indices = indices + [len(state.validators) - 1]
    indices[0], indices[1] = indices[1], indices[0]  # unsorted
    attester_slashing.attestation_1.attesting_indices = type(
        attester_slashing.attestation_1.attesting_indices)(*indices)
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


# --- proposer slashings (additional scenarios) ---

@with_all_phases
@spec_state_test
def test_proposer_slashing_block_header_from_future(spec, state):
    # a header pair for a FUTURE slot is still slashable evidence
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, slot=state.slot + 5)
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_proposer_slashing_sig_2(spec, state):
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, signed_1=True, signed_2=False)
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_proposer_slashing_sig_1_and_2_swap(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    sig_1 = proposer_slashing.signed_header_1.signature
    proposer_slashing.signed_header_1.signature = \
        proposer_slashing.signed_header_2.signature
    proposer_slashing.signed_header_2.signature = sig_1
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashing_incorrect_proposer_index(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    # out-of-registry index
    bad = len(state.validators)
    proposer_slashing.signed_header_1.message.proposer_index = bad
    proposer_slashing.signed_header_2.message.proposer_index = bad
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashing_different_proposer_indices(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    proposer_slashing.signed_header_2.message.proposer_index = \
        proposer_slashing.signed_header_1.message.proposer_index + 1
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashing_slots_of_different_epochs(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    proposer_slashing.signed_header_2.message.slot += spec.SLOTS_PER_EPOCH
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashing_not_activated(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    index = proposer_slashing.signed_header_1.message.proposer_index
    state.validators[index].activation_epoch = spec.FAR_FUTURE_EPOCH
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashing_proposer_is_withdrawn(spec, state):
    next_slots(spec, state, 2 * spec.SLOTS_PER_EPOCH)
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    index = proposer_slashing.signed_header_1.message.proposer_index
    current_epoch = spec.get_current_epoch(state)
    state.validators[index].withdrawable_epoch = current_epoch - 1
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


# --- voluntary exits ---

@with_all_phases
@spec_state_test
def test_voluntary_exit_basic(spec, state):
    # move state forward SHARD_COMMITTEE_PERIOD epochs to allow for exit
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    signed_exit = prepare_signed_exits(spec, state, [0])[0]
    yield from run_voluntary_exit_processing(spec, state, signed_exit)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_voluntary_exit_sig(spec, state):
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    voluntary_exit = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state), validator_index=0)
    # sign with the wrong key
    signed_exit = sign_voluntary_exit(spec, state, voluntary_exit, privkeys[1])
    yield from run_voluntary_exit_processing(spec, state, signed_exit, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_voluntary_exit_validator_not_long_enough_active(spec, state):
    signed_exit = prepare_signed_exits(spec, state, [0])[0]
    assert spec.get_current_epoch(state) \
        < state.validators[0].activation_epoch + spec.config.SHARD_COMMITTEE_PERIOD
    yield from run_voluntary_exit_processing(spec, state, signed_exit, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_voluntary_exit_already_exited(spec, state):
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    state.validators[0].exit_epoch = spec.get_current_epoch(state) + 2
    signed_exit = prepare_signed_exits(spec, state, [0])[0]
    yield from run_voluntary_exit_processing(spec, state, signed_exit, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_voluntary_exit_in_future(spec, state):
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    voluntary_exit = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state) + 1, validator_index=0)
    signed_exit = sign_voluntary_exit(spec, state, voluntary_exit, privkeys[0])
    yield from run_voluntary_exit_processing(spec, state, signed_exit, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_voluntary_exit_incorrect_validator_index(spec, state):
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    voluntary_exit = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state),
        validator_index=len(state.validators))
    signed_exit = sign_voluntary_exit(spec, state, voluntary_exit, privkeys[0])
    yield from run_voluntary_exit_processing(spec, state, signed_exit, valid=False)


@with_all_phases
@spec_state_test
def test_voluntary_exit_events_consistency(spec, state):
    # two different validators exiting in sequence join the same exit
    # queue epoch until the churn limit binds
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    exits = prepare_signed_exits(spec, state, [0, 1])
    spec.process_voluntary_exit(state, exits[0])
    yield from run_voluntary_exit_processing(spec, state, exits[1])
    assert state.validators[0].exit_epoch == state.validators[1].exit_epoch
