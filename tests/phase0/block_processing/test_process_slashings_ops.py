"""Proposer/attester slashing + voluntary exit operation tests.

Reference: ``test/phase0/block_processing/test_process_proposer_slashing.py``,
``test_process_attester_slashing.py``, ``test_process_voluntary_exit.py``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, always_bls,
)
from consensus_specs_tpu.test_infra.slashings import (
    get_valid_proposer_slashing, run_proposer_slashing_processing,
    get_valid_attester_slashing, run_attester_slashing_processing,
)
from consensus_specs_tpu.test_infra.voluntary_exits import (
    prepare_signed_exits, run_voluntary_exit_processing, sign_voluntary_exit,
)
from consensus_specs_tpu.test_infra.keys import privkeys


# --- proposer slashings ---

@with_all_phases
@spec_state_test
def test_proposer_slashing_basic(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_proposer_slashing_sig_1(spec, state):
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, signed_1=False, signed_2=True)
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashing_identical_headers(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    proposer_slashing.signed_header_2 = proposer_slashing.signed_header_1
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashing_slots_mismatch(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    header = proposer_slashing.signed_header_2.message
    header.slot = header.slot + 1
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashing_repeat(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state)
    slashed_index = proposer_slashing.signed_header_1.message.proposer_index
    spec.process_proposer_slashing(state, proposer_slashing)
    assert state.validators[slashed_index].slashed
    # second identical slashing is invalid (validator no longer slashable)
    yield from run_proposer_slashing_processing(
        spec, state, proposer_slashing, valid=False)


# --- attester slashings ---

@with_all_phases
@spec_state_test
def test_attester_slashing_basic_double(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    yield from run_attester_slashing_processing(spec, state, attester_slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_attester_slashing_sig_2(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=False)
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_attester_slashing_same_data(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    attester_slashing.attestation_2 = attester_slashing.attestation_1
    yield from run_attester_slashing_processing(
        spec, state, attester_slashing, valid=False)


# --- voluntary exits ---

@with_all_phases
@spec_state_test
def test_voluntary_exit_basic(spec, state):
    # move state forward SHARD_COMMITTEE_PERIOD epochs to allow for exit
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    signed_exit = prepare_signed_exits(spec, state, [0])[0]
    yield from run_voluntary_exit_processing(spec, state, signed_exit)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_voluntary_exit_sig(spec, state):
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    voluntary_exit = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state), validator_index=0)
    # sign with the wrong key
    signed_exit = sign_voluntary_exit(spec, state, voluntary_exit, privkeys[1])
    yield from run_voluntary_exit_processing(spec, state, signed_exit, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_voluntary_exit_validator_not_long_enough_active(spec, state):
    signed_exit = prepare_signed_exits(spec, state, [0])[0]
    assert spec.get_current_epoch(state) \
        < state.validators[0].activation_epoch + spec.config.SHARD_COMMITTEE_PERIOD
    yield from run_voluntary_exit_processing(spec, state, signed_exit, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_voluntary_exit_already_exited(spec, state):
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    state.validators[0].exit_epoch = spec.get_current_epoch(state) + 2
    signed_exit = prepare_signed_exits(spec, state, [0])[0]
    yield from run_voluntary_exit_processing(spec, state, signed_exit, valid=False)
