"""process_block_header operation tests.

Reference model: ``test/phase0/block_processing/test_process_block_header.py``
against ``specs/phase0/beacon-chain.md:1711``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root


def _prepare(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block.slot)
    return block


def run_block_header_processing(spec, state, block, valid=True):
    yield "pre", state
    yield "block", block
    if not valid:
        expect_assertion_error(
            lambda: spec.process_block_header(state, block))
        yield "post", None
        return
    spec.process_block_header(state, block)
    yield "post", state


@with_all_phases
@spec_state_test
def test_success_block_header(spec, state):
    block = _prepare(spec, state)
    yield from run_block_header_processing(spec, state, block)
    # latest header caches the block with an empty state root
    assert state.latest_block_header.slot == block.slot
    assert state.latest_block_header.state_root == spec.Root()
    assert state.latest_block_header.body_root == \
        hash_tree_root(block.body)


@with_all_phases
@spec_state_test
def test_invalid_slot_block_header(spec, state):
    block = _prepare(spec, state)
    block.slot = state.slot + 1  # header slot != state slot
    yield from run_block_header_processing(spec, state, block, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_index(spec, state):
    block = _prepare(spec, state)
    active = spec.get_active_validator_indices(
        state, spec.get_current_epoch(state))
    block.proposer_index = (block.proposer_index + 1) % len(active)
    yield from run_block_header_processing(spec, state, block, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_parent_root(spec, state):
    block = _prepare(spec, state)
    block.parent_root = b"\x99" * 32
    yield from run_block_header_processing(spec, state, block, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_multiple_blocks_single_slot(spec, state):
    block = _prepare(spec, state)
    spec.process_block_header(state, block)
    # a second block for the same slot must fail the freshness check
    child = block.copy()
    child.parent_root = hash_tree_root(state.latest_block_header)
    yield from run_block_header_processing(spec, state, child, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashed(spec, state):
    block = _prepare(spec, state)
    state.validators[block.proposer_index].slashed = True
    yield from run_block_header_processing(spec, state, block, valid=False)
