"""process_voluntary_exit operation tests.

Reference model: ``test/phase0/block_processing/test_process_voluntary_exit.py``
against ``specs/phase0/beacon-chain.md`` (process_voluntary_exit).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, always_bls,
)
from consensus_specs_tpu.test_infra.voluntary_exits import (
    prepare_signed_exits, sign_voluntary_exit, run_voluntary_exit_processing,
)
from consensus_specs_tpu.test_infra.keys import privkeys


def _age_state(spec, state):
    state.slot += spec.SLOTS_PER_EPOCH * spec.config.SHARD_COMMITTEE_PERIOD


@with_all_phases
@spec_state_test
def test_success_exit(spec, state):
    _age_state(spec, state)
    signed_exit = prepare_signed_exits(spec, state, [0])[0]
    yield from run_voluntary_exit_processing(spec, state, signed_exit)


@with_all_phases
@spec_state_test
@always_bls
def test_success_exit_queue_churn(spec, state):
    """More exits than the churn limit spread across two epochs."""
    _age_state(spec, state)
    churn_limit = int(spec.get_validator_churn_limit(state))
    indices = list(range(churn_limit + 1))
    signed_exits = prepare_signed_exits(spec, state, indices)
    for signed_exit in signed_exits[:-1]:
        spec.process_voluntary_exit(state, signed_exit)
    yield from run_voluntary_exit_processing(spec, state, signed_exits[-1])
    # the overflow exit lands one epoch later
    first_epoch = state.validators[0].exit_epoch
    assert state.validators[churn_limit].exit_epoch == first_epoch + 1


@with_all_phases
@spec_state_test
def test_invalid_not_active(spec, state):
    _age_state(spec, state)
    index = 0
    state.validators[index].activation_epoch = spec.FAR_FUTURE_EPOCH
    signed_exit = prepare_signed_exits(spec, state, [index])[0]
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
def test_invalid_exit_already_initiated(spec, state):
    _age_state(spec, state)
    index = 0
    spec.initiate_validator_exit(state, index)
    signed_exit = prepare_signed_exits(spec, state, [index])[0]
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
def test_invalid_future_exit_epoch(spec, state):
    _age_state(spec, state)
    index = 0
    exit_msg = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state) + 5, validator_index=index)
    signed_exit = sign_voluntary_exit(spec, state, exit_msg, privkeys[index])
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
def test_invalid_not_active_long_enough(spec, state):
    # fresh genesis: SHARD_COMMITTEE_PERIOD has not elapsed
    signed_exit = prepare_signed_exits(spec, state, [0])[0]
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_signature_wrong_key(spec, state):
    _age_state(spec, state)
    index = 0
    exit_msg = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state), validator_index=index)
    signed_exit = sign_voluntary_exit(spec, state, exit_msg,
                                      privkeys[index + 1])
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
def test_invalid_incorrect_validator_index(spec, state):
    """validator_index out of registry range."""
    _age_state(spec, state)
    exit_msg = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state),
        validator_index=len(state.validators))
    signed_exit = sign_voluntary_exit(spec, state, exit_msg, privkeys[0])
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
def test_default_exit_epoch_subsequent_exit(spec, state):
    """A later exit lands in the same default exit epoch until churn
    fills; the exit queue epoch never moves backwards."""
    _age_state(spec, state)
    signed_exits = prepare_signed_exits(spec, state, [0, 1])
    yield "pre", state
    spec.process_voluntary_exit(state, signed_exits[0])
    first_epoch = state.validators[0].exit_epoch
    spec.process_voluntary_exit(state, signed_exits[1])
    yield "post", state
    assert state.validators[1].exit_epoch >= first_epoch


@with_all_phases
@spec_state_test
def test_exit_queue_spreads_past_churn(spec, state):
    """churn+1 exits in one epoch: the last one lands one epoch later."""
    _age_state(spec, state)
    churn = int(spec.get_validator_churn_limit(state))
    indices = list(range(churn + 1))
    signed_exits = prepare_signed_exits(spec, state, indices)
    yield "pre", state
    for signed_exit in signed_exits:
        spec.process_voluntary_exit(state, signed_exit)
    yield "post", state
    epochs = [int(state.validators[i].exit_epoch) for i in indices]
    assert max(epochs) == min(epochs) + 1
    assert epochs.count(min(epochs)) == churn
