"""Deposit operation tests. Reference: ``test/phase0/block_processing/test_process_deposit.py``."""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, always_bls,
)
from consensus_specs_tpu.test_infra.deposits import (
    prepare_state_and_deposit, run_deposit_processing,
)


@with_all_phases
@spec_state_test
def test_new_deposit_under_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE - 1
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_top_up__max_effective_balance(spec, state):
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    state.balances[validator_index] = spec.MAX_EFFECTIVE_BALANCE
    state.validators[validator_index].effective_balance = spec.MAX_EFFECTIVE_BALANCE
    yield from run_deposit_processing(spec, state, deposit, validator_index)
    assert state.balances[validator_index] == spec.MAX_EFFECTIVE_BALANCE + amount
    assert state.validators[validator_index].effective_balance == spec.MAX_EFFECTIVE_BALANCE


@with_all_phases
@spec_state_test
@always_bls
def test_new_deposit_invalid_sig(spec, state):
    # deposit with bad signature is still "valid" (no-op: validator not added)
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=False)
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, effective=False)


@with_all_phases
@spec_state_test
@always_bls
def test_top_up_invalid_sig(spec, state):
    # top-ups do not verify the signature: still effective
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=False)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_invalid_bad_merkle_proof(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    # break the proof
    deposit.proof[0] = b"\x27" * 32
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_wrong_deposit_for_deposit_count(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    # claim a different outstanding deposit index
    state.eth1_deposit_index = 1
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, valid=False)


@with_all_phases
@spec_state_test
def test_new_deposit_over_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE + 1
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)
    # balance carries the excess; effective balance is capped
    assert state.balances[validator_index] == amount
    assert state.validators[validator_index].effective_balance \
        == spec.MAX_EFFECTIVE_BALANCE


@with_all_phases
@spec_state_test
def test_new_deposit_eth1_withdrawal_credentials(spec, state):
    validator_index = len(state.validators)
    withdrawal_credentials = (
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\x59" * 20)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount,
        withdrawal_credentials=withdrawal_credentials, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)
    assert state.validators[validator_index].withdrawal_credentials \
        == withdrawal_credentials


@with_all_phases
@spec_state_test
def test_new_deposit_non_versioned_withdrawal_credentials(spec, state):
    # any credentials bytes are accepted at deposit time (versioning is
    # enforced at withdrawal, not here)
    validator_index = len(state.validators)
    withdrawal_credentials = b"\xff" * 32
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount,
        withdrawal_credentials=withdrawal_credentials, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_top_up__less_effective_balance(spec, state):
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    initial = spec.MAX_EFFECTIVE_BALANCE - 1000
    state.balances[validator_index] = initial
    state.validators[validator_index].effective_balance = \
        initial - initial % spec.EFFECTIVE_BALANCE_INCREMENT
    yield from run_deposit_processing(spec, state, deposit, validator_index)
    assert state.balances[validator_index] == initial + amount
    # effective balance only updates at the epoch boundary
    assert state.validators[validator_index].effective_balance \
        == initial - initial % spec.EFFECTIVE_BALANCE_INCREMENT


@with_all_phases
@spec_state_test
def test_top_up__zero_balance(spec, state):
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    state.balances[validator_index] = 0
    state.validators[validator_index].effective_balance = 0
    yield from run_deposit_processing(spec, state, deposit, validator_index)
    assert state.balances[validator_index] == amount
    assert state.validators[validator_index].effective_balance == 0


@with_all_phases
@spec_state_test
@always_bls
def test_incorrect_sig_top_up(spec, state):
    # a top-up to an existing validator skips signature verification:
    # the deposit is still EFFECTIVE despite the bad signature
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_incorrect_withdrawal_credentials_top_up(spec, state):
    # top-ups do not check withdrawal credentials; balance still credited
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    withdrawal_credentials = spec.BLS_WITHDRAWAL_PREFIX + b"\x77" * 31
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount,
        withdrawal_credentials=withdrawal_credentials, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
@always_bls
def test_key_validate_invalid_subgroup(spec, state):
    # identity-pubkey deposit: KeyValidate must reject it, deposit is
    # ineffective (no new validator) but the operation itself succeeds
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    pubkey = b"\xc0" + b"\x00" * 47  # compressed point at infinity
    deposit_data_list = []
    from consensus_specs_tpu.test_infra.deposits import deposit_from_context
    deposit_data = spec.DepositData(
        pubkey=pubkey,
        withdrawal_credentials=spec.BLS_WITHDRAWAL_PREFIX + b"\x11" * 31,
        amount=amount,
        signature=b"\x00" * 96,
    )
    deposit_data_list.append(deposit_data)
    deposit, root, _ = deposit_from_context(spec, deposit_data_list, 0)
    state.eth1_deposit_index = 0
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = 1
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, effective=False)


@with_all_phases
@spec_state_test
@always_bls
def test_key_validate_invalid_decompression(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    # 0xff... has the compression flag set but an x >= field modulus:
    # decompression must fail KeyValidate
    from consensus_specs_tpu.test_infra.deposits import deposit_from_context
    deposit_data = spec.DepositData(
        pubkey=b"\xff" * 48,
        withdrawal_credentials=spec.BLS_WITHDRAWAL_PREFIX + b"\x11" * 31,
        amount=amount,
        signature=b"\x00" * 96,
    )
    deposit_data_list = [deposit_data]
    deposit, root, _ = deposit_from_context(spec, deposit_data_list, 0)
    state.eth1_deposit_index = 0
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = 1
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, effective=False)
