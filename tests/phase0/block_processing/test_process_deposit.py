"""Deposit operation tests. Reference: ``test/phase0/block_processing/test_process_deposit.py``."""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, always_bls,
)
from consensus_specs_tpu.test_infra.deposits import (
    prepare_state_and_deposit, run_deposit_processing,
)


@with_all_phases
@spec_state_test
def test_new_deposit_under_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE - 1
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_top_up__max_effective_balance(spec, state):
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    state.balances[validator_index] = spec.MAX_EFFECTIVE_BALANCE
    state.validators[validator_index].effective_balance = spec.MAX_EFFECTIVE_BALANCE
    yield from run_deposit_processing(spec, state, deposit, validator_index)
    assert state.balances[validator_index] == spec.MAX_EFFECTIVE_BALANCE + amount
    assert state.validators[validator_index].effective_balance == spec.MAX_EFFECTIVE_BALANCE


@with_all_phases
@spec_state_test
@always_bls
def test_new_deposit_invalid_sig(spec, state):
    # deposit with bad signature is still "valid" (no-op: validator not added)
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=False)
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, effective=False)


@with_all_phases
@spec_state_test
@always_bls
def test_top_up_invalid_sig(spec, state):
    # top-ups do not verify the signature: still effective
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=False)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_invalid_bad_merkle_proof(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    # break the proof
    deposit.proof[0] = b"\x27" * 32
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_wrong_deposit_for_deposit_count(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    # claim a different outstanding deposit index
    state.eth1_deposit_index = 1
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, valid=False)
