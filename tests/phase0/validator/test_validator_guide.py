"""Honest-validator duties, p2p subnets, weak subjectivity.

Reference model: ``test/phase0/unittests/validator/test_validator_unittest.py``
and the executable blocks of ``specs/phase0/validator.md``,
``specs/phase0/p2p-interface.md:1021``, ``specs/phase0/weak-subjectivity.md``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, with_phases, always_bls, never_bls,
)
from consensus_specs_tpu.test_infra.keys import privkeys, pubkeys
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.block import next_slots
from consensus_specs_tpu.utils import bls


@with_all_phases
@spec_state_test
def test_committee_assignment_covers_all_validators(spec, state):
    epoch = spec.get_current_epoch(state)
    seen = set()
    for index in spec.get_active_validator_indices(state, epoch):
        assignment = spec.get_committee_assignment(state, epoch, index)
        assert assignment is not None
        committee, committee_index, slot = assignment
        assert index in committee
        assert spec.compute_epoch_at_slot(slot) == epoch
        assert committee_index < spec.get_committee_count_per_slot(
            state, epoch)
        seen.add(int(index))
    assert seen == set(
        int(i) for i in spec.get_active_validator_indices(state, epoch))
    # next-epoch lookahead allowed; beyond raises
    assert spec.get_committee_assignment(state, epoch + 1, 0) is not None
    try:
        spec.get_committee_assignment(state, epoch + 2, 0)
        raise SystemExit("two-epoch lookahead must fail")
    except AssertionError:
        pass


@with_all_phases
@spec_state_test
def test_is_proposer_matches_proposer_index(spec, state):
    proposer = spec.get_beacon_proposer_index(state)
    assert spec.is_proposer(state, proposer)
    assert not spec.is_proposer(state, (proposer + 1) % len(state.validators))


@with_all_phases
@spec_state_test
def test_compute_subnet_for_attestation_range(spec, state):
    epoch = spec.get_current_epoch(state)
    committees_per_slot = spec.get_committee_count_per_slot(state, epoch)
    seen = set()
    for slot in range(spec.SLOTS_PER_EPOCH):
        for index in range(committees_per_slot):
            subnet = spec.compute_subnet_for_attestation(
                committees_per_slot, slot, index)
            assert 0 <= subnet < spec.ATTESTATION_SUBNET_COUNT
            seen.add(int(subnet))
    assert len(seen) == min(
        committees_per_slot * spec.SLOTS_PER_EPOCH,
        spec.ATTESTATION_SUBNET_COUNT)


@with_all_phases
@spec_state_test
@always_bls
def test_is_aggregator_selection_deterministic(spec, state):
    slot = state.slot
    committee_index = 0
    committee = spec.get_beacon_committee(state, slot, committee_index)
    # with a minimal committee, modulo is 1 -> everyone aggregates
    modulo = max(1, len(committee) // spec.TARGET_AGGREGATORS_PER_COMMITTEE)
    results = []
    for validator_index in committee[:4]:
        sig = spec.get_slot_signature(state, slot,
                                      privkeys[validator_index])
        results.append(spec.is_aggregator(state, slot, committee_index, sig))
    if modulo == 1:
        assert all(results)


@with_all_phases
@spec_state_test
@always_bls
def test_aggregate_and_proof_roundtrip(spec, state):
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation = get_valid_attestation(spec, state, slot=state.slot - 1,
                                        signed=True)
    aggregator = spec.get_beacon_committee(
        state, attestation.data.slot, attestation.data.index)[0]
    aap = spec.get_aggregate_and_proof(
        state, aggregator, attestation, privkeys[aggregator])
    assert aap.aggregator_index == aggregator
    signature = spec.get_aggregate_and_proof_signature(
        state, aap, privkeys[aggregator])
    # verify against the published pubkey
    domain = spec.get_domain(
        state, spec.DOMAIN_AGGREGATE_AND_PROOF,
        spec.compute_epoch_at_slot(attestation.data.slot))
    signing_root = spec.compute_signing_root(aap, domain)
    assert bls.Verify(pubkeys[aggregator], signing_root, signature)


@with_all_phases
@spec_state_test
def test_eth1_vote_default_and_majority(spec, state):
    # mock genesis uses genesis_time=0, putting the voting period start
    # before any candidate block could exist; give it a real clock
    state.genesis_time = 10**9
    period_start = spec.voting_period_start_time(state)
    follow = (spec.config.SECONDS_PER_ETH1_BLOCK
              * spec.config.ETH1_FOLLOW_DISTANCE)
    blocks = [spec.Eth1Block(timestamp=max(0, period_start - follow - i),
                             deposit_root=spec.Root(bytes([i]) * 32),
                             deposit_count=state.eth1_data.deposit_count + i)
              for i in range(1, 4)]
    vote = spec.get_eth1_vote(state, blocks)
    # no prior votes: default = latest candidate block's data
    assert vote == spec.get_eth1_data(blocks[-1]) or vote == state.eth1_data

    # now cast a majority of votes for one candidate
    target = spec.get_eth1_data(blocks[0])
    for _ in range(2):
        state.eth1_data_votes.append(target)
    vote = spec.get_eth1_vote(state, blocks)
    assert vote == target


@with_all_phases
@spec_state_test
def test_compute_subscribed_subnets(spec, state):
    for node_id in (0, 1, 2**255 + 12345):
        subnets = spec.compute_subscribed_subnets(node_id, epoch=5)
        assert len(subnets) == spec.SUBNETS_PER_NODE
        for s in subnets:
            assert 0 <= s < spec.ATTESTATION_SUBNET_COUNT
        # stable within the subscription period
        assert subnets == spec.compute_subscribed_subnets(node_id, epoch=5)


@with_phases(["phase0"])
@spec_state_test
def test_weak_subjectivity_period(spec, state):
    ws_period = spec.compute_weak_subjectivity_period(state)
    # at least the withdrawability delay
    assert ws_period >= spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY

    # store within the period accepts; far-future store rejects
    header = state.latest_block_header.copy()
    if header.state_root == spec.Root():
        header.state_root = spec.hash_tree_root(state)
    ws_checkpoint = spec.Checkpoint(
        epoch=spec.compute_epoch_at_slot(state.slot), root=header.state_root)

    class _Store:
        time = int(state.genesis_time
                   + spec.config.SECONDS_PER_SLOT * state.slot)
        genesis_time = int(state.genesis_time)
    ws_state = state.copy()
    ws_state.latest_block_header.state_root = header.state_root
    assert spec.is_within_weak_subjectivity_period(
        _Store(), ws_state, ws_checkpoint)

    far_future_time = int(state.genesis_time + spec.config.SECONDS_PER_SLOT
                          * (state.slot + (int(ws_period) + 2)
                             * spec.SLOTS_PER_EPOCH))

    class _LateStore:
        time = far_future_time
        genesis_time = int(state.genesis_time)
    assert not spec.is_within_weak_subjectivity_period(
        _LateStore(), ws_state, ws_checkpoint)


@with_phases(["altair", "bellatrix", "capella", "deneb"])
@spec_state_test
@always_bls
def test_sync_committee_duties(spec, state):
    committee_pubkeys = list(state.current_sync_committee.pubkeys)
    all_pubkeys = [bytes(v.pubkey) for v in state.validators]
    validator_index = all_pubkeys.index(bytes(committee_pubkeys[0]))

    # message construction + signature verifies
    block_root = spec.Root(b"\x25" * 32)
    msg = spec.get_sync_committee_message(
        state, block_root, validator_index, privkeys[validator_index])
    domain = spec.get_domain(state, spec.DOMAIN_SYNC_COMMITTEE,
                             spec.get_current_epoch(state))
    signing_root = spec.compute_signing_root(block_root, domain)
    assert bls.Verify(pubkeys[validator_index], signing_root, msg.signature)

    # subnets for a committee member are in range and non-empty
    subnets = spec.compute_subnets_for_sync_committee(state, validator_index)
    assert subnets and all(
        0 <= s < spec.SYNC_COMMITTEE_SUBNET_COUNT for s in subnets)

    # selection proof + aggregator determinism
    proof = spec.get_sync_committee_selection_proof(
        state, state.slot, list(subnets)[0], privkeys[validator_index])
    assert isinstance(spec.is_sync_committee_aggregator(proof), bool)

    # contribution-and-proof signature verifies
    contribution = spec.SyncCommitteeContribution(
        slot=state.slot, beacon_block_root=block_root,
        subcommittee_index=list(subnets)[0])
    contribution.aggregation_bits[0] = True
    contribution.signature = msg.signature
    cap = spec.get_contribution_and_proof(
        state, validator_index, contribution, privkeys[validator_index])
    sig = spec.get_contribution_and_proof_signature(
        state, cap, privkeys[validator_index])
    domain = spec.get_domain(state, spec.DOMAIN_CONTRIBUTION_AND_PROOF,
                             spec.compute_epoch_at_slot(contribution.slot))
    signing_root = spec.compute_signing_root(cap, domain)
    assert bls.Verify(pubkeys[validator_index], signing_root, sig)


@with_all_phases
@spec_state_test
@never_bls
def test_committee_assignment_none_outside_lookahead(spec, state):
    """Assignments exist for current/next epoch only; further epochs
    raise (the spec's lookahead bound)."""
    epoch = spec.get_current_epoch(state)
    assert spec.get_committee_assignment(state, epoch, 0) is not None
    try:
        spec.get_committee_assignment(state, epoch + 2, 0)
        raised = False
    except AssertionError:
        raised = True
    assert raised


@with_all_phases
@spec_state_test
@never_bls
def test_committee_assignment_next_epoch(spec, state):
    """Next-epoch assignments are computable (duty lookahead)."""
    epoch = spec.get_current_epoch(state) + 1
    found = 0
    for index in range(len(state.validators)):
        a = spec.get_committee_assignment(state, epoch, index)
        if a is not None:
            committee, committee_index, slot = a
            assert index in committee
            assert spec.compute_epoch_at_slot(slot) == epoch
            found += 1
    assert found == len(state.validators)  # all active at genesis


@with_all_phases
@spec_state_test
@never_bls
def test_compute_time_at_slot_linear(spec, state):
    t0 = spec.compute_time_at_slot(state, 0)
    assert t0 == state.genesis_time
    assert spec.compute_time_at_slot(state, 5) == \
        state.genesis_time + 5 * spec.config.SECONDS_PER_SLOT


@with_all_phases
@spec_state_test
@never_bls
def test_eth1_candidate_block_window(spec, state):
    """is_candidate_block bounds: inside [period_start - 2*follow*T,
    period_start - follow*T]."""
    follow = int(spec.config.ETH1_FOLLOW_DISTANCE)
    sec = int(spec.config.SECONDS_PER_ETH1_BLOCK)
    period_start = spec.voting_period_start_time(state)

    class Blk:
        def __init__(self, ts):
            self.timestamp = ts

    lo = period_start - 2 * follow * sec
    hi = period_start - follow * sec
    assert spec.is_candidate_block(Blk(lo), period_start)
    assert spec.is_candidate_block(Blk(hi), period_start)
    assert not spec.is_candidate_block(Blk(hi + sec), period_start)
    assert not spec.is_candidate_block(Blk(lo - sec), period_start)


@with_all_phases
@spec_state_test
@never_bls
def test_aggregator_modulus_floor(spec, state):
    """is_aggregator survives committees smaller than
    TARGET_AGGREGATORS_PER_COMMITTEE (the max(1, ...) modulus floor:
    every member becomes an aggregator instead of div-by-zero)."""
    committee = spec.get_beacon_committee(state, state.slot, 0)
    sig = spec.get_slot_signature(state, state.slot,
                                  privkeys[int(committee[0])])
    result = spec.is_aggregator(state, state.slot, 0, sig)
    assert isinstance(result, bool)
    if len(committee) <= spec.TARGET_AGGREGATORS_PER_COMMITTEE:
        # modulus floors at 1: everyone aggregates
        assert result is True


@with_all_phases
@spec_state_test
@never_bls
def test_subscribed_subnets_stable_within_seed_window(spec, state):
    """Subscriptions are a pure function of the node's rotation window:
    the window index is (epoch + node_id % period) // period, so two
    epochs in the SAME window give identical subnets and the window
    boundary rotates them (p2p-interface.md compute_subscribed_subnet)."""
    node_id = 0x1234567890ABCDEF
    period = int(spec.config.EPOCHS_PER_SUBNET_SUBSCRIPTION)
    offset = node_id % period
    # pick two epochs inside one window, and one past its boundary
    window_start = period - offset      # first epoch of window 1
    a = list(spec.compute_subscribed_subnets(node_id, window_start))
    b = list(spec.compute_subscribed_subnets(node_id,
                                             window_start + period - 1))
    c = list(spec.compute_subscribed_subnets(node_id,
                                             window_start + period))
    assert a == b                       # same window: stable
    assert all(0 <= s < spec.config.ATTESTATION_SUBNET_COUNT
               for s in a + c)
    # determinism
    assert a == list(spec.compute_subscribed_subnets(node_id,
                                                     window_start))


@with_all_phases
@spec_state_test
@never_bls
def test_subscribed_subnets_depend_on_node_prefix(spec, state):
    """The subnet choice keys on the node id's HIGH bits (the DHT
    prefix), so nodes with different prefixes spread across subnets."""
    # 256-bit node ids differing in their top bits
    ids = [(i << 248) | 0xABC for i in (1, 37, 99, 201)]
    sets = {tuple(spec.compute_subscribed_subnets(nid, 0)) for nid in ids}
    assert len(sets) > 1


@with_all_phases
@spec_state_test
@never_bls
def test_proposer_uniqueness_per_slot(spec, state):
    """Exactly one validator believes it proposes each slot."""
    proposers = [index for index in range(len(state.validators))
                 if spec.is_proposer(state, index)]
    assert len(proposers) == 1
    assert proposers[0] == spec.get_beacon_proposer_index(state)
