"""Honest-validator duties, p2p subnets, weak subjectivity.

Reference model: ``test/phase0/unittests/validator/test_validator_unittest.py``
and the executable blocks of ``specs/phase0/validator.md``,
``specs/phase0/p2p-interface.md:1021``, ``specs/phase0/weak-subjectivity.md``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, with_phases, always_bls,
)
from consensus_specs_tpu.test_infra.keys import privkeys, pubkeys
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.block import next_slots
from consensus_specs_tpu.utils import bls


@with_all_phases
@spec_state_test
def test_committee_assignment_covers_all_validators(spec, state):
    epoch = spec.get_current_epoch(state)
    seen = set()
    for index in spec.get_active_validator_indices(state, epoch):
        assignment = spec.get_committee_assignment(state, epoch, index)
        assert assignment is not None
        committee, committee_index, slot = assignment
        assert index in committee
        assert spec.compute_epoch_at_slot(slot) == epoch
        assert committee_index < spec.get_committee_count_per_slot(
            state, epoch)
        seen.add(int(index))
    assert seen == set(
        int(i) for i in spec.get_active_validator_indices(state, epoch))
    # next-epoch lookahead allowed; beyond raises
    assert spec.get_committee_assignment(state, epoch + 1, 0) is not None
    try:
        spec.get_committee_assignment(state, epoch + 2, 0)
        raise SystemExit("two-epoch lookahead must fail")
    except AssertionError:
        pass


@with_all_phases
@spec_state_test
def test_is_proposer_matches_proposer_index(spec, state):
    proposer = spec.get_beacon_proposer_index(state)
    assert spec.is_proposer(state, proposer)
    assert not spec.is_proposer(state, (proposer + 1) % len(state.validators))


@with_all_phases
@spec_state_test
def test_compute_subnet_for_attestation_range(spec, state):
    epoch = spec.get_current_epoch(state)
    committees_per_slot = spec.get_committee_count_per_slot(state, epoch)
    seen = set()
    for slot in range(spec.SLOTS_PER_EPOCH):
        for index in range(committees_per_slot):
            subnet = spec.compute_subnet_for_attestation(
                committees_per_slot, slot, index)
            assert 0 <= subnet < spec.ATTESTATION_SUBNET_COUNT
            seen.add(int(subnet))
    assert len(seen) == min(
        committees_per_slot * spec.SLOTS_PER_EPOCH,
        spec.ATTESTATION_SUBNET_COUNT)


@with_all_phases
@spec_state_test
@always_bls
def test_is_aggregator_selection_deterministic(spec, state):
    slot = state.slot
    committee_index = 0
    committee = spec.get_beacon_committee(state, slot, committee_index)
    # with a minimal committee, modulo is 1 -> everyone aggregates
    modulo = max(1, len(committee) // spec.TARGET_AGGREGATORS_PER_COMMITTEE)
    results = []
    for validator_index in committee[:4]:
        sig = spec.get_slot_signature(state, slot,
                                      privkeys[validator_index])
        results.append(spec.is_aggregator(state, slot, committee_index, sig))
    if modulo == 1:
        assert all(results)


@with_all_phases
@spec_state_test
@always_bls
def test_aggregate_and_proof_roundtrip(spec, state):
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation = get_valid_attestation(spec, state, slot=state.slot - 1,
                                        signed=True)
    aggregator = spec.get_beacon_committee(
        state, attestation.data.slot, attestation.data.index)[0]
    aap = spec.get_aggregate_and_proof(
        state, aggregator, attestation, privkeys[aggregator])
    assert aap.aggregator_index == aggregator
    signature = spec.get_aggregate_and_proof_signature(
        state, aap, privkeys[aggregator])
    # verify against the published pubkey
    domain = spec.get_domain(
        state, spec.DOMAIN_AGGREGATE_AND_PROOF,
        spec.compute_epoch_at_slot(attestation.data.slot))
    signing_root = spec.compute_signing_root(aap, domain)
    assert bls.Verify(pubkeys[aggregator], signing_root, signature)


@with_all_phases
@spec_state_test
def test_eth1_vote_default_and_majority(spec, state):
    # mock genesis uses genesis_time=0, putting the voting period start
    # before any candidate block could exist; give it a real clock
    state.genesis_time = 10**9
    period_start = spec.voting_period_start_time(state)
    follow = (spec.config.SECONDS_PER_ETH1_BLOCK
              * spec.config.ETH1_FOLLOW_DISTANCE)
    blocks = [spec.Eth1Block(timestamp=max(0, period_start - follow - i),
                             deposit_root=spec.Root(bytes([i]) * 32),
                             deposit_count=state.eth1_data.deposit_count + i)
              for i in range(1, 4)]
    vote = spec.get_eth1_vote(state, blocks)
    # no prior votes: default = latest candidate block's data
    assert vote == spec.get_eth1_data(blocks[-1]) or vote == state.eth1_data

    # now cast a majority of votes for one candidate
    target = spec.get_eth1_data(blocks[0])
    for _ in range(2):
        state.eth1_data_votes.append(target)
    vote = spec.get_eth1_vote(state, blocks)
    assert vote == target


@with_all_phases
@spec_state_test
def test_compute_subscribed_subnets(spec, state):
    for node_id in (0, 1, 2**255 + 12345):
        subnets = spec.compute_subscribed_subnets(node_id, epoch=5)
        assert len(subnets) == spec.SUBNETS_PER_NODE
        for s in subnets:
            assert 0 <= s < spec.ATTESTATION_SUBNET_COUNT
        # stable within the subscription period
        assert subnets == spec.compute_subscribed_subnets(node_id, epoch=5)


@with_phases(["phase0"])
@spec_state_test
def test_weak_subjectivity_period(spec, state):
    ws_period = spec.compute_weak_subjectivity_period(state)
    # at least the withdrawability delay
    assert ws_period >= spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY

    # store within the period accepts; far-future store rejects
    header = state.latest_block_header.copy()
    if header.state_root == spec.Root():
        header.state_root = spec.hash_tree_root(state)
    ws_checkpoint = spec.Checkpoint(
        epoch=spec.compute_epoch_at_slot(state.slot), root=header.state_root)

    class _Store:
        time = int(state.genesis_time
                   + spec.config.SECONDS_PER_SLOT * state.slot)
        genesis_time = int(state.genesis_time)
    ws_state = state.copy()
    ws_state.latest_block_header.state_root = header.state_root
    assert spec.is_within_weak_subjectivity_period(
        _Store(), ws_state, ws_checkpoint)

    far_future_time = int(state.genesis_time + spec.config.SECONDS_PER_SLOT
                          * (state.slot + (int(ws_period) + 2)
                             * spec.SLOTS_PER_EPOCH))

    class _LateStore:
        time = far_future_time
        genesis_time = int(state.genesis_time)
    assert not spec.is_within_weak_subjectivity_period(
        _LateStore(), ws_state, ws_checkpoint)


@with_phases(["altair", "bellatrix", "capella", "deneb"])
@spec_state_test
@always_bls
def test_sync_committee_duties(spec, state):
    committee_pubkeys = list(state.current_sync_committee.pubkeys)
    all_pubkeys = [bytes(v.pubkey) for v in state.validators]
    validator_index = all_pubkeys.index(bytes(committee_pubkeys[0]))

    # message construction + signature verifies
    block_root = spec.Root(b"\x25" * 32)
    msg = spec.get_sync_committee_message(
        state, block_root, validator_index, privkeys[validator_index])
    domain = spec.get_domain(state, spec.DOMAIN_SYNC_COMMITTEE,
                             spec.get_current_epoch(state))
    signing_root = spec.compute_signing_root(block_root, domain)
    assert bls.Verify(pubkeys[validator_index], signing_root, msg.signature)

    # subnets for a committee member are in range and non-empty
    subnets = spec.compute_subnets_for_sync_committee(state, validator_index)
    assert subnets and all(
        0 <= s < spec.SYNC_COMMITTEE_SUBNET_COUNT for s in subnets)

    # selection proof + aggregator determinism
    proof = spec.get_sync_committee_selection_proof(
        state, state.slot, list(subnets)[0], privkeys[validator_index])
    assert isinstance(spec.is_sync_committee_aggregator(proof), bool)

    # contribution-and-proof signature verifies
    contribution = spec.SyncCommitteeContribution(
        slot=state.slot, beacon_block_root=block_root,
        subcommittee_index=list(subnets)[0])
    contribution.aggregation_bits[0] = True
    contribution.signature = msg.signature
    cap = spec.get_contribution_and_proof(
        state, validator_index, contribution, privkeys[validator_index])
    sig = spec.get_contribution_and_proof_signature(
        state, cap, privkeys[validator_index])
    domain = spec.get_domain(state, spec.DOMAIN_CONTRIBUTION_AND_PROOF,
                             spec.compute_epoch_at_slot(contribution.slot))
    signing_root = spec.compute_signing_root(cap, domain)
    assert bls.Verify(pubkeys[validator_index], signing_root, sig)
