"""Genesis initialization and validity tests.

Reference model: ``test/phase0/genesis/test_initialization.py`` /
``test_validity.py`` against ``initialize_beacon_state_from_eth1``
(``specs/phase0/beacon-chain.md:1195``) and ``is_valid_genesis_state``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_test, with_phases, with_presets, single_phase,
)
from consensus_specs_tpu.test_infra.deposits import (
    prepare_full_genesis_deposits,
)
from consensus_specs_tpu.gen.gen_runner import YamlPart
from consensus_specs_tpu.utils.ssz import hash_tree_root


def _eth1_params(spec):
    return spec.Hash32(b"\x12" * 32), spec.uint64(1578009600)


@with_phases(["phase0"])
@with_presets(["minimal"], reason="mainnet genesis counts exceed the test key pool")
@spec_test
@single_phase
def test_initialize_beacon_state_from_eth1(spec):
    deposit_count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    deposits, deposit_root, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, deposit_count, signed=True)
    eth1_block_hash, eth1_timestamp = _eth1_params(spec)

    yield "eth1_block_hash", eth1_block_hash
    yield "eth1_timestamp", eth1_timestamp
    yield "deposits", deposits

    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, eth1_timestamp, deposits)

    assert state.genesis_time == \
        eth1_timestamp + spec.config.GENESIS_DELAY
    assert len(state.validators) == deposit_count
    assert state.eth1_data.deposit_root == deposit_root
    assert state.eth1_data.deposit_count == deposit_count
    assert state.eth1_data.block_hash == eth1_block_hash
    assert spec.get_total_active_balance(state) == \
        deposit_count * spec.MAX_EFFECTIVE_BALANCE
    # every genesis validator activated immediately
    for v in state.validators:
        assert v.activation_epoch == spec.GENESIS_EPOCH
    assert state.genesis_validators_root == hash_tree_root(state.validators)
    yield "state", state


@with_phases(["phase0"])
@with_presets(["minimal"], reason="mainnet genesis counts exceed the test key pool")
@spec_test
@single_phase
def test_initialize_duplicate_pubkey_deposit_tops_up(spec):
    """A second deposit for an existing pubkey adds balance, not a
    validator (beacon-chain.md:1877 apply_deposit else-branch)."""
    deposit_count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT + 1
    deposits, _, deposit_data_list = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, deposit_count, signed=True,
        duplicate_last=True)
    eth1_block_hash, eth1_timestamp = _eth1_params(spec)
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, eth1_timestamp, deposits)
    # one fewer validator than deposits; the duplicate topped up instead
    assert len(state.validators) == deposit_count - 1
    assert state.balances[deposit_count - 2] == \
        2 * spec.MAX_EFFECTIVE_BALANCE


@with_phases(["phase0"])
@with_presets(["minimal"], reason="mainnet genesis counts exceed the test key pool")
@spec_test
@single_phase
def test_is_valid_genesis_state_true(spec):
    deposit_count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    deposits, _, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, deposit_count, signed=True)
    eth1_block_hash, eth1_timestamp = _eth1_params(spec)
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, eth1_timestamp, deposits)
    yield "genesis", state
    assert spec.is_valid_genesis_state(state)
    yield "is_valid", YamlPart(value=True)


@with_phases(["phase0"])
@with_presets(["minimal"], reason="mainnet genesis counts exceed the test key pool")
@spec_test
@single_phase
def test_is_valid_genesis_state_false_invalid_timestamp(spec):
    deposit_count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    deposits, _, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, deposit_count, signed=True)
    eth1_block_hash, _ = _eth1_params(spec)
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, spec.uint64(0), deposits)
    if spec.config.MIN_GENESIS_TIME > spec.config.GENESIS_DELAY:
        yield "genesis", state
        assert not spec.is_valid_genesis_state(state)
        yield "is_valid", YamlPart(value=False)


@with_phases(["phase0"])
@with_presets(["minimal"], reason="mainnet genesis counts exceed the test key pool")
@spec_test
@single_phase
def test_is_valid_genesis_state_false_not_enough_validators(spec):
    deposit_count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT - 1
    deposits, _, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, deposit_count, signed=True)
    eth1_block_hash, eth1_timestamp = _eth1_params(spec)
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, eth1_timestamp, deposits)
    yield "genesis", state
    assert not spec.is_valid_genesis_state(state)
    yield "is_valid", YamlPart(value=False)


@with_phases(["phase0"])
@with_presets(["minimal"], reason="mainnet genesis counts exceed the test key pool")
@spec_test
@single_phase
def test_initialize_beacon_state_some_small_balances(spec):
    # half the deposits carry max balance, half only half: small-balance
    # depositors are registered but NOT active at genesis
    count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    full, root_full, dlist = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, count, signed=True)
    small, deposit_root, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE // 2, count // 2, signed=True,
        deposit_data_list=dlist, min_pubkey_index=count)
    deposits = full + small
    eth1_block_hash, eth1_timestamp = _eth1_params(spec)
    yield "eth1_block_hash", eth1_block_hash
    yield "eth1_timestamp", eth1_timestamp
    yield "deposits", deposits
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, eth1_timestamp, deposits)
    assert len(state.validators) == count + count // 2
    active = spec.get_active_validator_indices(state, spec.GENESIS_EPOCH)
    assert len(active) == count
    assert spec.is_valid_genesis_state(state)
    yield "state", state


@with_phases(["phase0"])
@with_presets(["minimal"], reason="mainnet genesis counts exceed the test key pool")
@spec_test
@single_phase
def test_initialize_beacon_state_one_topup_activation(spec):
    # a deposit at half balance plus a top-up for the same key reaches
    # the activation threshold at genesis
    count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    full, _, dlist = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, count - 1, signed=True)
    half1, _, dlist = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE // 2, 1, signed=True,
        deposit_data_list=dlist, min_pubkey_index=count - 1)
    half2, _, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE // 2, 1, signed=True,
        deposit_data_list=dlist, min_pubkey_index=count - 1)
    deposits = full + half1 + half2
    eth1_block_hash, eth1_timestamp = _eth1_params(spec)
    yield "eth1_block_hash", eth1_block_hash
    yield "eth1_timestamp", eth1_timestamp
    yield "deposits", deposits
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, eth1_timestamp, deposits)
    assert len(state.validators) == count
    active = spec.get_active_validator_indices(state, spec.GENESIS_EPOCH)
    assert len(active) == count
    assert spec.is_valid_genesis_state(state)
    yield "state", state


@with_phases(["phase0"])
@with_presets(["minimal"], reason="mainnet genesis counts exceed the test key pool")
@spec_test
@single_phase
def test_is_valid_genesis_state_true_one_more_validator(spec):
    count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT + 1
    deposits, _, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, count, signed=True)
    eth1_block_hash, eth1_timestamp = _eth1_params(spec)
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, eth1_timestamp, deposits)
    assert spec.is_valid_genesis_state(state)
    yield "state", state


@with_phases(["phase0"])
@with_presets(["minimal"], reason="mainnet genesis counts exceed the test key pool")
@spec_test
@single_phase
def test_is_valid_genesis_state_true_extra_balance(spec):
    # over-max deposits still count once toward the active threshold
    count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    deposits, _, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE + spec.EFFECTIVE_BALANCE_INCREMENT,
        count, signed=True)
    eth1_block_hash, eth1_timestamp = _eth1_params(spec)
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, eth1_timestamp, deposits)
    assert spec.is_valid_genesis_state(state)
    yield "state", state
