"""Sanity whole-block transition tests.

Reference: ``test/phase0/sanity/test_blocks.py``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, always_bls, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, build_empty_block, state_transition_and_sign_block, sign_block, next_epoch, next_slots)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.slashings import get_valid_proposer_slashing


@with_all_phases
@spec_state_test
def test_empty_block_transition(spec, state):
    pre_slot = state.slot
    pre_eth1_votes = len(state.eth1_data_votes)
    pre_mix = spec.get_randao_mix(state, spec.get_current_epoch(state))

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state

    assert state.slot == pre_slot + 1
    assert len(state.eth1_data_votes) == pre_eth1_votes + 1
    assert spec.get_block_root_at_slot(state, pre_slot) == block.parent_root
    assert spec.get_randao_mix(state, spec.get_current_epoch(state)) != pre_mix


@with_all_phases
@spec_state_test
def test_skipped_slots(spec, state):
    yield "pre", state
    block = build_empty_block(spec, state, state.slot + 4)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state

    assert state.slot == block.slot
    assert spec.get_randao_mix(state, spec.get_current_epoch(state)) != spec.Bytes32()
    for slot in range(int(block.slot) - 4, int(block.slot)):
        assert spec.get_block_root_at_slot(state, slot) == block.parent_root


@with_all_phases
@spec_state_test
def test_empty_epoch_transition(spec, state):
    pre_slot = state.slot
    yield "pre", state
    block = build_empty_block(spec, state, state.slot + spec.SLOTS_PER_EPOCH)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state

    assert state.slot == block.slot
    for slot in range(int(pre_slot), int(state.slot)):
        assert spec.get_block_root_at_slot(state, slot) == block.parent_root


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_state_root(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.state_root = b"\xaa" * 32
    signed_block = sign_block(spec, state, block)
    expect_assertion_error(
        lambda: spec.state_transition(state, signed_block))
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_block_sig(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    # transition on a copy to compute the correct state root, then break sig
    tmp_state = state.copy()
    signed_block = state_transition_and_sign_block(spec, tmp_state, block)
    invalid_signed_block = spec.SignedBeaconBlock(message=block)  # empty signature
    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))
    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_invalid_proposer_index_sig_from_expected_proposer(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    expect_proposer_index = block.proposer_index
    # set invalid proposer index but correct everything else
    active = spec.get_active_validator_indices(state, spec.get_current_epoch(state))
    block.proposer_index = (expect_proposer_index + 1) % len(active)
    invalid_signed_block = sign_block(spec, state, block, expect_proposer_index)
    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))
    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_prev_slot_block_transition(spec, state):
    spec.process_slots(state, state.slot + 1)
    block = build_empty_block(spec, state, slot=state.slot)
    proposer_index = spec.get_beacon_proposer_index(state)
    spec.process_slots(state, state.slot + 1)
    yield "pre", state
    signed_block = sign_block(spec, state, block, proposer_index)
    expect_assertion_error(
        lambda: spec.state_transition(state, signed_block))
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_attestation(spec, state):
    next_epoch(spec, state)
    yield "pre", state

    attestation_block = build_empty_block(
        spec, state, state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    index = 0
    attestation = get_valid_attestation(spec, state, index=index, signed=True)

    # attestation is valid already MIN_ATTESTATION_INCLUSION_DELAY slots later
    attestation_block.body.attestations.append(attestation)
    signed_attestation_block = state_transition_and_sign_block(
        spec, state, attestation_block)

    if spec.fork == "phase0":
        assert len(state.current_epoch_attestations) == 1
    else:
        assert any(f != 0 for f in state.current_epoch_participation)

    yield "blocks", [signed_attestation_block]
    yield "post", state


@with_all_phases
@spec_state_test
def test_proposer_slashing_block(spec, state):
    # copy for later balance comparison
    pre_state = state.copy()
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, signed_1=True, signed_2=True)
    slashed_index = proposer_slashing.signed_header_1.message.proposer_index

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings.append(proposer_slashing)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state

    slashed_validator = state.validators[slashed_index]
    assert slashed_validator.slashed
    assert slashed_validator.exit_epoch < spec.FAR_FUTURE_EPOCH
    assert slashed_validator.withdrawable_epoch < spec.FAR_FUTURE_EPOCH
    assert state.balances[slashed_index] < pre_state.balances[slashed_index]


@with_all_phases
@spec_state_test
def test_duplicate_attestation_same_block(spec, state):
    next_epoch(spec, state)
    yield "pre", state
    block = build_empty_block(
        spec, state, state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation = get_valid_attestation(spec, state, index=0, signed=True)
    for _ in range(2):
        block.body.attestations.append(attestation)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    if spec.fork == "phase0":
        # duplicates are valid in phase0 (both become pending attestations)
        assert len(state.current_epoch_attestations) == 2
    else:
        # altair+: the second copy grants no new flags (idempotent)
        assert any(f != 0 for f in state.current_epoch_participation)


@with_all_phases
@spec_state_test
def test_invalid_same_slot_block_transition(spec, state):
    # a block for the CURRENT slot (already processed) is invalid
    spec.process_slots(state, state.slot + 1)
    block = build_empty_block(spec, state, slot=state.slot)
    yield "pre", state
    signed_block = sign_block(spec, state, block)
    expect_assertion_error(
        lambda: spec.state_transition(state, signed_block))
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_invalid_proposal_for_genesis_slot(spec, state):
    assert state.slot == spec.GENESIS_SLOT
    block = build_empty_block(spec, state, slot=spec.GENESIS_SLOT)
    block.parent_root = state.latest_block_header.parent_root
    yield "pre", state
    signed_block = sign_block(spec, state, block)
    expect_assertion_error(
        lambda: spec.state_transition(state, signed_block))
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_invalid_parent_from_same_slot(spec, state):
    yield "pre", state
    parent_block = build_empty_block_for_next_slot(spec, state)
    signed_parent = state_transition_and_sign_block(spec, state, parent_block)
    child_block = parent_block.copy()
    child_block.parent_root = state.latest_block_header.parent_root
    # same-slot child of the parent's parent: header check must fail
    signed_child = sign_block(spec, state, child_block)
    expect_assertion_error(
        lambda: spec.state_transition(state, signed_child))
    yield "blocks", [signed_parent, signed_child]
    yield "post", None


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_all_zeroed_sig(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    tmp_state = state.copy()
    state_transition_and_sign_block(spec, tmp_state, block)
    invalid_signed_block = spec.SignedBeaconBlock(
        message=block, signature=b"\x00" * 96)
    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))
    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_invalid_proposer_index_sig_from_proposer_index(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    expect_proposer_index = int(block.proposer_index)
    active = spec.get_active_validator_indices(
        state, spec.get_current_epoch(state))
    wrong_index = (expect_proposer_index + 1) % len(active)
    block.proposer_index = wrong_index
    # signed by the CLAIMED (wrong) proposer: index check must fail
    invalid_signed_block = sign_block(spec, state, block, wrong_index)
    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))
    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_proposer_self_slashing(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, proposer_index=int(block.proposer_index),
        signed_1=True, signed_2=True)
    block.body.proposer_slashings.append(proposer_slashing)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.validators[block.proposer_index].slashed


@with_all_phases
@spec_state_test
def test_invalid_duplicate_proposer_slashings_same_block(spec, state):
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, signed_1=True, signed_2=True)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings.append(proposer_slashing)
    block.body.proposer_slashings.append(proposer_slashing)
    signed_block = sign_block_after_failed_transition(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_multiple_different_proposer_slashings_same_block(spec, state):
    from consensus_specs_tpu.test_infra.slashings import (
        get_valid_proposer_slashing as _gvps)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    proposer = int(block.proposer_index)
    indices = [i for i in range(len(state.validators)) if i != proposer][:2]
    for index in indices:
        block.body.proposer_slashings.append(_gvps(
            spec, state, proposer_index=index,
            signed_1=True, signed_2=True))
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    for index in indices:
        assert state.validators[index].slashed


@with_all_phases
@spec_state_test
def test_attester_slashing(spec, state):
    from consensus_specs_tpu.test_infra.slashings import (
        get_valid_attester_slashing)
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    slashed = set(attester_slashing.attestation_1.attesting_indices) \
        .intersection(attester_slashing.attestation_2.attesting_indices)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attester_slashings.append(attester_slashing)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    for index in slashed:
        assert state.validators[index].slashed


@with_all_phases
@spec_state_test
def test_invalid_duplicate_attester_slashing_same_block(spec, state):
    from consensus_specs_tpu.test_infra.slashings import (
        get_valid_attester_slashing)
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attester_slashings.append(attester_slashing)
    block.body.attester_slashings.append(attester_slashing)
    signed_block = sign_block_after_failed_transition(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_deposit_in_block(spec, state):
    from consensus_specs_tpu.test_infra.deposits import (
        prepare_state_and_deposit)
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.deposits.append(deposit)
    block.body.eth1_data.deposit_count = state.eth1_data.deposit_count
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert len(state.validators) == validator_index + 1
    assert state.balances[validator_index] == amount


@with_all_phases
@spec_state_test
def test_deposit_top_up(spec, state):
    from consensus_specs_tpu.test_infra.deposits import (
        prepare_state_and_deposit)
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    # baseline: the same empty block without the deposit (isolates the
    # top-up from per-block sync-committee rewards/penalties in altair+)
    baseline = state.copy()
    state_transition_and_sign_block(
        spec, baseline, build_empty_block_for_next_slot(spec, baseline))
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    pre_count = len(state.validators)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.deposits.append(deposit)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert len(state.validators) == pre_count
    assert state.balances[validator_index] \
        == baseline.balances[validator_index] + amount


@with_all_phases
@spec_state_test
def test_voluntary_exit(spec, state):
    from consensus_specs_tpu.test_infra.voluntary_exits import (
        prepare_signed_exits)
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    signed_exit = prepare_signed_exits(spec, state, [0])[0]
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.voluntary_exits.append(signed_exit)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.validators[0].exit_epoch < spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_invalid_duplicate_validator_exit_same_block(spec, state):
    from consensus_specs_tpu.test_infra.voluntary_exits import (
        prepare_signed_exits)
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    signed_exit = prepare_signed_exits(spec, state, [0])[0]
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.voluntary_exits.append(signed_exit)
    block.body.voluntary_exits.append(signed_exit)
    signed_block = sign_block_after_failed_transition(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_multiple_different_validator_exits_same_block(spec, state):
    from consensus_specs_tpu.test_infra.voluntary_exits import (
        prepare_signed_exits)
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    exits = prepare_signed_exits(spec, state, [0, 1, 2])
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    for signed_exit in exits:
        block.body.voluntary_exits.append(signed_exit)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    for index in (0, 1, 2):
        assert state.validators[index].exit_epoch < spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_slash_and_exit_same_index(spec, state):
    # slashing and a voluntary exit for the SAME validator in one block:
    # the exit must fail (validator no longer active at exit processing)
    from consensus_specs_tpu.test_infra.slashings import (
        get_valid_proposer_slashing as _gvps)
    from consensus_specs_tpu.test_infra.voluntary_exits import (
        prepare_signed_exits)
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    proposer = int(build_empty_block_for_next_slot(spec, state).proposer_index)
    index = (proposer + 1) % len(state.validators)
    slashing = _gvps(spec, state, proposer_index=index,
                     signed_1=True, signed_2=True)
    signed_exit = prepare_signed_exits(spec, state, [index])[0]
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings.append(slashing)
    block.body.voluntary_exits.append(signed_exit)
    signed_block = sign_block_after_failed_transition(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_slash_and_exit_diff_index(spec, state):
    from consensus_specs_tpu.test_infra.slashings import (
        get_valid_proposer_slashing as _gvps)
    from consensus_specs_tpu.test_infra.voluntary_exits import (
        prepare_signed_exits)
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    proposer = int(build_empty_block_for_next_slot(spec, state).proposer_index)
    slash_index = (proposer + 1) % len(state.validators)
    exit_index = (proposer + 2) % len(state.validators)
    slashing = _gvps(spec, state, proposer_index=slash_index,
                     signed_1=True, signed_2=True)
    signed_exit = prepare_signed_exits(spec, state, [exit_index])[0]
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings.append(slashing)
    block.body.voluntary_exits.append(signed_exit)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.validators[slash_index].slashed
    assert state.validators[exit_index].exit_epoch < spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_high_proposer_index(spec, state):
    # build a block at a slot whose proposer sits in the upper half of
    # the registry (probing a couple of epochs of proposer draws; falls
    # back to the next slot if the draw never lands there)
    next_epoch(spec, state)
    active = spec.get_active_validator_indices(
        state, spec.get_current_epoch(state))
    slot = None
    probe = state.copy()
    for _ in range(2 * int(spec.SLOTS_PER_EPOCH)):
        spec.process_slots(probe, probe.slot + 1)
        if spec.get_beacon_proposer_index(probe) >= len(active) // 2:
            slot = int(probe.slot)
            break
    if slot is None:
        slot = int(state.slot) + 1  # fall back: any proposer
    yield "pre", state
    block = build_empty_block(spec, state, slot=slot)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state


@with_all_phases
@spec_state_test
def test_historical_batch(spec, state):
    # cross a SLOTS_PER_HISTORICAL_ROOT boundary: the accumulator grows
    state.slot = spec.SLOTS_PER_HISTORICAL_ROOT - 1
    pre_historical = len(getattr(state, "historical_roots", [])) \
        + len(getattr(state, "historical_summaries", []))
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    post_historical = len(getattr(state, "historical_roots", [])) \
        + len(getattr(state, "historical_summaries", []))
    assert post_historical == pre_historical + 1


def sign_block_after_failed_transition(spec, state, block):
    """Sign a block that must FAIL state_transition: compute the
    signature over the block as-is against a throwaway copy, then assert
    the real transition rejects it."""
    signed_block = sign_block(spec, state.copy(), block)
    expect_assertion_error(
        lambda: spec.state_transition(state, signed_block))
    return signed_block


@with_all_phases
@spec_state_test
def test_invalid_similar_proposer_slashings_same_block(spec, state):
    """Two slashings of the SAME proposer built from different header
    pairs: the second is a no-op re-slash, the block is invalid."""
    from consensus_specs_tpu.test_infra.slashings import (
        get_valid_proposer_slashing)
    s1 = get_valid_proposer_slashing(spec, state)
    s2 = get_valid_proposer_slashing(spec, state)
    s2.signed_header_2.message.body_root = b"\x42" * 32
    from consensus_specs_tpu.test_infra.slashings import sign_block_header
    from consensus_specs_tpu.test_infra.keys import privkeys
    s2.signed_header_2 = sign_block_header(
        spec, state, s2.signed_header_2.message,
        privkeys[s2.signed_header_1.message.proposer_index])
    assert s1.signed_header_1.message.proposer_index == \
        s2.signed_header_1.message.proposer_index
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings.append(s1)
    block.body.proposer_slashings.append(s2)
    expect_assertion_error(
        lambda: state_transition_and_sign_block(spec, state, block))
    yield "post", None


@with_all_phases
@spec_state_test
def test_multiple_attester_slashings_no_overlap(spec, state):
    """Two attester slashings over disjoint committees both apply."""
    from consensus_specs_tpu.test_infra.slashings import (
        get_valid_attester_slashing, get_indexed_attestation_participants)
    next_slots(spec, state, 2)
    s1 = get_valid_attester_slashing(spec, state, slot=state.slot - 2,
                                     signed_1=True, signed_2=True)
    s2 = get_valid_attester_slashing(spec, state, slot=state.slot - 1,
                                     signed_1=True, signed_2=True)
    p1 = set(get_indexed_attestation_participants(spec, s1.attestation_1))
    p2 = set(get_indexed_attestation_participants(spec, s2.attestation_1))
    if p1 & p2:
        return  # committee layout overlap on this preset: vacuous
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attester_slashings.append(s1)
    block.body.attester_slashings.append(s2)
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state
    for i in p1 | p2:
        assert state.validators[i].slashed


@with_all_phases
@spec_state_test
def test_invalid_only_increase_deposit_count(spec, state):
    """The STATE expects a deposit (eth1_data.deposit_count advanced)
    but the block provides none: process_operations rejects."""
    state.eth1_data.deposit_count += 1
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    expect_assertion_error(
        lambda: state_transition_and_sign_block(spec, state, block))
    yield "post", None


@with_all_phases
@spec_state_test
def test_invalid_duplicate_deposit_same_block(spec, state):
    """The same deposit twice: the second replays an index and fails the
    merkle branch at the advanced deposit index."""
    from consensus_specs_tpu.test_infra.deposits import (
        prepare_state_and_deposit)
    index = len(state.validators)
    deposit = prepare_state_and_deposit(
        spec, state, index, spec.MAX_EFFECTIVE_BALANCE, signed=True)
    state.eth1_data.deposit_count += 1   # state expects TWO deposits now
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.deposits.append(deposit)
    block.body.deposits.append(deposit)
    expect_assertion_error(
        lambda: state_transition_and_sign_block(spec, state, block))
    yield "post", None


@with_all_phases
@spec_state_test
def test_proposer_after_inactive_index(spec, state):
    """An exited validator leaves the proposer rotation; chain continues
    with a proposer whose index is above the inactive one."""
    inactive = 2
    state.validators[inactive].exit_epoch = spec.get_current_epoch(state)
    next_slots(spec, state, spec.SLOTS_PER_EPOCH)  # rotation catches up
    yield "pre", state
    blocks = []
    for _ in range(spec.SLOTS_PER_EPOCH):
        block = build_empty_block_for_next_slot(spec, state)
        assert block.proposer_index != inactive
        blocks.append(state_transition_and_sign_block(spec, state, block))
    yield "blocks", blocks
    yield "post", state


@with_all_phases
@spec_state_test
def test_balance_driven_status_transitions(spec, state):
    """Dropping a validator to EJECTION_BALANCE exits it through the
    epoch transition inside a block-driven chain."""
    index = 3
    state.validators[index].effective_balance = spec.config.EJECTION_BALANCE
    state.balances[index] = spec.config.EJECTION_BALANCE
    assert state.validators[index].exit_epoch == spec.FAR_FUTURE_EPOCH
    yield "pre", state
    blocks = []
    for _ in range(spec.SLOTS_PER_EPOCH):
        block = build_empty_block_for_next_slot(spec, state)
        blocks.append(state_transition_and_sign_block(spec, state, block))
    yield "blocks", blocks
    yield "post", state
    assert state.validators[index].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_eth1_data_votes_consensus(spec, state):
    """A strict majority of identical votes within one voting period
    adopts the new eth1_data (minimal preset: period = 32 slots)."""
    period = int(spec.EPOCHS_PER_ETH1_VOTING_PERIOD) \
        * int(spec.SLOTS_PER_EPOCH)
    if period > 4 * int(spec.SLOTS_PER_EPOCH):
        return  # mainnet-scale period (2048 slots): minimal-only scenario
    pre_eth1 = state.eth1_data.copy()
    new_eth1 = spec.Eth1Data(
        deposit_root=b"\x11" * 32,
        deposit_count=state.eth1_data.deposit_count,
        block_hash=b"\x22" * 32)
    assert new_eth1 != pre_eth1
    yield "pre", state
    blocks = []
    votes_needed = period // 2 + 1
    for _ in range(votes_needed):
        block = build_empty_block_for_next_slot(spec, state)
        block.body.eth1_data = new_eth1
        blocks.append(state_transition_and_sign_block(spec, state, block))
    yield "blocks", blocks
    yield "post", state
    assert state.eth1_data == new_eth1

@with_all_phases
@spec_state_test
def test_eth1_data_votes_no_consensus(spec, state):
    """Split votes never adopt a new eth1_data."""
    pre_eth1 = state.eth1_data.copy()
    vote_a = spec.Eth1Data(deposit_root=b"\x11" * 32,
                           deposit_count=state.eth1_data.deposit_count,
                           block_hash=b"\x22" * 32)
    vote_b = spec.Eth1Data(deposit_root=b"\x33" * 32,
                           deposit_count=state.eth1_data.deposit_count,
                           block_hash=b"\x44" * 32)
    yield "pre", state
    blocks = []
    for i in range(int(spec.SLOTS_PER_EPOCH)):
        block = build_empty_block_for_next_slot(spec, state)
        block.body.eth1_data = vote_a if i % 2 == 0 else vote_b
        blocks.append(state_transition_and_sign_block(spec, state, block))
    yield "blocks", blocks
    yield "post", state
    assert state.eth1_data == pre_eth1
