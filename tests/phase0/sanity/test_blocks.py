"""Sanity whole-block transition tests.

Reference: ``test/phase0/sanity/test_blocks.py``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, always_bls, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, build_empty_block, state_transition_and_sign_block, sign_block, next_epoch)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.slashings import get_valid_proposer_slashing


@with_all_phases
@spec_state_test
def test_empty_block_transition(spec, state):
    pre_slot = state.slot
    pre_eth1_votes = len(state.eth1_data_votes)
    pre_mix = spec.get_randao_mix(state, spec.get_current_epoch(state))

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state

    assert state.slot == pre_slot + 1
    assert len(state.eth1_data_votes) == pre_eth1_votes + 1
    assert spec.get_block_root_at_slot(state, pre_slot) == block.parent_root
    assert spec.get_randao_mix(state, spec.get_current_epoch(state)) != pre_mix


@with_all_phases
@spec_state_test
def test_skipped_slots(spec, state):
    yield "pre", state
    block = build_empty_block(spec, state, state.slot + 4)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state

    assert state.slot == block.slot
    assert spec.get_randao_mix(state, spec.get_current_epoch(state)) != spec.Bytes32()
    for slot in range(int(block.slot) - 4, int(block.slot)):
        assert spec.get_block_root_at_slot(state, slot) == block.parent_root


@with_all_phases
@spec_state_test
def test_empty_epoch_transition(spec, state):
    pre_slot = state.slot
    yield "pre", state
    block = build_empty_block(spec, state, state.slot + spec.SLOTS_PER_EPOCH)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state

    assert state.slot == block.slot
    for slot in range(int(pre_slot), int(state.slot)):
        assert spec.get_block_root_at_slot(state, slot) == block.parent_root


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_state_root(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.state_root = b"\xaa" * 32
    signed_block = sign_block(spec, state, block)
    expect_assertion_error(
        lambda: spec.state_transition(state, signed_block))
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_block_sig(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    # transition on a copy to compute the correct state root, then break sig
    tmp_state = state.copy()
    signed_block = state_transition_and_sign_block(spec, tmp_state, block)
    invalid_signed_block = spec.SignedBeaconBlock(message=block)  # empty signature
    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))
    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_invalid_proposer_index_sig_from_expected_proposer(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    expect_proposer_index = block.proposer_index
    # set invalid proposer index but correct everything else
    active = spec.get_active_validator_indices(state, spec.get_current_epoch(state))
    block.proposer_index = (expect_proposer_index + 1) % len(active)
    invalid_signed_block = sign_block(spec, state, block, expect_proposer_index)
    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))
    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_prev_slot_block_transition(spec, state):
    spec.process_slots(state, state.slot + 1)
    block = build_empty_block(spec, state, slot=state.slot)
    proposer_index = spec.get_beacon_proposer_index(state)
    spec.process_slots(state, state.slot + 1)
    yield "pre", state
    signed_block = sign_block(spec, state, block, proposer_index)
    expect_assertion_error(
        lambda: spec.state_transition(state, signed_block))
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_attestation(spec, state):
    next_epoch(spec, state)
    yield "pre", state

    attestation_block = build_empty_block(
        spec, state, state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    index = 0
    attestation = get_valid_attestation(spec, state, index=index, signed=True)

    # attestation is valid already MIN_ATTESTATION_INCLUSION_DELAY slots later
    attestation_block.body.attestations.append(attestation)
    signed_attestation_block = state_transition_and_sign_block(
        spec, state, attestation_block)

    if spec.fork == "phase0":
        assert len(state.current_epoch_attestations) == 1
    else:
        assert any(f != 0 for f in state.current_epoch_participation)

    yield "blocks", [signed_attestation_block]
    yield "post", state


@with_all_phases
@spec_state_test
def test_proposer_slashing_block(spec, state):
    # copy for later balance comparison
    pre_state = state.copy()
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, signed_1=True, signed_2=True)
    slashed_index = proposer_slashing.signed_header_1.message.proposer_index

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings.append(proposer_slashing)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state

    slashed_validator = state.validators[slashed_index]
    assert slashed_validator.slashed
    assert slashed_validator.exit_epoch < spec.FAR_FUTURE_EPOCH
    assert slashed_validator.withdrawable_epoch < spec.FAR_FUTURE_EPOCH
    assert state.balances[slashed_index] < pre_state.balances[slashed_index]


@with_all_phases
@spec_state_test
def test_duplicate_attestation_same_block(spec, state):
    next_epoch(spec, state)
    yield "pre", state
    block = build_empty_block(
        spec, state, state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation = get_valid_attestation(spec, state, index=0, signed=True)
    for _ in range(2):
        block.body.attestations.append(attestation)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    if spec.fork == "phase0":
        # duplicates are valid in phase0 (both become pending attestations)
        assert len(state.current_epoch_attestations) == 2
    else:
        # altair+: the second copy grants no new flags (idempotent)
        assert any(f != 0 for f in state.current_epoch_participation)
