"""Sanity slot-transition tests. Reference: ``test/phase0/sanity/test_slots.py``."""
from consensus_specs_tpu.test_infra.context import spec_state_test, with_all_phases
from consensus_specs_tpu.utils.ssz import hash_tree_root


@with_all_phases
@spec_state_test
def test_slots_1(spec, state):
    pre_slot = state.slot
    pre_root = hash_tree_root(state)
    yield "pre", state
    slots = 1
    yield "slots", slots
    spec.process_slots(state, state.slot + slots)
    yield "post", state
    assert state.slot == pre_slot + 1
    assert hash_tree_root(state) != pre_root


@with_all_phases
@spec_state_test
def test_slots_2(spec, state):
    yield "pre", state
    slots = 2
    yield "slots", slots
    spec.process_slots(state, state.slot + slots)
    yield "post", state


@with_all_phases
@spec_state_test
def test_empty_epoch(spec, state):
    pre_slot = state.slot
    yield "pre", state
    slots = spec.SLOTS_PER_EPOCH
    yield "slots", slots
    spec.process_slots(state, state.slot + slots)
    yield "post", state
    assert state.slot == pre_slot + spec.SLOTS_PER_EPOCH


@with_all_phases
@spec_state_test
def test_double_empty_epoch(spec, state):
    pre_slot = state.slot
    yield "pre", state
    slots = spec.SLOTS_PER_EPOCH * 2
    yield "slots", slots
    spec.process_slots(state, state.slot + slots)
    yield "post", state
    assert state.slot == pre_slot + 2 * spec.SLOTS_PER_EPOCH


@with_all_phases
@spec_state_test
def test_over_epoch_boundary(spec, state):
    spec.process_slots(state, state.slot + spec.SLOTS_PER_EPOCH // 2)
    yield "pre", state
    slots = spec.SLOTS_PER_EPOCH
    yield "slots", slots
    spec.process_slots(state, state.slot + slots)
    yield "post", state
