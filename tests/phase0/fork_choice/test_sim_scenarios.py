"""Curated adversarial-simulator seeds as fork-choice vectors.

The feed from the adversarial sweep (``consensus_specs_tpu/sim``) into
the conformance corpus: each test replays one catalog scenario at a
pinned seed through the real store, emitting the cross-client
``fork_choice`` event log (anchor parts + block/attestation parts in
event order + a ``steps`` yaml with store checks) via the driver's
``test_steps`` hook — the same dual pytest/generator consumption every
other suite uses (``generators/fork_choice/main.py`` registers this
module under the ``sim`` handler).

Seeds are pinned, not arbitrary: each was picked from sweep runs for
hitting its storyline's interesting outcome (finality through a leak,
boost defending against the ex-ante release, evidence landing during
equivocation).  The behavioral asserts below pin that outcome, so a
seed that drifts into a boring chain fails instead of silently
emitting a weaker vector.
"""
import pytest

from consensus_specs_tpu.sim import driver, scenarios
from consensus_specs_tpu.test_infra.context import (
    spec_test, with_all_phases, with_phases, never_bls)
from consensus_specs_tpu.forks import build_spec

# multi-epoch store replays (~1-4s each x forks): outside the tier-1
# budget.  The CI adversarial-sim job runs this file explicitly, the
# per-fork conformance legs run it unfiltered, and the fork_choice
# generator replays it at vector-emission time regardless of markers.
pytestmark = pytest.mark.slow


def _run_scenario(spec, name, seed, test_steps):
    epoch = int(spec.SLOTS_PER_EPOCH)
    scenario = scenarios.build(seed, epoch, epoch * 8, name=name)
    if scenario.config_overrides:
        spec = build_spec(spec.fork, spec.preset_name,
                          scenario.config_overrides)
    result = driver.execute(spec, scenario.script, scenario.n_validators,
                            test_steps=test_steps)
    assert result.accepted > 0
    return result


@with_all_phases
@spec_test
@never_bls
def test_sim_steady_finalizes(spec):
    """The control storyline: full participation, finality marching."""
    test_steps = []
    result = _run_scenario(spec, "steady", 3, test_steps)
    assert result.finalized[0] >= 1
    assert result.rejected == 0
    yield "steps", test_steps


@with_phases(["phase0", "altair"])
@spec_test
@never_bls
def test_sim_inactivity_leak_recovers(spec):
    """40%ish offline through the leak, then recovery to finality —
    the longest-horizon storyline in the catalog (~26 epochs).
    phase0 + altair cover both leak mechanisms (pending-attestation vs
    participation-flag/inactivity-score); the altair+ fork matrix is
    exercised by the random-scenario leak suite
    (``tests/altair/test_random_scenarios.py``) and the generator."""
    test_steps = []
    result = _run_scenario(spec, "inactivity_leak", 9, test_steps)
    # the defining outcome: finality stalled during the leak, then
    # snapped forward after the offline set returned
    assert result.finalized[0] >= 8
    yield "steps", test_steps


@with_phases(["phase0", "altair"])
@spec_test
@never_bls
def test_sim_exante_reorg_boost_defends(spec):
    """Withheld-block release races proposer boost; the timely honest
    chain must keep finalizing regardless."""
    test_steps = []
    result = _run_scenario(spec, "exante_reorg", 4, test_steps)
    assert result.finalized[0] >= 1
    yield "steps", test_steps


@with_phases(["phase0", "altair"])
@spec_test
@never_bls
def test_sim_equivocation_with_evidence(spec):
    """Equivocating proposers + double votes; slashing evidence rides
    into bodies on this seed and the chain survives the split."""
    test_steps = []
    result = _run_scenario(spec, "equivocation", 1, test_steps)
    assert result.slots >= 2 * int(spec.SLOTS_PER_EPOCH)
    yield "steps", test_steps


@with_phases(["phase0", "altair"])
@spec_test
@never_bls
def test_sim_balancing_resolves(spec):
    """Sustained weight-balancing across sibling tips, then the
    network converges: the head flip-flop must settle and finalize."""
    test_steps = []
    result = _run_scenario(spec, "balancing", 0, test_steps)
    assert result.finalized[0] >= 1
    yield "steps", test_steps


@with_phases(["phase0", "altair"])
@spec_test
@never_bls
def test_sim_deep_nonfinality_prunes(spec):
    """Multi-epoch justification stall with unpruned side forks, then
    one finalization snap prunes the whole backlog."""
    test_steps = []
    result = _run_scenario(spec, "deep_nonfinality", 2, test_steps)
    assert result.finalized[0] >= 1
    yield "steps", test_steps
