"""Randomized differential suite: the proto-array engine
(``forkchoice/proto_array.py``) against the spec-loop fork choice.

Every scenario drives ONE store through an event stream and, after
every event, answers ``get_head`` / ``get_weight`` /
``get_filtered_block_tree`` twice — once with the engine forced on
(``use_proto``), once with the spec loops forced (``use_spec``) — and
requires byte-identical results.  The engine-hit counters are asserted
so a silent fallback cannot turn the comparison into a
loop-vs-loop tautology.  Scenarios cover random block trees with
competing forks, attestation streams, proposer boost, equivocation
discard, late-justification pull-ups, and finalization pruning.
"""
import random

from consensus_specs_tpu.forkchoice import proto_array
from consensus_specs_tpu.test_infra.metrics import counting
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, with_phases, never_bls, pytest_only,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
    next_slots,
)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.slashings import (
    get_valid_attester_slashing, get_indexed_attestation_participants,
)
from consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store_and_block, on_tick_and_append_step,
    tick_and_add_block, add_attestation, add_attester_slashing,
    apply_next_epoch_with_attestations,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root


def _store_with_engine(spec, state):
    """A genesis store with the engine force-attached, so the
    differential comparison is meaningful even when the suite runs
    under ``CS_TPU_PROTO_ARRAY=0`` (the satellite's engine-off leg):
    attach happens at store creation; after that the write hooks keep
    the engine in sync in either mode."""
    proto_array.use_proto()
    try:
        store, genesis_block = get_genesis_forkchoice_store_and_block(
            spec, state)
    finally:
        proto_array.use_auto()
    assert store._fc_proto is not None
    return store, genesis_block


def _assert_engines_agree(spec, store, check_weights=True):
    """Both engines answer the full read surface identically; the proto
    side must really have been the engine (counter-asserted)."""
    eng = getattr(store, "_fc_proto", None)
    assert eng is not None, "engine not attached (CS_TPU_PROTO_ARRAY=0?)"
    assert not eng._broken
    proto_array.use_proto()
    try:
        with counting() as delta:
            head_proto = bytes(spec.get_head(store))
            tree_proto = spec.get_filtered_block_tree(store)
            weights_proto = {
                r: int(spec.get_weight(store, r)) for r in store.blocks
            } if check_weights else None
    finally:
        proto_array.use_spec()
    assert delta["forkchoice.head{path=engine}"] == 1
    assert delta["forkchoice.filtered_tree{path=engine}"] == 1
    try:
        head_spec = bytes(spec.get_head(store))
        tree_spec = spec.get_filtered_block_tree(store)
        weights_spec = {
            r: int(spec.get_weight(store, r)) for r in store.blocks
        } if check_weights else None
    finally:
        proto_array.use_auto()
    assert head_proto == head_spec
    assert set(tree_proto) == set(tree_spec)
    for r in tree_proto:
        assert tree_proto[r] is tree_spec[r]
    if check_weights:
        assert weights_proto == weights_spec
    return head_proto


def _tick_next_slot(spec, store, test_steps):
    slot = spec.get_current_slot(store) + 1
    on_tick_and_append_step(
        spec, store,
        store.genesis_time + int(slot) * int(spec.config.SECONDS_PER_SLOT),
        test_steps)


@with_all_phases
@spec_state_test
@never_bls
@pytest_only
def test_proto_differential_random_tree(spec, state):
    """Random branching trees + attestation streams: byte-identical
    head/weights/filtered-tree after every event."""
    for seed in (11, 29):
        rng = random.Random(seed)
        test_steps = []
        store, genesis_block = _store_with_engine(spec, state.copy())
        branches = [(state.copy(), hash_tree_root(genesis_block))]
        for _ in range(14):
            action = rng.random()
            if action < 0.55 or len(store.blocks) < 3:
                # extend a random branch (sometimes forking it first)
                i = rng.randrange(len(branches))
                branch_state, _ = branches[i]
                if rng.random() < 0.4 and len(branches) < 4:
                    branch_state = branch_state.copy()   # new fork
                else:
                    branches.pop(i)
                block = build_empty_block_for_next_slot(spec, branch_state)
                block.body.graffiti = bytes([rng.randrange(256)]) * 32
                signed = state_transition_and_sign_block(
                    spec, branch_state, block)
                tick_and_add_block(spec, store, signed, test_steps)
                branches.append((branch_state, hash_tree_root(block)))
            elif action < 0.85:
                # attest a random branch tip with a random committee
                branch_state, _ = rng.choice(branches)
                att_state = branch_state.copy()
                att = get_valid_attestation(
                    spec, att_state, slot=att_state.slot,
                    index=0, signed=True)
                next_slots(spec, att_state, 2)
                while spec.get_current_slot(store) <= att.data.slot:
                    _tick_next_slot(spec, store, test_steps)
                add_attestation(spec, store, att, test_steps)
            else:
                _tick_next_slot(spec, store, test_steps)
            _assert_engines_agree(spec, store)


@with_all_phases
@spec_state_test
@never_bls
@pytest_only
def test_proto_differential_boost_and_equivocation(spec, state):
    """Proposer boost on/off and equivocation discard keep both engines
    byte-identical."""
    test_steps = []
    store, genesis_block = _store_with_engine(spec, state)
    base = state.copy()
    state_a = base.copy()
    block_a = build_empty_block_for_next_slot(spec, state_a)
    signed_a = state_transition_and_sign_block(spec, state_a, block_a)
    state_b = base.copy()
    block_b = build_empty_block_for_next_slot(spec, state_b)
    block_b.body.graffiti = b"\x42" * 32
    signed_b = state_transition_and_sign_block(spec, state_b, block_b)
    tick_and_add_block(spec, store, signed_a, test_steps)
    # the first timely block carries the proposer boost
    assert bytes(store.proposer_boost_root) == bytes(hash_tree_root(block_a))
    _assert_engines_agree(spec, store)
    tick_and_add_block(spec, store, signed_b, test_steps)
    _assert_engines_agree(spec, store)

    _tick_next_slot(spec, store, test_steps)   # boost wears off
    _assert_engines_agree(spec, store)

    # votes flip the head to the tie-break loser, then the voters are
    # slashed and the head reverts — engines agree at every step
    tie_winner = _assert_engines_agree(spec, store)
    loser_state, loser_root = \
        (state_a, hash_tree_root(block_a)) \
        if tie_winner == bytes(hash_tree_root(block_b)) \
        else (state_b, hash_tree_root(block_b))
    att = get_valid_attestation(spec, loser_state, signed=True)
    _tick_next_slot(spec, store, test_steps)
    add_attestation(spec, store, att, test_steps)
    assert _assert_engines_agree(spec, store) == bytes(loser_root)
    slashing = get_valid_attester_slashing(
        spec, loser_state, slot=att.data.slot, signed_1=True, signed_2=True)
    participants = get_indexed_attestation_participants(
        spec, slashing.attestation_1)
    add_attester_slashing(spec, store, slashing, test_steps)
    assert all(int(i) in store.equivocating_indices for i in participants)
    assert _assert_engines_agree(spec, store) == tie_winner


@with_phases(["phase0", "altair", "deneb"])
@spec_state_test
@never_bls
@pytest_only
def test_proto_differential_justification_and_pruning(spec, state):
    """Epochs of attested blocks: justified/finalized checkpoints
    advance (late-justification pull-ups included) and finalization
    prunes the proto array; engines stay byte-identical throughout."""
    test_steps = []
    store, _ = _store_with_engine(spec, state)
    eng = store._fc_proto
    for epoch in range(4):
        # no previous-epoch attestations to fill in the first epoch
        state, store, _ = apply_next_epoch_with_attestations(
            spec, state, store, True, epoch > 0, test_steps)
        _assert_engines_agree(spec, store, check_weights=(epoch % 2 == 0))
    assert store.finalized_checkpoint.epoch > spec.GENESIS_EPOCH
    # the finalized update pruned everything outside the finalized
    # subtree; the spec store keeps every block
    proto_array.use_proto()
    try:
        spec.get_head(store)
    finally:
        proto_array.use_auto()
    assert proto_array.stats()["prunes"] > 0
    assert len(eng._roots) < len(store.blocks)
    assert eng._parent[0] == -1
    assert eng._roots[0] == bytes(store.finalized_checkpoint.root)
    _assert_engines_agree(spec, store)


@with_phases(["phase0"])
@spec_state_test
@never_bls
@pytest_only
def test_weight_is_first_engine_read_after_finalization(spec, state):
    """Regression: ``get_weight`` as the FIRST engine read after a
    finalization advance (no preceding ``get_head``).  The prune inside
    ``_refresh`` compacts the arrays and remaps every index, so a root
    lookup taken before the refresh read another node's subtree weight
    (or raised IndexError).  Covers both a surviving root (engine
    answer at the remapped index) and a pruned root (engine declines,
    spec-loop fallback)."""
    test_steps = []
    store, genesis_block = _store_with_engine(spec, state)
    eng = store._fc_proto
    genesis_root = bytes(hash_tree_root(genesis_block))
    # advance finalization with every read forced onto the spec loop:
    # the write hooks keep the engine fed, but no get_head drains the
    # pending prune
    proto_array.use_spec()
    try:
        last = None
        for epoch in range(4):
            state, store, last = apply_next_epoch_with_attestations(
                spec, state, store, True, epoch > 0, test_steps)
    finally:
        proto_array.use_auto()
    assert store.finalized_checkpoint.epoch > spec.GENESIS_EPOCH
    assert bytes(store.finalized_checkpoint.root) != genesis_root
    surviving_root = bytes(hash_tree_root(last.message))
    # the prune really is still pending
    assert eng._fin_seen != proto_array._ckpt_key(store.finalized_checkpoint)
    with counting() as delta:
        proto_array.use_proto()
        try:
            w_surviving = int(spec.get_weight(store, surviving_root))
            w_pruned = int(spec.get_weight(store, genesis_root))
        finally:
            proto_array.use_spec()
        try:
            assert w_surviving == int(spec.get_weight(store, surviving_root))
            assert w_pruned == int(spec.get_weight(store, genesis_root))
        finally:
            proto_array.use_auto()
    # the very first read triggered the prune and was still answered by
    # the engine; the pruned root fell back to the spec loop
    assert delta["forkchoice.prunes"] == 1
    assert delta["forkchoice.weight{path=engine}"] == 1
    assert delta["forkchoice.weight{path=spec}"] == 3
    assert genesis_root not in eng._index
    assert surviving_root in eng._index
    _assert_engines_agree(spec, store)


@with_phases(["phase0"])
@spec_state_test
@never_bls
@pytest_only
def test_proto_disabled_restores_pure_spec_path(spec, state):
    """With the switch off at store-creation time no engine is attached
    and every read runs the spec loop."""
    proto_array.use_spec()
    try:
        test_steps = []
        store, genesis_block = get_genesis_forkchoice_store_and_block(
            spec, state)
        assert getattr(store, "_fc_proto", None) is None
        block = build_empty_block_for_next_slot(spec, state)
        signed = state_transition_and_sign_block(spec, state, block)
        tick_and_add_block(spec, store, signed, test_steps)
        with counting() as delta:
            assert bytes(spec.get_head(store)) == bytes(hash_tree_root(block))
        assert delta["forkchoice.head{path=engine}"] == 0
        assert delta["forkchoice.head{path=spec}"] == 1
    finally:
        proto_array.use_auto()


@with_phases(["phase0"])
@spec_state_test
@never_bls
@pytest_only
def test_heldover_delta_survives_node_growth(spec, state):
    """Regression: a pending delta array left behind by a refresh that
    fell back after queuing deltas is smaller than a node array that
    grew afterwards; the next propagation must grow it instead of
    crashing (IndexError)."""
    test_steps = []
    store, genesis_block = _store_with_engine(spec, state)
    eng = store._fc_proto
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    tick_and_add_block(spec, store, signed, test_steps)
    att = get_valid_attestation(spec, state, slot=block.slot, signed=True)
    _tick_next_slot(spec, store, test_steps)
    add_attestation(spec, store, att, test_steps)
    proto_array.use_proto()
    try:
        spec.get_head(store)   # drain real deltas
    finally:
        proto_array.use_auto()
    # prime a held-over delta at the CURRENT node count (what a
    # fallback between the delta passes and propagation leaves behind)
    eng._get_delta()
    assert eng._delta is not None
    held_size = eng._delta.size
    # grow the array through RAW handlers (no test-infra store checks,
    # whose per-event get_head would drain the delta early) with LATE
    # blocks, so the boost stays cleared and the next refresh reaches
    # propagation with the stale, smaller delta still pending
    spec.on_tick(store, store.time
                 + 2 * int(spec.config.SECONDS_PER_SLOT))
    for _ in range(2):
        block = build_empty_block_for_next_slot(spec, state)
        signed = state_transition_and_sign_block(spec, state, block)
        spec.on_block(store, signed)
    assert bytes(store.proposer_boost_root) == b"\x00" * 32
    assert eng._delta is not None and eng._delta.size == held_size
    assert eng._n > held_size
    assert _assert_engines_agree(spec, store) == bytes(hash_tree_root(block))


@with_phases(["phase0"])
@spec_state_test
@never_bls
@pytest_only
def test_direct_block_insertion_falls_back(spec, state):
    """A consumer inserting into ``store.blocks`` directly (bypassing
    the wrapped on_block) must never be answered from stale caches: the
    children index rebuilds from scratch and the engine refuses the
    array, falling back to the spec loop."""
    test_steps = []
    store, genesis_block = _store_with_engine(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    tick_and_add_block(spec, store, signed, test_steps)
    # second block: registered by hand, store bookkeeping bypassed
    rogue = build_empty_block_for_next_slot(spec, state)
    rogue_signed = state_transition_and_sign_block(spec, state, rogue)
    rogue_root = bytes(hash_tree_root(rogue))
    store.blocks[rogue_root] = rogue_signed.message.copy()
    store.block_states[rogue_root] = state.copy()
    store.unrealized_justifications[rogue_root] = \
        store.justified_checkpoint.copy()
    assert rogue_root not in store._fc_children.get(
        bytes(rogue.parent_root), [])
    # the children index detects staleness and rebuilds from scratch
    rebuilt = spec._children_index(store)
    assert rebuilt is not store._fc_children
    assert rogue_root in rebuilt[bytes(rogue.parent_root)]
    # the engine detects the unseen block and falls back to the spec
    # loop, which sees the rogue block as the new head
    with counting() as delta:
        proto_array.use_proto()
        try:
            head = bytes(spec.get_head(store))
        finally:
            proto_array.use_auto()
    # the spec get_head itself re-enters wrapped reads (filtered tree,
    # per-child weights), each refusing the stale array in turn
    assert delta["forkchoice.fallbacks{reason=guard}"] > 0
    assert delta["forkchoice.head{path=engine}"] == 0
    assert delta["forkchoice.head{path=spec}"] == 1
    assert head == rogue_root


@with_phases(["phase0"])
@spec_state_test
@never_bls
@pytest_only
def test_children_index_consistent_out_of_order(spec, state):
    """The incrementally-maintained parent->children index matches a
    from-scratch rebuild under out-of-order (forked, interleaved)
    insertion."""
    test_steps = []
    store, genesis_block = get_genesis_forkchoice_store_and_block(
        spec, state)
    base = state.copy()
    # three competing forks, extended in interleaved order so children
    # lists accrete out of chain order
    forks = []
    for tag in (b"\x01", b"\x02", b"\x03"):
        fork_state = base.copy()
        block = build_empty_block_for_next_slot(spec, fork_state)
        block.body.graffiti = tag * 32
        forks.append((fork_state,
                      state_transition_and_sign_block(spec, fork_state,
                                                      block)))
    # add fork tips 2, 0, 1, then extend 0 and 2
    for i in (2, 0, 1):
        tick_and_add_block(spec, store, forks[i][1], test_steps)
    for i in (0, 2):
        fork_state = forks[i][0]
        block = build_empty_block_for_next_slot(spec, fork_state)
        signed = state_transition_and_sign_block(spec, fork_state, block)
        tick_and_add_block(spec, store, signed, test_steps)

    maintained = spec._children_index(store)
    assert maintained is store._fc_children
    # the pre-accel spec body, reachable through the wrapper's
    # __wrapped__, rebuilds the index from every block in the store
    rebuilt = type(spec)._children_index.__wrapped__(spec, store)
    assert {k: sorted(v) for k, v in maintained.items()} \
        == {k: sorted(map(bytes, v)) for k, v in rebuilt.items()}


@with_phases(["phase0"])
@spec_state_test
@never_bls
@pytest_only
def test_ancestor_cache_matches_uncached_walk(spec, state):
    """The memoized get_ancestor equals the uncached spec walk for every
    (block, slot) pair in a forked store."""
    test_steps = []
    store, genesis_block = get_genesis_forkchoice_store_and_block(
        spec, state)
    base = state.copy()
    for tag in (b"\x00", b"\x11"):
        fork_state = base.copy()
        for _ in range(3):
            block = build_empty_block_for_next_slot(spec, fork_state)
            block.body.graffiti = tag * 32
            signed = state_transition_and_sign_block(spec, fork_state, block)
            tick_and_add_block(spec, store, signed, test_steps)
    uncached = type(spec).get_ancestor.__wrapped__
    slots = sorted({int(b.slot) for b in store.blocks.values()})
    for root in store.blocks:
        for slot in slots:
            assert bytes(spec.get_ancestor(store, root, slot)) \
                == bytes(uncached(spec, store, root, slot))
    # cache hits answer without re-walking: poison-proof because keys
    # are (root, slot) of an immutable chain structure
    assert store._fc_ancestors
