"""Ex-ante reorg defense and the get_proposer_head single-slot re-org
rule.

Reference models: ``test/phase0/fork_choice/test_ex_ante.py`` (proposer
boost beating withheld-block attacks) and ``test_get_proposer_head.py``
against ``specs/phase0/fork-choice.md`` get_proposer_head /
proposer-boost scoring.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, never_bls,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
    next_slots,
)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store_and_block, on_tick_and_append_step,
    tick_and_add_block, add_block, add_attestation,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root


def _slot_time(spec, store, slot, interval=0):
    per_interval = int(spec.config.SECONDS_PER_SLOT) // spec.INTERVALS_PER_SLOT
    return store.genesis_time + int(slot) * int(spec.config.SECONDS_PER_SLOT) \
        + interval * per_interval


@with_all_phases
@spec_state_test
@never_bls
def test_ex_ante_withheld_block_loses_to_boosted_proposal(spec, state):
    """An adversary withholds its slot-n block and reveals it at slot
    n+1 alongside the honest proposal: the honest block's proposer
    boost outweighs the withheld block's head start."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    base = state.copy()

    # common parent at slot 1
    state_a = base.copy()
    block_a = build_empty_block_for_next_slot(spec, state_a)
    signed_a = state_transition_and_sign_block(spec, state_a, block_a)
    tick_and_add_block(spec, store, signed_a, test_steps)

    # adversary builds (and withholds) a slot-2 child of A
    state_b = state_a.copy()
    block_b = build_empty_block_for_next_slot(spec, state_b)
    block_b.body.graffiti = b"\xbb" * 32
    signed_b = state_transition_and_sign_block(spec, state_b, block_b)

    # honest proposer builds the slot-3 child of A (not of B: B unseen)
    state_c = state_a.copy()
    next_slots(spec, state_c, 1)
    block_c = build_empty_block_for_next_slot(spec, state_c)
    signed_c = state_transition_and_sign_block(spec, state_c, block_c)

    # slot 3 begins: the withheld B arrives late (no boost), C on time
    on_tick_and_append_step(
        spec, store, _slot_time(spec, store, block_c.slot), test_steps)
    add_block(spec, store, signed_b, test_steps)
    assert bytes(store.proposer_boost_root) == b"\x00" * 32  # B not timely
    add_block(spec, store, signed_c, test_steps)
    root_c = hash_tree_root(block_c)
    assert bytes(store.proposer_boost_root) == root_c

    root_b = hash_tree_root(block_b)
    assert int(spec.get_weight(store, root_c)) > \
        int(spec.get_weight(store, root_b))
    assert bytes(spec.get_head(store)) == root_c
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_ex_ante_sandwich_without_attestations(spec, state):
    """Withheld block + one late attestation for it: the boosted honest
    proposal still wins when the adversarial vote fraction is below the
    boost (40% committee weight)."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    state_a = state.copy()
    block_a = build_empty_block_for_next_slot(spec, state_a)
    signed_a = state_transition_and_sign_block(spec, state_a, block_a)
    tick_and_add_block(spec, store, signed_a, test_steps)

    state_b = state_a.copy()
    block_b = build_empty_block_for_next_slot(spec, state_b)
    block_b.body.graffiti = b"\xbb" * 32
    signed_b = state_transition_and_sign_block(spec, state_b, block_b)

    # a SINGLE adversarial attester votes for B at its slot
    att_b = get_valid_attestation(
        spec, state_b, slot=block_b.slot,
        filter_participant_set=lambda c: {min(c)}, signed=True)

    state_c = state_a.copy()
    next_slots(spec, state_c, 1)
    block_c = build_empty_block_for_next_slot(spec, state_c)
    signed_c = state_transition_and_sign_block(spec, state_c, block_c)

    on_tick_and_append_step(
        spec, store, _slot_time(spec, store, block_c.slot), test_steps)
    add_block(spec, store, signed_b, test_steps)
    add_block(spec, store, signed_c, test_steps)
    add_attestation(spec, store, att_b, test_steps)

    root_b, root_c = hash_tree_root(block_b), hash_tree_root(block_c)
    boost = int(spec.get_proposer_score(store))
    one_vote = int(spec.get_weight(store, root_b))
    # precondition of the scenario: the boost outweighs one lone vote
    assert boost > one_vote
    assert bytes(spec.get_head(store)) == root_c
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_proposer_head_prefers_parent_of_late_weak_head(spec, state):
    """get_proposer_head returns the PARENT when the head arrived late,
    is weak (no votes), and the parent is strong — the single-slot
    re-org rule."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)

    # parent block with TWO slots of attestation weight behind it
    # (is_parent_strong needs > REORG_PARENT_WEIGHT_THRESHOLD = 160%
    # of one slot's committee weight)
    state_p = state.copy()
    block_p = build_empty_block_for_next_slot(spec, state_p)
    signed_p = state_transition_and_sign_block(spec, state_p, block_p)
    tick_and_add_block(spec, store, signed_p, test_steps)
    atts = []
    epoch = spec.compute_epoch_at_slot(block_p.slot)
    committees = spec.get_committee_count_per_slot(state_p, epoch)
    for index in range(committees):
        atts.append(get_valid_attestation(
            spec, state_p, slot=block_p.slot, index=index, signed=True))

    # the head is block_p's DIRECT child (single-slot rule) arriving
    # LATE in its slot (interval 2: not timely)
    state_h = state_p.copy()
    block_h = build_empty_block_for_next_slot(spec, state_h)
    # the head slot's own attesters never saw the late block: they vote
    # for block_p as head — the second slot of parent weight
    state_empty = state_p.copy()
    next_slots(spec, state_empty, 1)
    assert state_empty.slot == block_h.slot
    for index in range(committees):
        atts.append(get_valid_attestation(
            spec, state_empty, slot=state_empty.slot, index=index,
            signed=True))
    signed_h = state_transition_and_sign_block(spec, state_h, block_h)
    on_tick_and_append_step(
        spec, store, _slot_time(spec, store, block_h.slot, interval=2),
        test_steps)
    add_block(spec, store, signed_h, test_steps)
    root_h = hash_tree_root(block_h)
    assert not store.block_timeliness[root_h]

    # next slot, proposing on time; the attestations (including the
    # head slot's own, which require slot+1) land now
    on_tick_and_append_step(
        spec, store, _slot_time(spec, store, block_h.slot + 1), test_steps)
    for att in atts:
        add_attestation(spec, store, att, test_steps)
    assert bytes(spec.get_head(store)) == root_h   # head by chain length
    proposal_head = bytes(spec.get_proposer_head(
        store, root_h, block_h.slot + 1))
    assert proposal_head == hash_tree_root(block_p)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_proposer_head_keeps_timely_head(spec, state):
    """A TIMELY head is never re-orged by get_proposer_head even when
    voteless."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    state_p = state.copy()
    block_p = build_empty_block_for_next_slot(spec, state_p)
    signed_p = state_transition_and_sign_block(spec, state_p, block_p)
    tick_and_add_block(spec, store, signed_p, test_steps)

    state_h = state_p.copy()
    block_h = build_empty_block_for_next_slot(spec, state_h)
    signed_h = state_transition_and_sign_block(spec, state_h, block_h)
    on_tick_and_append_step(
        spec, store, _slot_time(spec, store, block_h.slot), test_steps)
    add_block(spec, store, signed_h, test_steps)
    root_h = hash_tree_root(block_h)
    assert store.block_timeliness[root_h]

    on_tick_and_append_step(
        spec, store, _slot_time(spec, store, block_h.slot + 1), test_steps)
    proposal_head = bytes(spec.get_proposer_head(
        store, root_h, block_h.slot + 1))
    assert proposal_head == root_h
    yield "steps", test_steps
