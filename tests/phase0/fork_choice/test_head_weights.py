"""get_head weight accounting: attestation votes, latest-message
freshness, equivocation discard, proposer-boost weight.

Reference models: ``test/phase0/fork_choice/test_get_head.py``
(``discard_equivocations``, vote-shifted heads) against
``specs/phase0/fork-choice.md`` get_weight/on_attester_slashing.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, never_bls,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
    next_slots,
)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.slashings import (
    get_valid_attester_slashing, get_indexed_attestation_participants,
)
from consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store_and_block, on_tick_and_append_step,
    tick_and_add_block, add_attestation, add_attester_slashing,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root


def _two_forks(spec, state, store, test_steps):
    """Two competing single-block forks on top of genesis; returns
    (state_a, root_a, state_b, root_b) with both blocks in the store."""
    base = state.copy()
    state_a = base.copy()
    block_a = build_empty_block_for_next_slot(spec, state_a)
    signed_a = state_transition_and_sign_block(spec, state_a, block_a)
    state_b = base.copy()
    block_b = build_empty_block_for_next_slot(spec, state_b)
    block_b.body.graffiti = b"\x42" * 32
    signed_b = state_transition_and_sign_block(spec, state_b, block_b)
    tick_and_add_block(spec, store, signed_a, test_steps)
    tick_and_add_block(spec, store, signed_b, test_steps)
    return (state_a, hash_tree_root(block_a),
            state_b, hash_tree_root(block_b))


def _tick_next_slot(spec, store, test_steps):
    slot = spec.get_current_slot(store) + 1
    on_tick_and_append_step(
        spec, store,
        store.genesis_time + int(slot) * int(spec.config.SECONDS_PER_SLOT),
        test_steps)


@with_all_phases
@spec_state_test
@never_bls
def test_attestation_flips_head(spec, state):
    """Votes for the tie-break loser flip the head to it."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    state_a, root_a, state_b, root_b = _two_forks(spec, state, store,
                                                  test_steps)
    tie_winner = bytes(spec.get_head(store))
    loser_state, loser_root = \
        (state_a, root_a) if tie_winner == bytes(root_b) else (state_b, root_b)
    att = get_valid_attestation(spec, loser_state, signed=True)
    # attestation slot must be reached + 1 before on_attestation accepts
    next_slots(spec, loser_state, 2)
    _tick_next_slot(spec, store, test_steps)
    _tick_next_slot(spec, store, test_steps)
    add_attestation(spec, store, att, test_steps)
    assert bytes(spec.get_head(store)) == bytes(loser_root)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_equivocating_votes_discarded(spec, state):
    """After on_attester_slashing, the equivocators' latest messages no
    longer count toward get_weight and the head reverts."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    state_a, root_a, state_b, root_b = _two_forks(spec, state, store,
                                                  test_steps)
    # with no votes and no boost the tie-break is the lexicographic max;
    # vote for the SMALLER root so both flips are observable
    _tick_next_slot(spec, store, test_steps)   # boost wears off
    tie_winner = max([bytes(root_a), bytes(root_b)])
    assert bytes(spec.get_head(store)) == tie_winner
    loser_state, loser_root = \
        (state_a, root_a) if tie_winner == bytes(root_b) else (state_b, root_b)
    att = get_valid_attestation(spec, loser_state, signed=True)
    _tick_next_slot(spec, store, test_steps)
    add_attestation(spec, store, att, test_steps)
    assert bytes(spec.get_head(store)) == bytes(loser_root)

    # slash exactly the attesting committee: their votes are discarded
    slashing = get_valid_attester_slashing(
        spec, loser_state, slot=att.data.slot, signed_1=True, signed_2=True)
    participants = get_indexed_attestation_participants(
        spec, slashing.attestation_1)
    add_attester_slashing(spec, store, slashing, test_steps)
    assert all(int(i) in store.equivocating_indices for i in participants)
    assert bytes(spec.get_head(store)) == tie_winner
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_equivocators_ignored_for_future_votes(spec, state):
    """A new attestation from an equivocating validator never re-enters
    latest_messages."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    state_a, root_a, state_b, root_b = _two_forks(spec, state, store,
                                                  test_steps)
    loser_state, loser_root = state_b, root_b
    att = get_valid_attestation(spec, loser_state, signed=True)
    slashing = get_valid_attester_slashing(
        spec, loser_state, slot=att.data.slot, signed_1=True, signed_2=True)
    participants = set(map(int, get_indexed_attestation_participants(
        spec, slashing.attestation_1)))
    _tick_next_slot(spec, store, test_steps)
    _tick_next_slot(spec, store, test_steps)
    add_attester_slashing(spec, store, slashing, test_steps)
    add_attestation(spec, store, att, test_steps)
    assert not (participants & set(store.latest_messages.keys()))
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_proposer_boost_weight_without_votes(spec, state):
    """A timely block's weight includes the committee-fraction boost
    even with zero attestations."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    on_tick_and_append_step(
        spec, store,
        store.genesis_time
        + int(signed.message.slot) * int(spec.config.SECONDS_PER_SLOT),
        test_steps)
    tick_and_add_block(spec, store, signed, test_steps)
    root = hash_tree_root(block)
    assert bytes(store.proposer_boost_root) == root
    assert spec.get_weight(store, root) > 0
    # after the boost wears off (next slot), weight drops back to zero
    _tick_next_slot(spec, store, test_steps)
    assert spec.get_weight(store, root) == 0
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_invalid_attestation_unknown_block(spec, state):
    """on_attestation rejects votes for blocks the store has not seen."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    att = get_valid_attestation(spec, state, signed=True)
    att.data.beacon_block_root = b"\x99" * 32
    _tick_next_slot(spec, store, test_steps)
    _tick_next_slot(spec, store, test_steps)
    add_attestation(spec, store, att, test_steps, valid=False)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_invalid_attestation_future_slot(spec, state):
    """Votes whose slot the store has not reached are rejected (queued
    by real clients, asserted here)."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    tick_and_add_block(spec, store, signed, test_steps)
    att = get_valid_attestation(spec, state, signed=True)
    # store time still at the attestation's slot: slot + 1 not reached
    assert spec.get_current_slot(store) == att.data.slot
    add_attestation(spec, store, att, test_steps, valid=False)
    yield "steps", test_steps
