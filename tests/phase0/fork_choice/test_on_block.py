"""on_block handler invariants: finalized-ancestry guards, proposer
boost timeliness, boost reset, pulled-up justification.

Reference model: ``test/phase0/fork_choice/test_on_block.py`` against
``specs/phase0/fork-choice.md`` on_block/on_tick.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, never_bls,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
    next_slots,
)
from consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store_and_block, on_tick_and_append_step,
    tick_and_add_block, add_block, apply_next_epoch_with_attestations,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root


def _block_time(spec, store, slot):
    return store.genesis_time + int(slot) * int(spec.config.SECONDS_PER_SLOT)


@with_all_phases
@spec_state_test
@never_bls
def test_on_block_basic_chain_checkpoints(spec, state):
    """Two attested epochs: store's justified checkpoint advances."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    on_tick_and_append_step(
        spec, store, _block_time(spec, store, state.slot), test_steps)
    for _ in range(3):
        state, store, _ = apply_next_epoch_with_attestations(
            spec, state, store, True, False, test_steps)
    assert store.justified_checkpoint.epoch > 0
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_invalid_on_block_before_finalized_slot(spec, state):
    """A block at/before the finalized epoch's start slot is rejected."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    on_tick_and_append_step(
        spec, store, _block_time(spec, store, state.slot), test_steps)
    # a competing branch buildable from genesis later
    early_state = state.copy()
    state, store, _ = apply_next_epoch_with_attestations(
        spec, state, store, True, False, test_steps)
    for _ in range(3):
        state, store, _ = apply_next_epoch_with_attestations(
            spec, state, store, True, True, test_steps)
    assert store.finalized_checkpoint.epoch > 0
    # block on the abandoned early branch: slot <= finalized start slot
    block = build_empty_block_for_next_slot(spec, early_state)
    signed = state_transition_and_sign_block(spec, early_state, block)
    assert signed.message.slot <= spec.compute_start_slot_at_epoch(
        store.finalized_checkpoint.epoch)
    add_block(spec, store, signed, test_steps, valid=False)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_invalid_on_block_not_finalized_descendant(spec, state):
    """A block past the finalized slot whose ancestry bypasses the
    finalized checkpoint is rejected."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    on_tick_and_append_step(
        spec, store, _block_time(spec, store, state.slot), test_steps)
    early_state = state.copy()
    state, store, _ = apply_next_epoch_with_attestations(
        spec, state, store, True, False, test_steps)
    for _ in range(3):
        state, store, _ = apply_next_epoch_with_attestations(
            spec, state, store, True, True, test_steps)
    assert store.finalized_checkpoint.epoch > 0
    # grow the early branch beyond the finalized slot, then submit its tip
    finalized_slot = spec.compute_start_slot_at_epoch(
        store.finalized_checkpoint.epoch)
    next_slots(spec, early_state, int(finalized_slot - early_state.slot) + 2)
    block = build_empty_block_for_next_slot(spec, early_state)
    signed = state_transition_and_sign_block(spec, early_state, block)
    assert signed.message.slot > finalized_slot
    # its parent chain is NOT in the store (pruned branch): on_block asserts
    add_block(spec, store, signed, test_steps, valid=False)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_on_block_finalized_skip_slots(spec, state):
    """A valid descendant after skipped slots is accepted and can win."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    on_tick_and_append_step(
        spec, store, _block_time(spec, store, state.slot), test_steps)
    state, store, _ = apply_next_epoch_with_attestations(
        spec, state, store, True, False, test_steps)
    state, store, _ = apply_next_epoch_with_attestations(
        spec, state, store, True, True, test_steps)
    next_slots(spec, state, 3)          # skip slots
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    tick_and_add_block(spec, store, signed, test_steps)
    assert bytes(spec.get_head(store)) == hash_tree_root(block)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_proposer_boost_timely_block(spec, state):
    """A block arriving before the attesting interval earns the boost."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    # tick exactly to the block's slot start: within the first interval
    on_tick_and_append_step(
        spec, store, _block_time(spec, store, signed.message.slot),
        test_steps)
    add_block(spec, store, signed, test_steps)
    assert bytes(store.proposer_boost_root) == hash_tree_root(block)
    assert store.block_timeliness[hash_tree_root(block)]
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_proposer_boost_late_block_not_boosted(spec, state):
    """Arrival after the attesting-interval cutoff: no boost."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    late = (_block_time(spec, store, signed.message.slot)
            + int(spec.config.SECONDS_PER_SLOT) // spec.INTERVALS_PER_SLOT + 1)
    on_tick_and_append_step(spec, store, late, test_steps)
    add_block(spec, store, signed, test_steps)
    assert bytes(store.proposer_boost_root) == b"\x00" * 32
    assert not store.block_timeliness[hash_tree_root(block)]
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_proposer_boost_cleared_next_slot(spec, state):
    """on_tick into the next slot wipes proposer_boost_root."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    on_tick_and_append_step(
        spec, store, _block_time(spec, store, signed.message.slot),
        test_steps)
    add_block(spec, store, signed, test_steps)
    assert bytes(store.proposer_boost_root) != b"\x00" * 32
    on_tick_and_append_step(
        spec, store, _block_time(spec, store, signed.message.slot + 1),
        test_steps)
    assert bytes(store.proposer_boost_root) == b"\x00" * 32
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_proposer_boost_not_stolen_by_second_block(spec, state):
    """Boost goes to the FIRST timely block of the slot only."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    base = state.copy()
    block_a = build_empty_block_for_next_slot(spec, state)
    signed_a = state_transition_and_sign_block(spec, state, block_a)
    # competing block for the SAME slot (different graffiti)
    state_b = base.copy()
    block_b = build_empty_block_for_next_slot(spec, state_b)
    block_b.body.graffiti = b"\x42" * 32
    signed_b = state_transition_and_sign_block(spec, state_b, block_b)
    on_tick_and_append_step(
        spec, store, _block_time(spec, store, signed_a.message.slot),
        test_steps)
    add_block(spec, store, signed_a, test_steps)
    add_block(spec, store, signed_b, test_steps)
    assert bytes(store.proposer_boost_root) == hash_tree_root(block_a)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_pulled_up_justification_applied_at_epoch_boundary(spec, state):
    """Unrealized justification becomes realized when the epoch ticks
    over (on_tick_per_slot at the boundary)."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    on_tick_and_append_step(
        spec, store, _block_time(spec, store, state.slot), test_steps)
    state, store, _ = apply_next_epoch_with_attestations(
        spec, state, store, True, False, test_steps)
    state, store, _ = apply_next_epoch_with_attestations(
        spec, state, store, True, False, test_steps)
    unrealized = store.unrealized_justified_checkpoint
    assert unrealized.epoch >= store.justified_checkpoint.epoch
    # tick to the next epoch boundary: unrealized promotes
    next_boundary_slot = spec.compute_start_slot_at_epoch(
        spec.compute_epoch_at_slot(spec.get_current_slot(store)) + 1)
    on_tick_and_append_step(
        spec, store, _block_time(spec, store, next_boundary_slot), test_steps)
    assert store.justified_checkpoint.epoch == unrealized.epoch
    yield "steps", test_steps
