"""Fork-choice store tests.

Reference models: ``test/phase0/fork_choice/test_get_head.py`` and
``test_on_block.py`` (event-sourced store simulation with head checks).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, never_bls, pytest_only,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block, next_slots)
from consensus_specs_tpu.test_infra.attestations import (
    get_valid_attestation,
)
from consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store_and_block, on_tick_and_append_step, tick_and_add_block, add_attestation, apply_next_epoch_with_attestations)
from consensus_specs_tpu.utils.ssz import hash_tree_root


@with_all_phases
@spec_state_test
@never_bls
def test_genesis_head(spec, state):
    store, genesis_block = get_genesis_forkchoice_store_and_block(spec, state)
    assert bytes(spec.get_head(store)) == hash_tree_root(genesis_block)
    yield


@with_all_phases
@spec_state_test
@never_bls
def test_chain_no_attestations(spec, state):
    test_steps = []
    store, genesis_block = get_genesis_forkchoice_store_and_block(spec, state)
    anchor_root = hash_tree_root(genesis_block)
    assert bytes(spec.get_head(store)) == anchor_root

    block1 = build_empty_block_for_next_slot(spec, state)
    signed1 = state_transition_and_sign_block(spec, state, block1)
    tick_and_add_block(spec, store, signed1, test_steps)
    block2 = build_empty_block_for_next_slot(spec, state)
    signed2 = state_transition_and_sign_block(spec, state, block2)
    tick_and_add_block(spec, store, signed2, test_steps)

    assert bytes(spec.get_head(store)) == hash_tree_root(block2)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_split_tie_breaker_no_attestations(spec, state):
    """Two competing heads at the same height: lexicographically
    greater root wins (fork-choice.md get_head tie-break)."""
    test_steps = []
    store, genesis_block = get_genesis_forkchoice_store_and_block(spec, state)
    base_state = state.copy()

    state1 = base_state.copy()
    block1 = build_empty_block_for_next_slot(spec, state1)
    signed1 = state_transition_and_sign_block(spec, state1, block1)

    state2 = base_state.copy()
    block2 = build_empty_block_for_next_slot(spec, state2)
    block2.body.graffiti = b"\x42" * 32
    signed2 = state_transition_and_sign_block(spec, state2, block2)

    # tick past slot 1 so the proposer boost does not break the tie
    time = store.genesis_time + (int(block2.slot) + 1) * spec.config.SECONDS_PER_SLOT
    on_tick_and_append_step(spec, store, time, test_steps)
    tick_and_add_block(spec, store, signed1, test_steps)
    tick_and_add_block(spec, store, signed2, test_steps)

    expected = max(hash_tree_root(block1), hash_tree_root(block2))
    assert bytes(spec.get_head(store)) == expected
    yield "steps", test_steps


@with_all_phases
@spec_state_test
def test_shorter_chain_but_heavier_weight(spec, state):
    """An attested one-block chain beats an unattested longer chain."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    base_state = state.copy()

    # longer chain with no attestations
    long_state = base_state.copy()
    for _ in range(3):
        b = build_empty_block_for_next_slot(spec, long_state)
        sb = state_transition_and_sign_block(spec, long_state, b)
        tick_and_add_block(spec, store, sb, test_steps)
    long_head = spec.get_head(store)

    # short chain with an attestation
    short_state = base_state.copy()
    short_block = build_empty_block_for_next_slot(spec, short_state)
    short_block.body.graffiti = b"\x99" * 32
    signed_short = state_transition_and_sign_block(spec, short_state, short_block)
    tick_and_add_block(spec, store, signed_short, test_steps)

    att = get_valid_attestation(spec, short_state, slot=short_block.slot,
                                signed=True)
    next_slots(spec, short_state, 2)  # make attestation slot reachable
    time = store.genesis_time + int(short_state.slot) * spec.config.SECONDS_PER_SLOT
    on_tick_and_append_step(spec, store, time, test_steps)
    add_attestation(spec, store, att, test_steps)

    head = spec.get_head(store)
    assert bytes(head) == hash_tree_root(short_block)
    assert bytes(head) != bytes(long_head)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_on_block_future_block(spec, state):
    """Blocks from the future are not added to the store."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    # do not tick: store time stays at genesis
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    tick_and_add_block(spec, store, signed, test_steps, valid=False,
                       block_not_ticked=True)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_on_block_bad_parent_root(spec, state):
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    signed.message.parent_root = b"\x55" * 32
    time = store.genesis_time + int(block.slot) * spec.config.SECONDS_PER_SLOT
    on_tick_and_append_step(spec, store, time, test_steps)
    from consensus_specs_tpu.test_infra.fork_choice import add_block
    add_block(spec, store, signed, test_steps, valid=False)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_proposer_boost(spec, state):
    """A timely block gets the proposer score boost; the boost wears off
    at the next slot (fork-choice.md on_block boost + on_tick reset)."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)

    # arrive exactly at the block's slot start: timely
    time = (store.genesis_time
            + int(block.slot) * spec.config.SECONDS_PER_SLOT)
    on_tick_and_append_step(spec, store, time, test_steps)
    tick_and_add_block(spec, store, signed, test_steps)
    root = hash_tree_root(block)
    assert bytes(store.proposer_boost_root) == root
    assert spec.get_weight(store, root) > 0

    # next slot: boost resets
    on_tick_and_append_step(
        spec, store, time + spec.config.SECONDS_PER_SLOT, test_steps)
    assert bytes(store.proposer_boost_root) == b"\x00" * 32
    assert spec.get_weight(store, root) == 0
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@never_bls
def test_on_attestation_future_epoch(spec, state):
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    tick_and_add_block(spec, store, signed, test_steps)
    # attestation targets a future epoch relative to store time
    att = get_valid_attestation(spec, state, slot=block.slot, signed=False)
    att.data.target.epoch = spec.get_current_store_epoch(store) + 1
    add_attestation(spec, store, att, test_steps, valid=False)
    yield "steps", test_steps


@with_all_phases
@spec_state_test
def test_on_attestation_updates_latest_messages(spec, state):
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    tick_and_add_block(spec, store, signed, test_steps)

    att = get_valid_attestation(spec, state, slot=block.slot, signed=True)
    # move store time forward so the attestation slot is in the past
    time = (store.genesis_time
            + (int(att.data.slot) + 2) * spec.config.SECONDS_PER_SLOT)
    on_tick_and_append_step(spec, store, time, test_steps)
    assert len(store.latest_messages) == 0
    add_attestation(spec, store, att, test_steps)
    assert len(store.latest_messages) > 0
    for msg in store.latest_messages.values():
        assert msg.root == bytes(att.data.beacon_block_root)
        assert msg.epoch == att.data.target.epoch
    yield "steps", test_steps


@with_all_phases
@spec_state_test
def test_justification_update_from_epoch_transition(spec, state):
    """Run >2 epochs of fully-attested blocks through the store and check
    the store's justified checkpoint advances."""
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    assert store.justified_checkpoint.epoch == 0
    for _ in range(3):
        state, store, _ = apply_next_epoch_with_attestations(
            spec, state, store, True, False, test_steps)
    assert store.justified_checkpoint.epoch > 0
    yield "steps", test_steps


@with_all_phases
@spec_state_test
@pytest_only
def test_safe_block_root_is_justified(spec, state):
    """specs/fork_choice/safe-block.md: at the genesis anchor the safe
    block IS the anchor, and its payload hash is the zero hash on every
    fork (pre-merge structurally; post-merge because the anchor block's
    empty payload carries a zero block_hash)."""
    store, anchor = get_genesis_forkchoice_store_and_block(spec, state)
    assert spec.get_safe_beacon_block_root(store) == \
        hash_tree_root(anchor)
    safe_hash = spec.get_safe_execution_payload_hash(store)
    assert bytes(safe_hash) == b"\x00" * 32
    assert hash_tree_root(safe_hash) == safe_hash  # SSZ-typed return
