"""Epoch-processing + finality tests.

Reference: ``test/phase0/epoch_processing/*`` and
``test/phase0/finality/test_finality.py`` (condensed representative cases).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, never_bls,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with, run_epoch_processing_to,
)
from consensus_specs_tpu.test_infra.attestations import next_epoch_with_attestations
from consensus_specs_tpu.test_infra.block import next_epoch


@with_all_phases
@spec_state_test
def test_effective_balance_hysteresis(spec, state):
    # run up to the sub-transition under test
    run_epoch_processing_to(spec, state, "process_effective_balance_updates")
    max_bal = spec.MAX_EFFECTIVE_BALANCE
    min_bal = spec.EFFECTIVE_BALANCE_INCREMENT
    down = spec.EFFECTIVE_BALANCE_INCREMENT // spec.HYSTERESIS_QUOTIENT \
        * spec.HYSTERESIS_DOWNWARD_MULTIPLIER
    up = spec.EFFECTIVE_BALANCE_INCREMENT // spec.HYSTERESIS_QUOTIENT \
        * spec.HYSTERESIS_UPWARD_MULTIPLIER
    cases = [
        # (pre_eff, balance, post_eff)
        (max_bal, max_bal, max_bal),
        (max_bal, max_bal - 1, max_bal),            # no change: within down threshold
        (max_bal, max_bal - down - 1, max_bal - min_bal),  # below downward threshold
        (max_bal - min_bal, max_bal - min_bal + up - 1, max_bal - min_bal),
        (max_bal - min_bal, max_bal - min_bal + up + 1, max_bal),  # above upward threshold
    ]
    for i, (pre_eff, balance, _) in enumerate(cases):
        state.validators[i].effective_balance = pre_eff
        state.balances[i] = balance
    yield "pre", state
    spec.process_effective_balance_updates(state)
    yield "post", state
    for i, (_, _, post_eff) in enumerate(cases):
        assert state.validators[i].effective_balance == post_eff, f"case {i}"


@with_all_phases
@spec_state_test
def test_eth1_data_votes_no_reset(spec, state):
    assert spec.EPOCHS_PER_ETH1_VOTING_PERIOD > 1
    # skip ahead to near the end of epoch 0
    state.slot = spec.SLOTS_PER_EPOCH - 1
    for i in range(state.slot + 1):
        state.eth1_data_votes.append(spec.Eth1Data(deposit_count=i))
    yield from run_epoch_processing_with(spec, state, "process_eth1_data_reset")
    assert len(state.eth1_data_votes) == spec.SLOTS_PER_EPOCH


@with_all_phases
@spec_state_test
def test_eth1_data_votes_reset(spec, state):
    state.slot = spec.SLOTS_PER_EPOCH * spec.EPOCHS_PER_ETH1_VOTING_PERIOD - 1
    for i in range(3):
        state.eth1_data_votes.append(spec.Eth1Data(deposit_count=i))
    yield from run_epoch_processing_with(spec, state, "process_eth1_data_reset")
    assert len(state.eth1_data_votes) == 0


@with_all_phases
@spec_state_test
def test_registry_activation(spec, state):
    # add a fresh validator awaiting activation
    index = len(state.validators)
    validator = spec.Validator(
        pubkey=b"\xaa" * 48,
        withdrawal_credentials=b"\x00" * 32,
        effective_balance=spec.MAX_EFFECTIVE_BALANCE,
        activation_eligibility_epoch=spec.FAR_FUTURE_EPOCH,
        activation_epoch=spec.FAR_FUTURE_EPOCH,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
    )
    state.validators.append(validator)
    state.balances.append(spec.MAX_EFFECTIVE_BALANCE)
    yield from run_epoch_processing_with(spec, state, "process_registry_updates")
    # eligibility epoch set (activation itself waits on finality)
    assert state.validators[index].activation_eligibility_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_ejection(spec, state):
    index = 0
    assert spec.is_active_validator(
        state.validators[index], spec.get_current_epoch(state))
    state.validators[index].effective_balance = spec.config.EJECTION_BALANCE
    yield from run_epoch_processing_with(spec, state, "process_registry_updates")
    assert state.validators[index].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
@never_bls
def test_finality_from_full_attestation_epochs(spec, state):
    # epoch 0 -> no finality possible yet
    next_epoch(spec, state)

    blocks = []
    for epoch in range(4):
        prev_state, new_blocks, state = next_epoch_with_attestations(
            spec, state, True, False)
        blocks += new_blocks

    # with full participation across epochs, justification + finalization advance
    assert state.current_justified_checkpoint.epoch > 0
    assert state.finalized_checkpoint.epoch > 0
    yield "post", state


@with_all_phases
@spec_state_test
@never_bls
def test_rewards_applied_at_epoch_boundary(spec, state):
    next_epoch(spec, state)
    _, _, state = next_epoch_with_attestations(spec, state, True, False)
    pre_balances = list(state.balances)
    # process one more epoch with the pending attestations
    spec.process_slots(
        state, state.slot + spec.SLOTS_PER_EPOCH - state.slot % spec.SLOTS_PER_EPOCH)
    # attesters must have earned rewards (balances changed)
    assert list(state.balances) != pre_balances
