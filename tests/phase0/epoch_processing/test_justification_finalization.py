"""Justification/finalization rule matrix.

Reference: ``test/phase0/epoch_processing/
test_process_justification_and_finalization.py`` (the 234/23/123/12
finality-rule cases).  Support is mocked directly: pending attestations
for phase0, participation flags for altair+, covering a controlled
fraction of the active set.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with, run_epoch_processing_to,
)
from consensus_specs_tpu.test_infra.block import next_epoch
from consensus_specs_tpu.utils.ssz import Bitlist


def _mock_target_support(spec, state, epoch, numer, denom):
    """Give the target checkpoint of ``epoch`` attesting support from
    ``numer/denom`` of each committee."""
    assert epoch in (spec.get_current_epoch(state),
                     spec.get_previous_epoch(state))
    target_root = spec.get_block_root(state, epoch)
    is_current = epoch == spec.get_current_epoch(state)
    start_slot = spec.compute_start_slot_at_epoch(epoch)
    if spec.fork == "phase0":
        pending = (state.current_epoch_attestations if is_current
                   else state.previous_epoch_attestations)
        for slot in range(start_slot,
                          start_slot + spec.SLOTS_PER_EPOCH):
            if slot >= state.slot:
                break
            committees = spec.get_committee_count_per_slot(
                state, epoch)
            for index in range(committees):
                committee = spec.get_beacon_committee(state, slot, index)
                take = (len(committee) * numer + denom - 1) // denom
                bits = [i < take for i in range(len(committee))]
                pending.append(spec.PendingAttestation(
                    aggregation_bits=Bitlist[
                        spec.MAX_VALIDATORS_PER_COMMITTEE](bits),
                    data=spec.AttestationData(
                        slot=slot, index=index,
                        beacon_block_root=target_root,
                        source=spec.Checkpoint(
                            epoch=state.current_justified_checkpoint.epoch
                            if is_current
                            else state.previous_justified_checkpoint.epoch),
                        target=spec.Checkpoint(
                            epoch=epoch, root=target_root),
                    ),
                    inclusion_delay=1,
                    proposer_index=0,
                ))
    else:
        participation = (state.current_epoch_participation if is_current
                         else state.previous_epoch_participation)
        active = spec.get_active_validator_indices(state, epoch)
        take = (len(active) * numer + denom - 1) // denom
        flag = spec.TIMELY_TARGET_FLAG_INDEX
        for i in active[:take]:
            participation[i] = spec.add_flag(participation[i], flag)


def _state_at_epoch(spec, state, epoch):
    while spec.get_current_epoch(state) < epoch:
        next_epoch(spec, state)


def _run_jf(spec, state):
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization")


@with_all_phases
@spec_state_test
def test_justify_previous_epoch_ok_support(spec, state):
    _state_at_epoch(spec, state, 3)
    run_epoch_processing_to(
        spec, state, "process_justification_and_finalization")
    prev = spec.get_previous_epoch(state)
    _mock_target_support(spec, state, prev, 3, 4)
    yield "pre", state
    spec.process_justification_and_finalization(state)
    yield "post", state
    assert state.current_justified_checkpoint.epoch == prev
    assert state.justification_bits[1]


@with_all_phases
@spec_state_test
def test_no_justification_poor_support(spec, state):
    _state_at_epoch(spec, state, 3)
    run_epoch_processing_to(
        spec, state, "process_justification_and_finalization")
    prev = spec.get_previous_epoch(state)
    pre_justified = state.current_justified_checkpoint.epoch
    _mock_target_support(spec, state, prev, 1, 4)
    yield "pre", state
    spec.process_justification_and_finalization(state)
    yield "post", state
    assert state.current_justified_checkpoint.epoch == pre_justified
    assert not state.justification_bits[1]


def _setup_finality_case(spec, state, epoch, prev_justified_epoch,
                         cur_justified_epoch, bits):
    _state_at_epoch(spec, state, epoch)
    run_epoch_processing_to(
        spec, state, "process_justification_and_finalization")
    state.previous_justified_checkpoint = spec.Checkpoint(
        epoch=prev_justified_epoch,
        root=spec.get_block_root(state, prev_justified_epoch))
    state.current_justified_checkpoint = spec.Checkpoint(
        epoch=cur_justified_epoch,
        root=spec.get_block_root(state, cur_justified_epoch))
    for i, bit in enumerate(bits):
        state.justification_bits[i] = bit


@with_all_phases
@spec_state_test
def test_finalize_rule_23(spec, state):
    # bits[1:3] after shift + old_previous.epoch + 2 == current
    _setup_finality_case(spec, state, epoch=4,
                         prev_justified_epoch=2, cur_justified_epoch=3,
                         bits=[1, 1, 0, 0])
    _mock_target_support(spec, state, spec.get_previous_epoch(state), 3, 4)
    yield "pre", state
    spec.process_justification_and_finalization(state)
    yield "post", state
    assert state.finalized_checkpoint.epoch == 2


@with_all_phases
@spec_state_test
def test_finalize_rule_234(spec, state):
    # bits[1:4] after shift + old_previous.epoch + 3 == current
    _setup_finality_case(spec, state, epoch=4,
                         prev_justified_epoch=1, cur_justified_epoch=3,
                         bits=[1, 1, 1, 0])
    _mock_target_support(spec, state, spec.get_previous_epoch(state), 3, 4)
    yield "pre", state
    spec.process_justification_and_finalization(state)
    yield "post", state
    assert state.finalized_checkpoint.epoch == 1


@with_all_phases
@spec_state_test
def test_finalize_rule_12(spec, state):
    # bits[0:2] after shift + old_current.epoch + 1 == current: needs
    # CURRENT-epoch supermajority
    _setup_finality_case(spec, state, epoch=4,
                         prev_justified_epoch=3, cur_justified_epoch=3,
                         bits=[1, 0, 0, 0])
    # full coverage: current-epoch attestations only span elapsed slots,
    # so a 3/4-per-committee fraction would land under the 2/3 line
    _mock_target_support(spec, state, spec.get_current_epoch(state), 1, 1)
    yield "pre", state
    spec.process_justification_and_finalization(state)
    yield "post", state
    assert state.finalized_checkpoint.epoch == 3


@with_all_phases
@spec_state_test
def test_finalize_rule_123(spec, state):
    # bits[0:3] after shift + old_current.epoch + 2 == current
    _setup_finality_case(spec, state, epoch=4,
                         prev_justified_epoch=2, cur_justified_epoch=2,
                         bits=[1, 1, 0, 0])
    # full coverage (see rule_12): current-epoch attestations span only
    # the elapsed slots, so 3/4 per committee would miss the 2/3 line
    _mock_target_support(spec, state, spec.get_current_epoch(state), 1, 1)
    yield "pre", state
    spec.process_justification_and_finalization(state)
    yield "post", state
    assert state.finalized_checkpoint.epoch == 2


@with_all_phases
@spec_state_test
def test_no_finalize_poor_support(spec, state):
    # bits chosen so no finality rule can fire from history alone: after
    # the shift only bits[1] is set, and poor support sets nothing new
    _setup_finality_case(spec, state, epoch=4,
                         prev_justified_epoch=2, cur_justified_epoch=3,
                         bits=[1, 0, 0, 0])
    _mock_target_support(spec, state, spec.get_previous_epoch(state), 1, 4)
    yield "pre", state
    spec.process_justification_and_finalization(state)
    yield "post", state
    assert state.finalized_checkpoint.epoch == 0
