"""Registry-update and slashings epoch-processing depth.

Reference: ``test/phase0/epoch_processing/test_process_registry_updates.py``
(activation queue ordering/efficiency/churn interaction) and
``test_process_slashings.py`` (penalty magnitudes).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with, run_epoch_processing_to,
)


def _queue_validator(spec, state, index, epoch):
    v = state.validators[index]
    v.activation_eligibility_epoch = epoch
    v.activation_epoch = spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_add_to_activation_queue(spec, state):
    index = 0
    state.validators[index].activation_eligibility_epoch = \
        spec.FAR_FUTURE_EPOCH
    state.validators[index].activation_epoch = spec.FAR_FUTURE_EPOCH
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    # eligibility is stamped with the NEXT epoch
    assert state.validators[index].activation_eligibility_epoch \
        == spec.get_current_epoch(state) + 1
    assert state.validators[index].activation_epoch \
        == spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_activation_queue_sorting(spec, state):
    # queue five validators with eligibility epochs out of index order:
    # activations must dequeue by (eligibility_epoch, index)
    churn = int(spec.get_validator_churn_limit(state))
    # eligibility must be <= finalized epoch to dequeue
    state.finalized_checkpoint.epoch = 2
    for index in range(5):
        _queue_validator(spec, state, index, epoch=2)
    # index 2 gets the EARLIEST eligibility: it must beat lower indices
    state.validators[2].activation_eligibility_epoch = 1
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    activated = [i for i in range(5)
                 if state.validators[i].activation_epoch
                 != spec.FAR_FUTURE_EPOCH]
    assert len(activated) == min(5, churn)
    # ordering: (eligibility_epoch, index) — index 2 first, then 0, 1...
    expected = [2] + [i for i in (0, 1, 3, 4)][:max(0, churn - 1)]
    assert sorted(activated) == sorted(expected)


@with_all_phases
@spec_state_test
def test_activation_queue_no_activation_no_finality(spec, state):
    # finality far behind eligibility: nobody activates
    index = 0
    _queue_validator(spec, state, index,
                     epoch=state.finalized_checkpoint.epoch + 1)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_epoch \
        == spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_activation_queue_efficiency_min(spec, state):
    # more eligible validators than the churn limit: exactly churn-many
    # activate per epoch
    churn = spec.get_validator_churn_limit(state)
    n = int(churn) + 2
    for index in range(n):
        _queue_validator(spec, state, index, epoch=0)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    activated = [i for i in range(n)
                 if state.validators[i].activation_epoch
                 != spec.FAR_FUTURE_EPOCH]
    assert len(activated) == churn


@with_all_phases
@spec_state_test
def test_ejection_past_churn_limit_min(spec, state):
    # every ejected validator is queued for exit even past the churn
    # limit: exit epochs spread out across the queue
    churn = spec.get_validator_churn_limit(state)
    n = int(churn) + 2
    for index in range(n):
        state.validators[index].effective_balance = \
            spec.config.EJECTION_BALANCE
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    exit_epochs = [state.validators[i].exit_epoch for i in range(n)]
    assert all(e != spec.FAR_FUTURE_EPOCH for e in exit_epochs)
    # the queue spills into at least one later epoch
    assert len(set(exit_epochs)) >= 2


@with_all_phases
@spec_state_test
def test_slashings_proportional_penalties(spec, state):
    # slash a third of the registry and check the exact proportional
    # penalty formula per fork (multiplier 1 in phase0, 2 in altair,
    # 3 from bellatrix — full wipe-out only when the cap saturates)
    slashed_count = (len(state.validators) + 2) // 3
    out_epoch = spec.get_current_epoch(state) \
        + spec.EPOCHS_PER_SLASHINGS_VECTOR // 2
    total_balance = spec.get_total_active_balance(state)
    for i in range(slashed_count):
        v = state.validators[i]
        v.slashed = True
        v.withdrawable_epoch = out_epoch
        state.slashings[
            out_epoch % spec.EPOCHS_PER_SLASHINGS_VECTOR] += \
            v.effective_balance
    pre_balances = [int(state.balances[i]) for i in range(slashed_count)]
    total_penalties = sum(state.slashings)
    run_epoch_processing_to(spec, state, "process_slashings")
    yield "pre", state
    spec.process_slashings(state)
    yield "post", state
    increment = spec.EFFECTIVE_BALANCE_INCREMENT
    # the multiplier is renamed per fork (1x / 2x / 3x) and the preset
    # injects all three names onto every spec: select by fork ladder
    if spec.fork == "phase0":
        multiplier = spec.PROPORTIONAL_SLASHING_MULTIPLIER
    elif spec.fork == "altair":
        multiplier = spec.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR
    else:
        multiplier = spec.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX
    adjusted = min(total_penalties * multiplier, total_balance)
    for i in range(slashed_count):
        eff = state.validators[i].effective_balance
        expected = eff // increment * adjusted // total_balance * increment
        assert state.balances[i] == pre_balances[i] - expected
        assert expected > 0


@with_all_phases
@spec_state_test
def test_slashings_low_penalty(spec, state):
    # one small slashing: penalty proportional to total slashed, floored
    # at increments
    out_epoch = spec.get_current_epoch(state) \
        + spec.EPOCHS_PER_SLASHINGS_VECTOR // 2
    v = state.validators[0]
    v.slashed = True
    v.withdrawable_epoch = out_epoch
    state.slashings[out_epoch % spec.EPOCHS_PER_SLASHINGS_VECTOR] = \
        v.effective_balance
    pre_balance = state.balances[0]
    run_epoch_processing_to(spec, state, "process_slashings")
    yield "pre", state
    spec.process_slashings(state)
    yield "post", state
    assert state.balances[0] <= pre_balance
    # single slashing against a large registry: penalty far below the
    # full effective balance
    assert state.balances[0] > pre_balance - v.effective_balance


@with_all_phases
@spec_state_test
def test_slashings_no_penalty_outside_window(spec, state):
    # slashed but withdrawable epoch NOT at the halfway point: no
    # penalty applied this epoch
    v = state.validators[0]
    v.slashed = True
    v.withdrawable_epoch = spec.get_current_epoch(state) \
        + spec.EPOCHS_PER_SLASHINGS_VECTOR // 2 + 5
    pre_balance = state.balances[0]
    run_epoch_processing_to(spec, state, "process_slashings")
    yield "pre", state
    spec.process_slashings(state)
    yield "post", state
    assert state.balances[0] == pre_balance


def _eject_validator(spec, state, index):
    state.validators[index].effective_balance = spec.config.EJECTION_BALANCE
    state.balances[index] = spec.config.EJECTION_BALANCE


@with_all_phases
@spec_state_test
def test_ejection(spec, state):
    """One validator at EJECTION_BALANCE is exited by registry updates."""
    index = 0
    assert spec.is_active_validator(
        state.validators[index], spec.get_current_epoch(state))
    _eject_validator(spec, state, index)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_activation_queue_to_activated_if_finalized(spec, state):
    """Eligibility at/below the finalized epoch dequeues; above it stays."""
    state.finalized_checkpoint.epoch = 2
    _queue_validator(spec, state, 0, epoch=2)       # dequeues
    _queue_validator(spec, state, 1, epoch=3)       # stays queued
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[0].activation_epoch != spec.FAR_FUTURE_EPOCH
    assert state.validators[1].activation_epoch == spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_activation_and_ejection_one_each(spec, state):
    """Activation churn and ejections process independently in one pass."""
    state.finalized_checkpoint.epoch = 2
    _queue_validator(spec, state, 0, epoch=2)
    _eject_validator(spec, state, 1)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[0].activation_epoch != spec.FAR_FUTURE_EPOCH
    assert state.validators[1].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_activation_queue_exceeds_churn_limit(spec, state):
    """churn+1 eligible validators: exactly churn activate, the tail
    (highest index) stays queued."""
    churn = int(spec.get_validator_churn_limit(state))
    state.finalized_checkpoint.epoch = 2
    for index in range(churn + 1):
        _queue_validator(spec, state, index, epoch=2)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    activated = [i for i in range(churn + 1)
                 if state.validators[i].activation_epoch
                 != spec.FAR_FUTURE_EPOCH]
    assert len(activated) == churn
    assert churn not in activated
    assert state.validators[churn].activation_epoch == spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_ejections_past_churn_all_exit(spec, state):
    """Ejections are NOT churn-limited at initiation: every ejected
    validator gets an exit epoch, the queue spreads via exit churn."""
    churn = int(spec.get_validator_churn_limit(state))
    count = churn + 2
    for index in range(count):
        _eject_validator(spec, state, index)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    exited = [i for i in range(count)
              if state.validators[i].exit_epoch != spec.FAR_FUTURE_EPOCH]
    assert len(exited) == count
    # exit epochs cluster then spill by churn
    epochs = sorted(int(state.validators[i].exit_epoch) for i in exited)
    assert epochs[-1] >= epochs[0]
    assert len([e for e in epochs if e == epochs[0]]) <= churn


@with_all_phases
@spec_state_test
def test_effective_balance_hysteresis(spec, state):
    """process_effective_balance_updates: the effective balance moves
    only when the balance leaves the hysteresis band."""
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    q = inc // int(spec.HYSTERESIS_QUOTIENT)
    down = q * int(spec.HYSTERESIS_DOWNWARD_MULTIPLIER)
    up = q * int(spec.HYSTERESIS_UPWARD_MULTIPLIER)
    max_eff = int(spec.MAX_EFFECTIVE_BALANCE)
    # (pre_effective, balance) pairs probing both band edges
    cases = [
        (max_eff, max_eff),                 # at cap, no move
        (max_eff, max_eff - down),          # inside band: hold
        (max_eff, max_eff - down - 1),      # below band: drop
        (max_eff - inc, max_eff - inc + up),      # inside band: hold
        (max_eff - inc, max_eff - inc + up + 1),  # above band: rise
    ]
    for i, (pre_eff, bal) in enumerate(cases):
        state.validators[i].effective_balance = pre_eff
        state.balances[i] = bal
    yield from run_epoch_processing_with(
        spec, state, "process_effective_balance_updates")
    for i, (pre_eff, bal) in enumerate(cases):
        if bal + down < pre_eff or pre_eff + up < bal:
            expected = min(bal - bal % inc, max_eff)
        else:
            expected = pre_eff
        assert int(state.validators[i].effective_balance) == expected, i
