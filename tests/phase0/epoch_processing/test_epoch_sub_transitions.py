"""Per-sub-transition epoch-processing tests via the isolation runner.

Reference model: the ``test/phase0/epoch_processing/`` family run through
``run_epoch_processing_to`` (``helpers/epoch_processing.py:43``).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, with_phases,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.test_infra.block import next_epoch
from consensus_specs_tpu.utils.ssz import hash_tree_root


@with_all_phases
@spec_state_test
def test_process_slashings_penalty_applied(spec, state):
    # slash a third of the balance-weight to make the penalty non-zero
    n_slashed = len(state.validators) // 3
    epoch = spec.get_current_epoch(state)
    for index in range(n_slashed):
        validator = state.validators[index]
        validator.slashed = True
        validator.withdrawable_epoch = \
            epoch + spec.EPOCHS_PER_SLASHINGS_VECTOR // 2
        state.slashings[epoch % spec.EPOCHS_PER_SLASHINGS_VECTOR] += \
            validator.effective_balance
    pre_balances = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(spec, state, "process_slashings")
    for index in range(n_slashed):
        assert int(state.balances[index]) < pre_balances[index], index
    assert int(state.balances[n_slashed + 1]) == pre_balances[n_slashed + 1]


@with_all_phases
@spec_state_test
def test_process_slashings_reset(spec, state):
    epoch = spec.get_current_epoch(state)
    next_index = (epoch + 1) % spec.EPOCHS_PER_SLASHINGS_VECTOR
    state.slashings[next_index] = spec.Gwei(10**9)
    yield from run_epoch_processing_with(spec, state,
                                         "process_slashings_reset")
    assert state.slashings[next_index] == 0


@with_all_phases
@spec_state_test
def test_process_randao_mixes_reset(spec, state):
    current_epoch = spec.get_current_epoch(state)
    next_index = (current_epoch + 1) % spec.EPOCHS_PER_HISTORICAL_VECTOR
    state.randao_mixes[next_index] = b"\x77" * 32
    yield from run_epoch_processing_with(spec, state,
                                         "process_randao_mixes_reset")
    assert bytes(state.randao_mixes[next_index]) == \
        bytes(spec.get_randao_mix(state, current_epoch))


@with_phases(["phase0"])
@spec_state_test
def test_process_historical_roots_update(spec, state):
    # jump to the last epoch of a SLOTS_PER_HISTORICAL_ROOT period
    period_epochs = spec.SLOTS_PER_HISTORICAL_ROOT // spec.SLOTS_PER_EPOCH
    while (spec.get_current_epoch(state) + 1) % period_epochs != 0:
        next_epoch(spec, state)
    pre_len = len(state.historical_roots)
    yield from run_epoch_processing_with(spec, state,
                                         "process_historical_roots_update")
    assert len(state.historical_roots) == pre_len + 1
    expected = hash_tree_root(spec.HistoricalBatch(
        block_roots=state.block_roots, state_roots=state.state_roots))
    assert bytes(state.historical_roots[-1]) == expected


@with_phases(["capella", "deneb"])
@spec_state_test
def test_process_historical_summaries_update(spec, state):
    period_epochs = spec.SLOTS_PER_HISTORICAL_ROOT // spec.SLOTS_PER_EPOCH
    while (spec.get_current_epoch(state) + 1) % period_epochs != 0:
        next_epoch(spec, state)
    pre_len = len(state.historical_summaries)
    yield from run_epoch_processing_with(
        spec, state, "process_historical_summaries_update")
    assert len(state.historical_summaries) == pre_len + 1
    assert bytes(state.historical_summaries[-1].block_summary_root) == \
        hash_tree_root(state.block_roots)


@with_phases(["altair", "bellatrix", "capella", "deneb"])
@spec_state_test
def test_process_participation_flag_updates(spec, state):
    for index in range(len(state.validators)):
        state.current_epoch_participation[index] = \
            spec.ParticipationFlags(0b111)
    yield from run_epoch_processing_with(
        spec, state, "process_participation_flag_updates")
    assert all(int(f) == 0b111 for f in state.previous_epoch_participation)
    assert all(int(f) == 0 for f in state.current_epoch_participation)


@with_phases(["altair", "bellatrix", "capella", "deneb"])
@spec_state_test
def test_process_sync_committee_updates_rotation(spec, state):
    """At a sync-committee period boundary, next becomes current."""
    # advance to the last epoch of the period
    while (spec.get_current_epoch(state) + 1) % \
            spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD != 0:
        next_epoch(spec, state)
    pre_next = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_sync_committee_updates")
    assert state.current_sync_committee == pre_next
    # the next committee must be RE-DERIVED, not left stale
    assert state.next_sync_committee == spec.get_next_sync_committee(state)


@with_phases(["altair", "bellatrix", "capella", "deneb"])
@spec_state_test
def test_process_inactivity_updates_scores(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    # non-participants gain score, participants decay to zero
    for index in range(len(state.validators)):
        state.inactivity_scores[index] = 4
        # half participate on target
        if index % 2 == 0:
            state.previous_epoch_participation[index] = \
                spec.ParticipationFlags(1 << spec.TIMELY_TARGET_FLAG_INDEX)
        else:
            state.previous_epoch_participation[index] = \
                spec.ParticipationFlags(0)
    yield from run_epoch_processing_with(spec, state,
                                         "process_inactivity_updates")
    # not in leak: everyone recovers by INACTIVITY_SCORE_RECOVERY_RATE,
    # participants additionally decrement first
    for index in range(len(state.validators)):
        if index % 2 == 0:
            assert int(state.inactivity_scores[index]) < 4
        else:
            expected = 4 + int(spec.config.INACTIVITY_SCORE_BIAS)
            if not spec.is_in_inactivity_leak(state):
                expected = max(0, expected - int(
                    spec.config.INACTIVITY_SCORE_RECOVERY_RATE))
            assert int(state.inactivity_scores[index]) == expected


@with_all_phases
@spec_state_test
def test_process_eth1_data_reset_at_period_boundary(spec, state):
    # fill a vote, advance to the voting-period boundary epoch
    state.eth1_data_votes.append(spec.Eth1Data(deposit_count=1))
    while (spec.get_current_epoch(state) + 1) % \
            spec.EPOCHS_PER_ETH1_VOTING_PERIOD != 0:
        next_epoch(spec, state)
    yield from run_epoch_processing_with(spec, state,
                                         "process_eth1_data_reset")
    assert len(state.eth1_data_votes) == 0
