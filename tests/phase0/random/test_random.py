"""Seeded randomized scenarios across all forks.

Reference model: ``tests/generators/random/main.py`` scenarios compiled
from ``test/utils/randomized_block_tests.py``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases,
)
from consensus_specs_tpu.test_infra.random_scenarios import (
    run_random_scenario,
)


@with_all_phases
@spec_state_test
def test_random_scenario_0(spec, state):
    yield "pre", state
    blocks = run_random_scenario(spec, state, seed=440)
    yield "blocks", blocks
    yield "post", state


@with_all_phases
@spec_state_test
def test_random_scenario_1(spec, state):
    yield "pre", state
    blocks = run_random_scenario(spec, state, seed=7021)
    yield "blocks", blocks
    yield "post", state


@with_all_phases
@spec_state_test
def test_random_scenario_2_longer(spec, state):
    yield "pre", state
    blocks = run_random_scenario(spec, state, seed=90210, epochs=3,
                                 blocks_per_epoch=3)
    yield "blocks", blocks
    yield "post", state
