"""Configuration invariants: cross-constant consistency conditions the
spec's correctness assumes but never re-checks at runtime.

Reference model: ``test/phase0/unittests/test_config_invariants.py``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, never_bls,
)


@with_all_phases
@spec_state_test
@never_bls
def test_validators(spec, state):
    yield
    assert spec.VALIDATOR_REGISTRY_LIMIT == 2 ** 40
    assert spec.MAX_COMMITTEES_PER_SLOT * spec.SLOTS_PER_EPOCH <= \
        spec.VALIDATOR_REGISTRY_LIMIT
    assert spec.config.MIN_PER_EPOCH_CHURN_LIMIT <= \
        spec.VALIDATOR_REGISTRY_LIMIT
    assert spec.config.CHURN_LIMIT_QUOTIENT > 0
    assert spec.SHUFFLE_ROUND_COUNT > 0
    assert spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT > 0


@with_all_phases
@spec_state_test
@never_bls
def test_balances(spec, state):
    yield
    assert spec.MAX_EFFECTIVE_BALANCE % spec.EFFECTIVE_BALANCE_INCREMENT == 0
    assert spec.MIN_DEPOSIT_AMOUNT <= spec.MAX_EFFECTIVE_BALANCE
    assert spec.config.EJECTION_BALANCE < spec.MAX_EFFECTIVE_BALANCE


@with_all_phases
@spec_state_test
@never_bls
def test_hysteresis_quotient(spec, state):
    yield
    assert spec.HYSTERESIS_QUOTIENT > 0
    assert spec.HYSTERESIS_UPWARD_MULTIPLIER > \
        spec.HYSTERESIS_DOWNWARD_MULTIPLIER
    # bounds are fractions of an increment: down = inc/Q, up = U*inc/Q;
    # up sits above one increment (U > Q) but below two (U < 2Q)
    assert spec.HYSTERESIS_DOWNWARD_MULTIPLIER < spec.HYSTERESIS_QUOTIENT
    assert spec.HYSTERESIS_UPWARD_MULTIPLIER < 2 * spec.HYSTERESIS_QUOTIENT


@with_all_phases
@spec_state_test
@never_bls
def test_incentives(spec, state):
    yield
    # penalties must not exceed what whistleblowing can recover
    assert spec.MIN_SLASHING_PENALTY_QUOTIENT > 0
    assert spec.WHISTLEBLOWER_REWARD_QUOTIENT > 0
    assert spec.PROPOSER_REWARD_QUOTIENT > 0 \
        if hasattr(spec, "PROPOSER_REWARD_QUOTIENT") else True
    assert spec.INACTIVITY_PENALTY_QUOTIENT > 0 \
        if hasattr(spec, "INACTIVITY_PENALTY_QUOTIENT") else True


@with_all_phases
@spec_state_test
@never_bls
def test_time(spec, state):
    yield
    assert spec.SLOTS_PER_EPOCH <= spec.SLOTS_PER_HISTORICAL_ROOT
    assert spec.MIN_SEED_LOOKAHEAD < spec.MAX_SEED_LOOKAHEAD
    assert spec.SLOTS_PER_HISTORICAL_ROOT % spec.SLOTS_PER_EPOCH == 0
    assert spec.config.SECONDS_PER_SLOT > 0
    assert spec.EPOCHS_PER_HISTORICAL_VECTOR > spec.MIN_SEED_LOOKAHEAD
    assert spec.EPOCHS_PER_HISTORICAL_VECTOR >= \
        spec.EPOCHS_PER_SLASHINGS_VECTOR
    assert spec.config.MIN_ATTESTATION_INCLUSION_DELAY if False else True
    assert spec.MIN_ATTESTATION_INCLUSION_DELAY <= spec.SLOTS_PER_EPOCH


@with_all_phases
@spec_state_test
@never_bls
def test_incentives_proportional(spec, state):
    """Slashing penalties stay under the full effective balance."""
    yield
    v = state.validators[0]
    assert v.effective_balance // spec.MIN_SLASHING_PENALTY_QUOTIENT \
        <= v.effective_balance


@with_all_phases
@spec_state_test
@never_bls
def test_fork_choice_constants(spec, state):
    yield
    assert 0 < spec.config.PROPOSER_SCORE_BOOST <= 100
    assert spec.INTERVALS_PER_SLOT > 0
    assert int(spec.config.SECONDS_PER_SLOT) % spec.INTERVALS_PER_SLOT == 0


@with_all_phases
@spec_state_test
@never_bls
def test_state_shape_matches_preset(spec, state):
    """The genesis state's vector fields match the preset constants."""
    yield
    assert len(state.block_roots) == spec.SLOTS_PER_HISTORICAL_ROOT
    assert len(state.state_roots) == spec.SLOTS_PER_HISTORICAL_ROOT
    assert len(state.randao_mixes) == spec.EPOCHS_PER_HISTORICAL_VECTOR
    assert len(state.slashings) == spec.EPOCHS_PER_SLASHINGS_VECTOR
