"""Cached-accessor immutability: ``get_active_validator_indices`` and
``get_beacon_committee`` return their cached tuples directly (no O(n)
defensive ``list()`` copy per call), so a caller can no longer poison
the cache by mutating the returned sequence."""
import pytest

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases, never_bls, pytest_only,
)


@with_all_phases
@spec_state_test
@never_bls
@pytest_only
def test_active_indices_cache_immutable(spec, state):
    epoch = spec.get_current_epoch(state)
    first = spec.get_active_validator_indices(state, epoch)
    assert isinstance(first, tuple)
    assert len(first) == len(state.validators)
    # mutation through the return value is impossible...
    with pytest.raises((TypeError, AttributeError)):
        first[0] = 99
    # ...and a caller-side copy can be mangled freely without touching
    # the cache: the next call still sees the full set
    mangled = list(first)
    mangled.clear()
    again = spec.get_active_validator_indices(state, epoch)
    assert again == first and len(again) == len(state.validators)
    # no defensive copy: repeated calls hand back the SAME cached object
    assert again is first


@with_all_phases
@spec_state_test
@never_bls
@pytest_only
def test_beacon_committee_cache_immutable(spec, state):
    committee = spec.get_beacon_committee(state, state.slot, 0)
    assert isinstance(committee, tuple)
    assert len(committee) > 0
    with pytest.raises((TypeError, AttributeError)):
        committee.append(0)
    mangled = list(committee)
    mangled.reverse()
    mangled.pop()
    again = spec.get_beacon_committee(state, state.slot, 0)
    assert again == committee
    assert again is committee
