"""Level-batched incremental merkleization: differential suite.

The dirty-subtree engine (``utils/ssz/merkle.IncrementalTree``), the
hash-forest batch scope (``utils/ssz/forest``) and the columnar
container-root path must produce roots byte-identical to a from-scratch
``merkleize_chunks`` rebuild (and, for typed values, to the
``decode_bytes(serialize())`` oracle — a fresh value with no caches) after
ARBITRARY interleavings of update/truncate/copy/append/pop — with the
batched dispatch forced both ON and OFF.  A divergence is a consensus bug.

The batched path is forced without any native/JAX dependency by
installing a hashlib-backed batched hasher, so this suite exercises the
gather/scatter machinery on every host.
"""
import os
import random
import subprocess
import sys
from hashlib import sha256

import pytest

from consensus_specs_tpu.utils.ssz import merkle
from consensus_specs_tpu.utils.ssz.merkle import (
    IncrementalTree, merkleize_chunks, zero_hashes)
from consensus_specs_tpu.utils.ssz import (
    Bitlist, Bytes32, Bytes48, Container, List, Vector,
    boolean, uint64, replace_basic_items)
from consensus_specs_tpu.utils.ssz import forest
from consensus_specs_tpu.utils.ssz.forest import hash_forest


def _py_batched(data: bytes, n: int) -> bytes:
    """A dependency-free 'batched' hasher: lets the suite force the
    gather/scatter dispatch machinery even when neither the native lib
    nor the JAX kernel is available."""
    return b"".join(sha256(data[i * 64:(i + 1) * 64]).digest()
                    for i in range(n))


@pytest.fixture(params=["batched", "scalar"])
def dispatch_mode(request):
    """Run the test body under both dispatch regimes: every pair/layer
    batched (threshold 1, synthetic batched hasher installed), and the
    pure per-pair hashlib path (threshold never reached)."""
    prev = merkle._batched_hasher
    prev_np = merkle._batched_hasher_np
    prev_thresholds = merkle.batch_thresholds()
    if request.param == "batched":
        merkle.set_batched_hasher(_py_batched)
        merkle.set_batched_hasher_np(None)
        merkle.set_batch_thresholds(layer=1, pairs=1)
    else:
        merkle.set_batched_hasher(None)
        merkle.set_batched_hasher_np(None)
        merkle.set_batch_thresholds(layer=10**9, pairs=10**9)
    yield request.param
    merkle.set_batched_hasher(prev)
    merkle.set_batched_hasher_np(prev_np)
    merkle.set_batch_thresholds(*prev_thresholds)


class Inner(Container):
    pubkey: Bytes48
    wc: Bytes32
    eff: uint64
    slashed: boolean


class Holder(Container):
    nums: List[uint64, 1 << 30]
    inners: List[Inner, 1 << 30]
    fixed: Vector[Bytes32, 32]
    bits: Bitlist[512]
    tag: uint64


def _fresh_root(v):
    return type(v).decode_bytes(v.serialize()).hash_tree_root()


# ---------------------------------------------------------------------------
# IncrementalTree vs merkleize_chunks
# ---------------------------------------------------------------------------

def test_incremental_tree_randomized_differential(dispatch_mode):
    rng = random.Random(20260803)
    for limit in (64, 4096):
        chunks = [rng.randbytes(32) for _ in range(rng.randrange(0, 40))]
        t = IncrementalTree(chunks, limit)
        for step in range(120):
            op = rng.randrange(10)
            if op < 6:     # update: sparse or wide, may extend with gaps
                width = rng.choice([1, 2, 7, 40, 150])
                hi = min(limit - 1, len(chunks) + rng.randrange(0, 30))
                ups = {rng.randrange(hi + 1): rng.randbytes(32)
                       for _ in range(width)}
                for i, c in ups.items():
                    while len(chunks) <= i:
                        chunks.append(b"\x00" * 32)
                    chunks[i] = c
                t.update(ups)
            elif op < 8 and chunks:    # truncate
                keep = rng.randrange(0, len(chunks))
                chunks = chunks[:keep]
                t.truncate(keep)
            elif op == 8:              # copy: divergence must not leak
                t2 = t.copy()
                t2.update({0: rng.randbytes(32)})
                t = t.copy()
            else:                      # bulk leaf replacement
                chunks = [rng.randbytes(32)
                          for _ in range(rng.randrange(0, min(90, limit)))]
                t.set_leaves(b"".join(chunks))
            assert t.root() == merkleize_chunks(chunks, limit=limit), \
                (dispatch_mode, limit, step, op)


def test_empty_and_zero_edges(dispatch_mode):
    t = IncrementalTree([], 4096)
    assert t.root() == zero_hashes[12]
    t.update({0: b"\x01" * 32})
    assert t.root() == merkleize_chunks([b"\x01" * 32], limit=4096)
    t.truncate(0)
    assert t.root() == zero_hashes[12]


# ---------------------------------------------------------------------------
# Typed SSZ values: interleaved mutations vs the no-cache oracle
# ---------------------------------------------------------------------------

def test_ssz_randomized_differential(dispatch_mode):
    rng = random.Random(77)
    v = Holder(
        nums=list(range(300)),
        inners=[Inner(eff=i, pubkey=bytes([i % 251]) * 48)
                for i in range(280)],
        bits=[True, False] * 40,
    )
    assert v.hash_tree_root() == _fresh_root(v)

    def mutate():
        op = rng.randrange(12)
        if op == 0:
            v.nums[rng.randrange(len(v.nums))] = rng.randrange(2 ** 64)
        elif op == 1:
            v.nums.append(rng.randrange(2 ** 64))
        elif op == 2 and len(v.nums) > 1:
            v.nums.pop()
        elif op == 3:
            v.inners[rng.randrange(len(v.inners))].eff = rng.randrange(2 ** 64)
        elif op == 4:
            v.inners[rng.randrange(len(v.inners))] = Inner(
                eff=rng.randrange(2 ** 64), wc=rng.randbytes(32))
        elif op == 5:
            v.inners.append(Inner(eff=rng.randrange(2 ** 64)))
        elif op == 6 and len(v.inners) > 1:
            v.inners.pop()
        elif op == 7:
            v.fixed[rng.randrange(32)] = rng.randbytes(32)
        elif op == 8:
            v.bits[rng.randrange(len(v.bits))] = rng.randrange(2)
        elif op == 9:
            # wide mutation burst: enough dirty chunks to cross batching
            # thresholds inside one flush
            for i in range(0, len(v.nums), 2):
                v.nums[i] = rng.randrange(2 ** 64)
        elif op == 10:
            for i in range(0, len(v.inners), 3):
                v.inners[i].slashed = rng.randrange(2)
        else:
            v.tag = rng.randrange(2 ** 64)

    for step in range(140):
        mutate()
        if step % 4 == 0:
            use_forest = step % 8 == 0
            if use_forest:
                with hash_forest():
                    got = v.hash_tree_root()
            else:
                got = v.hash_tree_root()
            assert got == _fresh_root(v), (dispatch_mode, step, use_forest)
    assert v.hash_tree_root() == _fresh_root(v)


def test_copies_stay_independent_under_batching(dispatch_mode):
    v = Holder(nums=list(range(100)),
               inners=[Inner(eff=i) for i in range(60)])
    r0 = v.hash_tree_root()
    c = v.copy()
    for i in range(0, 100, 2):
        c.nums[i] = 7
    c.inners[3].eff = 123456
    with hash_forest():
        rc = c.hash_tree_root()
    assert v.hash_tree_root() == r0
    assert rc == _fresh_root(c) != r0


def test_packed_commit_rejection_leaves_sequence_untouched():
    a = Holder(nums=[1, 2, 3, 4])
    r0 = a.hash_tree_root()
    with pytest.raises(ValueError):
        replace_basic_items(a.nums, [uint64(9), uint64(8)], packed=b"\x07")
    assert list(a.nums) == [1, 2, 3, 4]      # no partial swap
    assert a.hash_tree_root() == r0 == _fresh_root(a)


def test_packed_bulk_commit_matches_setitem(dispatch_mode):
    np = pytest.importorskip("numpy")
    a = Holder(nums=list(range(512)))
    b = Holder(nums=list(range(512)))
    a.hash_tree_root(), b.hash_tree_root()   # warm both trees
    col = np.arange(512, dtype=np.uint64) * np.uint64(3)
    items = [uint64(int(x)) for x in col.tolist()]
    replace_basic_items(a.nums, items, packed=col.astype("<u8").tobytes())
    for i in range(512):
        b.nums[i] = int(col[i])
    assert a.hash_tree_root() == b.hash_tree_root() == _fresh_root(a)


# ---------------------------------------------------------------------------
# Columnar bulk container roots
# ---------------------------------------------------------------------------

def test_bulk_element_roots_match_per_object(dispatch_mode):
    rng = random.Random(5)
    items = [Inner(pubkey=rng.randbytes(48), wc=rng.randbytes(32),
                   eff=rng.randrange(2 ** 64), slashed=rng.randrange(2))
             for _ in range(400)]
    data = forest.bulk_element_root_bytes(items, Inner)
    if data is None:    # CS_TPU_HASH_FOREST=0 run: nothing to compare
        pytest.skip("columnar path disabled")
    for k, x in enumerate(items):
        assert data[k * 32:(k + 1) * 32] == _fresh_root(x), k


def test_bulk_byte_vector_roots(dispatch_mode):
    rng = random.Random(6)
    for typ, size in ((Bytes32, 32), (Bytes48, 48)):
        items = [typ(rng.randbytes(size)) for _ in range(300)]
        data = forest.bulk_element_root_bytes(items, typ)
        if data is None:
            pytest.skip("columnar path disabled")
        for k, x in enumerate(items):
            assert data[k * 32:(k + 1) * 32] == x.hash_tree_root(), (size, k)


def test_columnar_fallback_field_kinds():
    """A container with a field the column planner cannot vectorize
    (a nested list -> per-object 'root' kind) still bulk-roots exactly."""
    class Odd(Container):
        xs: List[uint64, 64]
        tag: uint64

    items = [Odd(xs=list(range(i % 5)), tag=i) for i in range(300)]
    data = forest.bulk_element_root_bytes(items, Odd)
    if data is None:
        pytest.skip("columnar path disabled")
    for k, x in enumerate(items):
        assert data[k * 32:(k + 1) * 32] == _fresh_root(x), k


# ---------------------------------------------------------------------------
# All 12 forks: post-update state roots vs full re-merkleization
# ---------------------------------------------------------------------------

ALL_FORKS = ["phase0", "sharding", "custody_game", "altair", "bellatrix",
             "capella", "deneb", "eip6110", "eip7002", "eip7594", "whisk",
             "eip6914"]

_SPEC_CACHE = {}


def _spec(fork):
    if fork not in _SPEC_CACHE:
        from consensus_specs_tpu.forks import build_spec
        _SPEC_CACHE[fork] = build_spec(fork, "minimal")
    return _SPEC_CACHE[fork]


@pytest.mark.parametrize("fork", ALL_FORKS)
def test_fork_state_roots_differential(fork, dispatch_mode):
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    spec = _spec(fork)
    rng = random.Random(hash(fork) & 0xFFFF)
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 32, spec.MAX_EFFECTIVE_BALANCE)
    with hash_forest():
        assert state.hash_tree_root() == _fresh_root(state)
    # mutate across sibling trees: balances column, registry fields,
    # roots vectors, slot — then re-root incrementally vs the oracle
    for i in range(0, 32, 2):
        state.balances[i] = int(state.balances[i]) - rng.randrange(10 ** 6)
    for i in range(0, 32, 5):
        state.validators[i].effective_balance = \
            int(spec.MAX_EFFECTIVE_BALANCE) - 10 ** 9
        state.validators[i].slashed = True
    state.block_roots[3] = rng.randbytes(32)
    state.state_roots[7] = rng.randbytes(32)
    state.slot = 17
    with hash_forest():
        got = state.hash_tree_root()
    assert got == _fresh_root(state), (fork, dispatch_mode)
    # and again without the forest scope (plain incremental path)
    state.balances[1] = 7
    assert state.hash_tree_root() == _fresh_root(state), (fork, dispatch_mode)


# ---------------------------------------------------------------------------
# Dispatch accounting: wide commits must never hashlib per pair
# ---------------------------------------------------------------------------

def test_wide_update_batches_with_zero_scalar_pairs():
    prev = merkle._batched_hasher
    prev_np = merkle._batched_hasher_np
    prev_thresholds = merkle.batch_thresholds()
    merkle.set_batched_hasher(_py_batched)
    merkle.set_batched_hasher_np(None)
    merkle.set_batch_thresholds(layer=1, pairs=1)
    try:
        v = Holder(nums=list(range(4096)))
        v.hash_tree_root()
        for i in range(4096):
            v.nums[i] = i * 2 + 1
        merkle.reset_stats()
        v.hash_tree_root()
        stats = merkle.stats()
        assert stats["pair_scalar"] == 0, stats
        assert stats["pair_batch_pairs"] > 0, stats
    finally:
        merkle.set_batched_hasher(prev)
        merkle.set_batched_hasher_np(prev_np)
        merkle.set_batch_thresholds(*prev_thresholds)


# ---------------------------------------------------------------------------
# Env-tunable thresholds (CS_TPU_MERKLE_BATCH_MIN)
# ---------------------------------------------------------------------------

def test_batch_min_env_overrides_both_thresholds():
    code = ("from consensus_specs_tpu.utils.ssz import merkle; "
            "print(merkle.batch_thresholds())")
    env = dict(os.environ, CS_TPU_MERKLE_BATCH_MIN="7", JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, check=True)
    assert out.stdout.strip() == "(7, 7)"
    env.pop("CS_TPU_MERKLE_BATCH_MIN")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, check=True)
    assert out.stdout.strip() == "(256, 32)"


# ---------------------------------------------------------------------------
# _SequenceBase.__hash__: eq-consistent content hash, O(1) amortized
# ---------------------------------------------------------------------------

def test_sequence_hash_matches_eq_and_memoizes():
    a = List[uint64, 1024](1, 2, 3)
    b = List[uint64, 1024](1, 2, 3)
    c = List[uint64, 1024](1, 2, 4)
    assert a == b and hash(a) == hash(b)        # equal values collide
    d = {a: "x"}
    assert d[b] == "x" and c not in d           # dict/set usage works
    assert len({a, b, c}) == 2
    # __eq__ ignores the sequence class's limit/length; the hash must too
    wide = List[uint64, 4096](1, 2, 3)
    vec = Vector[uint64, 3]([1, 2, 3])
    assert a == wide == vec
    assert hash(a) == hash(wide) == hash(vec)
    # memoized against the mutation generation: repeated hashing reuses,
    # mutation recomputes
    h0 = hash(a)
    assert a._hash_memo[1] == h0
    gen = getattr(a, "_gen", 0)
    hash(a)
    assert getattr(a, "_gen", 0) == gen         # no recompute churn
    a[0] = 9
    assert hash(a) != h0 or a._items != [1, 2, 3]
    assert hash(a) == hash(List[uint64, 1024](9, 2, 3))
