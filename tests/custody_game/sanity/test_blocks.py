"""Full-block sanity tests for the custody game.

Reference model: ``test/custody_game/sanity/test_blocks.py`` — each
custody operation carried end-to-end through ``state_transition``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_presets,
    disable_process_reveal_deadlines,
)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.custody import (
    get_custody_secret, get_custody_slashable_shard_transition,
    get_sample_shard_transition, get_valid_chunk_challenge,
    get_valid_custody_chunk_response, get_valid_custody_key_reveal,
    get_valid_custody_slashing, get_valid_early_derived_secret_reveal,
    transition_to,
)


def _attested_transition(spec, state, slashable_secret_index=None):
    transition_to(spec, state, state.slot + 1)
    if slashable_secret_index is not None:
        secret = get_custody_secret(spec, state, slashable_secret_index)
        shard_transition, data = get_custody_slashable_shard_transition(
            spec, state.slot, [2**15 // 3], secret, slashable=True)
    else:
        shard_transition = get_sample_shard_transition(
            spec, state.slot, [2**15 // 3])
        data = None
    attestation = get_valid_attestation(
        spec, state, signed=True, shard_transition=shard_transition)
    transition_to(spec, state,
                  state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    spec.process_attestation(state, attestation)
    return attestation, shard_transition, data


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_block_with_chunk_challenge_and_response(spec, state):
    attestation, shard_transition, _ = _attested_transition(spec, state)
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.chunk_challenges.append(challenge)
    signed_block = state_transition_and_sign_block(spec, state, block)

    challenge_index = state.custody_chunk_challenge_index - 1
    response = get_valid_custody_chunk_response(
        spec, state, challenge, challenge_index, 2**15 // 3)
    block2 = build_empty_block_for_next_slot(spec, state)
    block2.body.chunk_challenge_responses.append(response)
    signed_block2 = state_transition_and_sign_block(spec, state, block2)
    yield "blocks", [signed_block, signed_block2]
    yield "post", state
    assert state.custody_chunk_challenge_records[0] == \
        spec.CustodyChunkChallengeRecord()


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_block_with_custody_key_reveal(spec, state):
    transition_to(spec, state, state.slot
                  + spec.SLOTS_PER_EPOCH * spec.EPOCHS_PER_CUSTODY_PERIOD)
    custody_key_reveal = get_valid_custody_key_reveal(spec, state)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.custody_key_reveals.append(custody_key_reveal)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.validators[0].next_custody_secret_to_reveal == 1


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_block_with_early_derived_secret_reveal(spec, state):
    reveal = get_valid_early_derived_secret_reveal(spec, state)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.early_derived_secret_reveals.append(reveal)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.validators[reveal.revealed_index].slashed


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_block_with_custody_slashing(spec, state):
    transition_to(spec, state, state.slot + 1)
    committee = spec.get_beacon_committee(state, state.slot, 0)
    malefactor_secret = get_custody_secret(spec, state, committee[0])
    shard_transition, data = get_custody_slashable_shard_transition(
        spec, state.slot, [2**15 // 3], malefactor_secret, slashable=True)
    attestation = get_valid_attestation(
        spec, state, signed=True, shard_transition=shard_transition)
    transition_to(spec, state,
                  state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    spec.process_attestation(state, attestation)

    slashing = get_valid_custody_slashing(
        spec, state, attestation, shard_transition, malefactor_secret, data)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.custody_slashings.append(slashing)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.validators[slashing.message.malefactor_index].slashed
