"""Early derived secret reveal processing.

Reference model: ``test/custody_game/block_processing/
test_process_early_derived_secret_reveal.py`` against
``specs/_features/custody_game/beacon-chain.md`` ("Early derived secret
reveals").
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, always_bls, never_bls,
    expect_assertion_error,
)
from consensus_specs_tpu.test_infra.custody import (
    get_valid_early_derived_secret_reveal, transition_to,
)


def run_early_derived_secret_reveal_processing(spec, state, reveal,
                                               valid=True):
    yield "pre", state
    yield "randao_key_reveal", reveal
    if not valid:
        expect_assertion_error(
            lambda: spec.process_early_derived_secret_reveal(state, reveal))
        yield "post", None
        return
    spec.process_early_derived_secret_reveal(state, reveal)
    slashed = state.validators[reveal.revealed_index].slashed
    if reveal.epoch >= spec.get_current_epoch(state) \
            + spec.CUSTODY_PERIOD_TO_RANDAO_PADDING:
        assert slashed
    else:
        assert reveal.revealed_index in state.exposed_derived_secrets[
            reveal.epoch % spec.EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS]
    yield "post", state


@with_phases(["custody_game"])
@spec_state_test
@always_bls
def test_success(spec, state):
    reveal = get_valid_early_derived_secret_reveal(spec, state)
    yield from run_early_derived_secret_reveal_processing(spec, state, reveal)


@with_phases(["custody_game"])
@spec_state_test
@never_bls
def test_reveal_from_current_epoch(spec, state):
    reveal = get_valid_early_derived_secret_reveal(
        spec, state, spec.get_current_epoch(state))
    yield from run_early_derived_secret_reveal_processing(
        spec, state, reveal, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@never_bls
def test_reveal_from_past_epoch(spec, state):
    transition_to(spec, state, state.slot + spec.SLOTS_PER_EPOCH)
    reveal = get_valid_early_derived_secret_reveal(
        spec, state, spec.get_current_epoch(state) - 1)
    yield from run_early_derived_secret_reveal_processing(
        spec, state, reveal, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@always_bls
def test_reveal_with_custody_padding(spec, state):
    reveal = get_valid_early_derived_secret_reveal(
        spec, state,
        spec.get_current_epoch(state) + spec.CUSTODY_PERIOD_TO_RANDAO_PADDING)
    yield from run_early_derived_secret_reveal_processing(
        spec, state, reveal, valid=True)


@with_phases(["custody_game"])
@spec_state_test
@always_bls
def test_reveal_with_custody_padding_minus_one(spec, state):
    """One epoch inside the padding: penalty path, not slashing."""
    reveal = get_valid_early_derived_secret_reveal(
        spec, state,
        spec.get_current_epoch(state)
        + spec.CUSTODY_PERIOD_TO_RANDAO_PADDING - 1)
    pre_balance = state.balances[reveal.revealed_index]
    yield from run_early_derived_secret_reveal_processing(
        spec, state, reveal, valid=True)
    assert not state.validators[reveal.revealed_index].slashed
    assert state.balances[reveal.revealed_index] < pre_balance


@with_phases(["custody_game"])
@spec_state_test
@never_bls
def test_double_reveal(spec, state):
    epoch = spec.get_current_epoch(state) + spec.RANDAO_PENALTY_EPOCHS
    reveal = get_valid_early_derived_secret_reveal(spec, state, epoch)
    spec.process_early_derived_secret_reveal(state, reveal)
    yield from run_early_derived_secret_reveal_processing(
        spec, state, reveal, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@never_bls
def test_revealer_is_slashed(spec, state):
    reveal = get_valid_early_derived_secret_reveal(
        spec, state, spec.get_current_epoch(state)
        + spec.RANDAO_PENALTY_EPOCHS)
    state.validators[reveal.revealed_index].slashed = True
    yield from run_early_derived_secret_reveal_processing(
        spec, state, reveal, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@never_bls
def test_far_future_epoch(spec, state):
    reveal = get_valid_early_derived_secret_reveal(
        spec, state,
        spec.get_current_epoch(state)
        + spec.EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS)
    yield from run_early_derived_secret_reveal_processing(
        spec, state, reveal, valid=False)
