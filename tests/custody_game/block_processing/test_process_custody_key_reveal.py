"""Custody key reveal processing.

Reference model: ``test/custody_game/block_processing/
test_process_custody_key_reveal.py`` against
``specs/_features/custody_game/beacon-chain.md`` ("Custody key reveals").
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, always_bls, expect_assertion_error,
    disable_process_reveal_deadlines,
)
from consensus_specs_tpu.test_infra.custody import (
    get_valid_custody_key_reveal, transition_to,
)


def run_custody_key_reveal_processing(spec, state, custody_key_reveal,
                                      valid=True):
    yield "pre", state
    yield "custody_key_reveal", custody_key_reveal
    if not valid:
        expect_assertion_error(
            lambda: spec.process_custody_key_reveal(state, custody_key_reveal))
        yield "post", None
        return
    revealer_index = custody_key_reveal.revealer_index
    pre_next = state.validators[revealer_index].next_custody_secret_to_reveal
    spec.process_custody_key_reveal(state, custody_key_reveal)
    assert state.validators[revealer_index].next_custody_secret_to_reveal \
        == pre_next + 1
    yield "post", state


def _advance_to_past_period(spec, state):
    transition_to(spec, state, state.slot
                  + spec.SLOTS_PER_EPOCH * spec.EPOCHS_PER_CUSTODY_PERIOD)


@with_phases(["custody_game"])
@spec_state_test
@always_bls
def test_success(spec, state):
    _advance_to_past_period(spec, state)
    custody_key_reveal = get_valid_custody_key_reveal(spec, state)
    yield from run_custody_key_reveal_processing(
        spec, state, custody_key_reveal)


@with_phases(["custody_game"])
@spec_state_test
@always_bls
def test_reveal_too_early(spec, state):
    custody_key_reveal = get_valid_custody_key_reveal(spec, state)
    yield from run_custody_key_reveal_processing(
        spec, state, custody_key_reveal, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@always_bls
def test_wrong_period(spec, state):
    _advance_to_past_period(spec, state)
    custody_key_reveal = get_valid_custody_key_reveal(spec, state, period=5)
    yield from run_custody_key_reveal_processing(
        spec, state, custody_key_reveal, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@always_bls
def test_double_reveal(spec, state):
    # advance two periods, then the second identical reveal must fail
    transition_to(spec, state, state.slot + spec.SLOTS_PER_EPOCH
                  * spec.EPOCHS_PER_CUSTODY_PERIOD * 2)
    custody_key_reveal = get_valid_custody_key_reveal(spec, state)
    spec.process_custody_key_reveal(state, custody_key_reveal)
    yield from run_custody_key_reveal_processing(
        spec, state, custody_key_reveal, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@always_bls
@disable_process_reveal_deadlines
def test_max_decrement(spec, state):
    # Far in the future, every past period can be revealed in sequence
    transition_to(spec, state, state.slot + spec.SLOTS_PER_EPOCH
                  * spec.EPOCHS_PER_CUSTODY_PERIOD * 3)
    for _ in range(2):
        custody_key_reveal = get_valid_custody_key_reveal(spec, state)
        spec.process_custody_key_reveal(state, custody_key_reveal)
    custody_key_reveal = get_valid_custody_key_reveal(spec, state)
    yield from run_custody_key_reveal_processing(
        spec, state, custody_key_reveal)
