"""Custody chunk challenge + response processing.

Reference model: ``test/custody_game/block_processing/
test_process_chunk_challenge.py`` against
``specs/_features/custody_game/beacon-chain.md`` ("Chunk challenges",
"Custody chunk response").
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_presets,
    disable_process_reveal_deadlines, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.custody import (
    get_sample_shard_transition, get_valid_chunk_challenge,
    get_valid_custody_chunk_response, get_custody_test_vector, transition_to,
)

_BLOCK_LEN = 2**15 // 3


def run_chunk_challenge_processing(spec, state, challenge, valid=True):
    yield "pre", state
    yield "custody_chunk_challenge", challenge
    if not valid:
        expect_assertion_error(
            lambda: spec.process_chunk_challenge(state, challenge))
        yield "post", None
        return
    spec.process_chunk_challenge(state, challenge)
    record = state.custody_chunk_challenge_records[
        state.custody_chunk_challenge_index - 1]
    assert record.responder_index == challenge.responder_index
    assert record.chunk_index == challenge.chunk_index
    yield "post", state


def run_custody_chunk_response_processing(spec, state, response, valid=True):
    yield "pre", state
    yield "custody_response", response
    if not valid:
        expect_assertion_error(
            lambda: spec.process_chunk_challenge_response(state, response))
        yield "post", None
        return
    spec.process_chunk_challenge_response(state, response)
    assert state.custody_chunk_challenge_records[response.challenge_index] \
        == spec.CustodyChunkChallengeRecord()
    yield "post", state


def _attested_shard_transition(spec, state, block_lengths=None):
    """Advance a slot, attest to a sample shard transition, include it."""
    transition_to(spec, state, state.slot + 1)
    shard_transition = get_sample_shard_transition(
        spec, state.slot, block_lengths or [_BLOCK_LEN])
    attestation = get_valid_attestation(
        spec, state, signed=True, shard_transition=shard_transition)
    transition_to(spec, state,
                  state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    spec.process_attestation(state, attestation)
    return attestation, shard_transition


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_challenge_appended(spec, state):
    attestation, shard_transition = _attested_shard_transition(spec, state)
    transition_to(spec, state, state.slot
                  + spec.SLOTS_PER_EPOCH * spec.EPOCHS_PER_CUSTODY_PERIOD)
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    yield from run_chunk_challenge_processing(spec, state, challenge)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_challenge_empty_element_replaced(spec, state):
    attestation, shard_transition = _attested_shard_transition(spec, state)
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    state.custody_chunk_challenge_records.append(
        spec.CustodyChunkChallengeRecord())
    yield from run_chunk_challenge_processing(spec, state, challenge)
    assert state.custody_chunk_challenge_records[0] != \
        spec.CustodyChunkChallengeRecord()


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_duplicate_challenge(spec, state):
    attestation, shard_transition = _attested_shard_transition(spec, state)
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    spec.process_chunk_challenge(state, challenge)
    yield from run_chunk_challenge_processing(
        spec, state, challenge, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_second_challenge_different_chunk(spec, state):
    attestation, shard_transition = _attested_shard_transition(spec, state)
    challenge0 = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition, chunk_index=0)
    spec.process_chunk_challenge(state, challenge0)
    challenge1 = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition, chunk_index=1)
    yield from run_chunk_challenge_processing(spec, state, challenge1)
    assert state.custody_chunk_challenge_index == 2


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_wrong_shard_transition(spec, state):
    attestation, shard_transition = _attested_shard_transition(spec, state)
    # Tamper with the transition so its root no longer matches the
    # attested shard_transition_root
    shard_transition.shard_block_lengths[0] = _BLOCK_LEN + 1
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    yield from run_chunk_challenge_processing(
        spec, state, challenge, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_challenge_expired(spec, state):
    attestation, shard_transition = _attested_shard_transition(spec, state)
    transition_to(spec, state, state.slot + spec.SLOTS_PER_EPOCH
                  * (spec.MAX_CHUNK_CHALLENGE_DELAY + 1))
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    yield from run_chunk_challenge_processing(
        spec, state, challenge, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_chunk_index_out_of_bounds(spec, state):
    attestation, shard_transition = _attested_shard_transition(spec, state)
    chunk_count = (_BLOCK_LEN + spec.BYTES_PER_CUSTODY_CHUNK - 1) \
        // spec.BYTES_PER_CUSTODY_CHUNK
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    challenge.chunk_index = chunk_count
    yield from run_chunk_challenge_processing(
        spec, state, challenge, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_custody_response(spec, state):
    attestation, shard_transition = _attested_shard_transition(spec, state)
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    spec.process_chunk_challenge(state, challenge)
    challenge_index = state.custody_chunk_challenge_index - 1
    response = get_valid_custody_chunk_response(
        spec, state, challenge, challenge_index, _BLOCK_LEN)
    yield from run_custody_chunk_response_processing(spec, state, response)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_custody_response_chunk_index_mismatch(spec, state):
    attestation, shard_transition = _attested_shard_transition(spec, state)
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition, chunk_index=1)
    spec.process_chunk_challenge(state, challenge)
    challenge_index = state.custody_chunk_challenge_index - 1
    response = get_valid_custody_chunk_response(
        spec, state, challenge, challenge_index, _BLOCK_LEN)
    response.chunk_index = 0
    yield from run_custody_chunk_response_processing(
        spec, state, response, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_custody_response_invalid_chunk(spec, state):
    attestation, shard_transition = _attested_shard_transition(spec, state)
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    spec.process_chunk_challenge(state, challenge)
    challenge_index = state.custody_chunk_challenge_index - 1
    response = get_valid_custody_chunk_response(
        spec, state, challenge, challenge_index, _BLOCK_LEN,
        invalid_chunk_data=True)
    yield from run_custody_chunk_response_processing(
        spec, state, response, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_custody_response_missing_challenge(spec, state):
    attestation, shard_transition = _attested_shard_transition(spec, state)
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    response = get_valid_custody_chunk_response(
        spec, state, challenge, challenge_index=7,
        block_length_or_custody_data=_BLOCK_LEN)
    yield from run_custody_chunk_response_processing(
        spec, state, response, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_custody_response_multiple_blocks(spec, state):
    attestation, shard_transition = _attested_shard_transition(
        spec, state, block_lengths=[_BLOCK_LEN, 2**14 // 3])
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition, data_index=1)
    spec.process_chunk_challenge(state, challenge)
    challenge_index = state.custody_chunk_challenge_index - 1
    response = get_valid_custody_chunk_response(
        spec, state, challenge, challenge_index,
        get_custody_test_vector(2**14 // 3))
    yield from run_custody_chunk_response_processing(spec, state, response)
