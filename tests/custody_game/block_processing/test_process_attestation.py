"""Attestation processing under the custody-game fork.

Reference model: ``test/custody_game/block_processing/
test_process_attestation.py`` — standard phase0 attestation rules still
hold with the sharding ``AttestationData`` extension.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, always_bls,
)
from consensus_specs_tpu.test_infra.attestations import (
    get_valid_attestation, run_attestation_processing,
)
from consensus_specs_tpu.test_infra.custody import (
    get_sample_shard_transition, transition_to,
)


@with_phases(["custody_game"])
@spec_state_test
@always_bls
def test_attestation(spec, state):
    transition_to(spec, state, state.slot + 1)
    shard_transition = get_sample_shard_transition(
        spec, state.slot, [2**15 // 3])
    attestation = get_valid_attestation(
        spec, state, signed=True, shard_transition=shard_transition)
    transition_to(spec, state,
                  state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)


@with_phases(["custody_game"])
@spec_state_test
@always_bls
def test_attestation_wrong_transition_root_sig(spec, state):
    """Tampering with the shard_transition_root after signing breaks the
    attestation signature (the root is part of the signed data)."""
    transition_to(spec, state, state.slot + 1)
    shard_transition = get_sample_shard_transition(
        spec, state.slot, [2**15 // 3])
    attestation = get_valid_attestation(
        spec, state, signed=True, shard_transition=shard_transition)
    attestation.data.shard_transition_root = b"\x11" * 32
    transition_to(spec, state,
                  state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(
        spec, state, attestation, valid=False)
