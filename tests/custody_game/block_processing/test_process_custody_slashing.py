"""Custody slashing processing.

Reference model: ``test/custody_game/block_processing/
test_process_custody_slashing.py`` against
``specs/_features/custody_game/beacon-chain.md`` ("Custody Slashings").
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_presets,
    disable_process_reveal_deadlines, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.custody import (
    get_custody_secret, get_custody_slashable_shard_transition,
    get_sample_shard_transition, get_valid_custody_slashing,
    get_custody_test_vector, transition_to,
)
from consensus_specs_tpu.utils.ssz import ByteList

_BLOCK_LEN = 2**15 // 3


def run_custody_slashing_processing(spec, state, slashing, valid=True,
                                    correct=True):
    yield "pre", state
    yield "custody_slashing", slashing
    if not valid:
        expect_assertion_error(
            lambda: spec.process_custody_slashing(state, slashing))
        yield "post", None
        return
    spec.process_custody_slashing(state, slashing)
    if correct:
        # The claim was correct: the malefactor is slashed
        assert state.validators[slashing.message.malefactor_index].slashed
    else:
        # The claim was false: the whistleblower is slashed
        assert state.validators[slashing.message.whistleblower_index].slashed
    yield "post", state


def _slashable_setup(spec, state, slashable=True):
    """Attest to shard data crafted (non-)slashable for the malefactor
    (the first member of the attesting committee)."""
    transition_to(spec, state, state.slot + 1)
    committee = spec.get_beacon_committee(state, state.slot, 0)
    malefactor_secret = get_custody_secret(spec, state, committee[0])
    shard_transition, data = get_custody_slashable_shard_transition(
        spec, state.slot, [_BLOCK_LEN], malefactor_secret,
        slashable=slashable)
    attestation = get_valid_attestation(
        spec, state, signed=True, shard_transition=shard_transition)
    transition_to(spec, state,
                  state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    spec.process_attestation(state, attestation)
    return attestation, shard_transition, malefactor_secret, data


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_custody_slashing(spec, state):
    attestation, shard_transition, secret, data = _slashable_setup(
        spec, state, slashable=True)
    slashing = get_valid_custody_slashing(
        spec, state, attestation, shard_transition, secret, data)
    yield from run_custody_slashing_processing(
        spec, state, slashing, correct=True)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_incorrect_custody_slashing(spec, state):
    attestation, shard_transition, secret, data = _slashable_setup(
        spec, state, slashable=False)
    slashing = get_valid_custody_slashing(
        spec, state, attestation, shard_transition, secret, data)
    yield from run_custody_slashing_processing(
        spec, state, slashing, correct=False)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_multiple_epochs_custody(spec, state):
    transition_to(spec, state, state.slot + spec.SLOTS_PER_EPOCH * 3)
    attestation, shard_transition, secret, data = _slashable_setup(
        spec, state, slashable=True)
    slashing = get_valid_custody_slashing(
        spec, state, attestation, shard_transition, secret, data)
    yield from run_custody_slashing_processing(
        spec, state, slashing, correct=True)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_invalid_custody_slashing_data_root(spec, state):
    attestation, shard_transition, secret, data = _slashable_setup(
        spec, state, slashable=True)
    # Hand the slashing different data than attested
    wrong = get_custody_test_vector(_BLOCK_LEN, offset=123)
    slashing = get_valid_custody_slashing(
        spec, state, attestation, shard_transition, secret,
        ByteList[spec.MAX_SHARD_BLOCK_SIZE](wrong))
    yield from run_custody_slashing_processing(
        spec, state, slashing, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_invalid_custody_slashing_length(spec, state):
    attestation, shard_transition, secret, data = _slashable_setup(
        spec, state, slashable=True)
    slashing = get_valid_custody_slashing(
        spec, state, attestation, shard_transition, secret,
        ByteList[spec.MAX_SHARD_BLOCK_SIZE](bytes(data)[:-1]))
    yield from run_custody_slashing_processing(
        spec, state, slashing, valid=False)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_custody_slashing_wrong_transition(spec, state):
    attestation, shard_transition, secret, data = _slashable_setup(
        spec, state, slashable=True)
    other_transition = get_sample_shard_transition(
        spec, shard_transition.start_slot, [_BLOCK_LEN + 5])
    slashing = get_valid_custody_slashing(
        spec, state, attestation, other_transition, secret, data)
    yield from run_custody_slashing_processing(
        spec, state, slashing, valid=False)
