"""Challenge-deadline epoch processing.

Reference model: ``test/custody_game/epoch_processing/
test_process_challenge_deadlines.py``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_presets,
    disable_process_reveal_deadlines,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.custody import (
    get_sample_shard_transition, get_valid_chunk_challenge, transition_to,
)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_validator_slashed_after_chunk_challenge(spec, state):
    transition_to(spec, state, state.slot + 1)
    shard_transition = get_sample_shard_transition(
        spec, state.slot, [2**15 // 3])
    attestation = get_valid_attestation(
        spec, state, signed=True, shard_transition=shard_transition)
    transition_to(spec, state,
                  state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    spec.process_attestation(state, attestation)

    validator_index = spec.get_beacon_committee(
        state, attestation.data.slot, attestation.data.index)[0]
    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    spec.process_chunk_challenge(state, challenge)
    assert state.validators[validator_index].slashed == 0

    # Response never arrives. Walk so the deadline (current_epoch >
    # inclusion + EPOCHS_PER_CUSTODY_PERIOD) is first crossed INSIDE the
    # stage under test, not at an earlier boundary of the walk itself.
    # (The reference test walks past the deadline first, which would
    # clear the record before the stage runs — latent bug in a suite its
    # repo never executes; see sharding.py lineage note.)
    inclusion = spec.get_current_epoch(state)
    transition_to(
        spec, state,
        (inclusion + spec.EPOCHS_PER_CUSTODY_PERIOD + 1)
        * spec.SLOTS_PER_EPOCH + 1)
    assert state.custody_chunk_challenge_records[0] != \
        spec.CustodyChunkChallengeRecord()
    yield from run_epoch_processing_with(
        spec, state, "process_challenge_deadlines")
    assert state.validators[validator_index].slashed == 1
    assert state.custody_chunk_challenge_records[0] == \
        spec.CustodyChunkChallengeRecord()
