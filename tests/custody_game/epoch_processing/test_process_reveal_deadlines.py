"""Reveal-deadline epoch processing.

Reference model: ``test/custody_game/epoch_processing/
test_process_reveal_deadlines.py`` against
``specs/_features/custody_game/beacon-chain.md`` ("Handling of reveal
deadlines").
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_presets,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.test_infra.custody import (
    get_valid_custody_key_reveal, transition_to,
)


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
def test_validator_slashed_after_reveal_deadline(spec, state):
    assert state.validators[0].slashed == 0
    transition_to(spec, state,
                  spec.get_randao_epoch_for_custody_period(0, 0)
                  * spec.SLOTS_PER_EPOCH)
    # At least one validator must keep revealing, or the whole registry
    # slashes and proposer selection fails
    custody_key_reveal = get_valid_custody_key_reveal(
        spec, state, validator_index=1)
    spec.process_custody_key_reveal(state, custody_key_reveal)

    transition_to(spec, state, state.slot
                  + spec.EPOCHS_PER_CUSTODY_PERIOD * spec.SLOTS_PER_EPOCH)
    # The walk itself already slashed at the deadline; reset to observe
    # the stage under test do it
    state.validators[0].slashed = 0
    yield from run_epoch_processing_with(
        spec, state, "process_reveal_deadlines")
    assert state.validators[0].slashed == 1


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
def test_validator_not_slashed_after_reveal(spec, state):
    transition_to(spec, state,
                  spec.EPOCHS_PER_CUSTODY_PERIOD * spec.SLOTS_PER_EPOCH)
    custody_key_reveal = get_valid_custody_key_reveal(spec, state)
    spec.process_custody_key_reveal(state, custody_key_reveal)
    assert state.validators[0].slashed == 0
    transition_to(spec, state, state.slot
                  + spec.EPOCHS_PER_CUSTODY_PERIOD * spec.SLOTS_PER_EPOCH)
    yield from run_epoch_processing_with(
        spec, state, "process_reveal_deadlines")
    assert state.validators[0].slashed == 0
