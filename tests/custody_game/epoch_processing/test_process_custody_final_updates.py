"""Custody final-updates epoch processing.

Reference model: ``test/custody_game/epoch_processing/
test_process_custody_final_updates.py`` against
``specs/_features/custody_game/beacon-chain.md`` ("Final updates").
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_presets,
    disable_process_reveal_deadlines,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.custody import (
    get_sample_shard_transition, get_valid_chunk_challenge,
    get_valid_custody_chunk_response, get_valid_custody_key_reveal,
    transition_to,
)
from consensus_specs_tpu.test_infra.voluntary_exits import (
    prepare_signed_exits,
)


def run_process_custody_final_updates(spec, state):
    yield from run_epoch_processing_with(
        spec, state, "process_custody_final_updates")


def _age_state_past_committee_period(spec, state):
    """Jump (not walk) the state far enough that validators may exit —
    boundary processing between here and genesis is irrelevant to the
    stage under test."""
    state.slot = spec.SLOTS_PER_EPOCH * (spec.config.SHARD_COMMITTEE_PERIOD + 1)


def _exit_validator(spec, state, index):
    exit_op = prepare_signed_exits(spec, state, [index])[0]
    spec.process_voluntary_exit(state, exit_op)


def _reveal_all_periods_through_exit(spec, state, index):
    state.slot = spec.SLOTS_PER_EPOCH * int(state.validators[index].exit_epoch)
    while (state.validators[index].next_custody_secret_to_reveal
           <= spec.get_custody_period_for_validator(
               index, state.validators[index].exit_epoch - 1)):
        custody_key_reveal = get_valid_custody_key_reveal(
            spec, state, validator_index=index)
        spec.process_custody_key_reveal(state, custody_key_reveal)


@with_phases(["custody_game"])
@spec_state_test
def test_validator_withdrawal_delay(spec, state):
    _age_state_past_committee_period(spec, state)
    _exit_validator(spec, state, 0)
    yield from run_process_custody_final_updates(spec, state)
    # exited but secrets unrevealed: withdrawability frozen
    assert state.validators[0].withdrawable_epoch == spec.FAR_FUTURE_EPOCH


@with_phases(["custody_game"])
@spec_state_test
@disable_process_reveal_deadlines
def test_validator_withdrawal_reenable_after_custody_reveal(spec, state):
    _age_state_past_committee_period(spec, state)
    _exit_validator(spec, state, 0)
    assert state.validators[0].withdrawable_epoch < spec.FAR_FUTURE_EPOCH
    _reveal_all_periods_through_exit(spec, state, 0)
    assert state.validators[0].all_custody_secrets_revealed_epoch \
        < spec.FAR_FUTURE_EPOCH
    yield from run_process_custody_final_updates(spec, state)
    assert state.validators[0].withdrawable_epoch < spec.FAR_FUTURE_EPOCH


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_validator_withdrawal_suspend_after_chunk_challenge(spec, state):
    _age_state_past_committee_period(spec, state)
    transition_to(spec, state, state.slot + 1)
    shard_transition = get_sample_shard_transition(
        spec, state.slot, [2**15 // 3])
    attestation = get_valid_attestation(
        spec, state, signed=True, shard_transition=shard_transition)
    transition_to(spec, state,
                  state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    spec.process_attestation(state, attestation)
    validator_index = spec.get_beacon_committee(
        state, attestation.data.slot, attestation.data.index)[0]
    _exit_validator(spec, state, validator_index)

    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    spec.process_chunk_challenge(state, challenge)
    yield from run_process_custody_final_updates(spec, state)
    assert state.validators[validator_index].withdrawable_epoch \
        == spec.FAR_FUTURE_EPOCH


@with_phases(["custody_game"])
@spec_state_test
@with_presets(["minimal"], reason="too slow")
@disable_process_reveal_deadlines
def test_validator_withdrawal_resume_after_chunk_challenge_response(
        spec, state):
    _age_state_past_committee_period(spec, state)
    transition_to(spec, state, state.slot + 1)
    shard_transition = get_sample_shard_transition(
        spec, state.slot, [2**15 // 3])
    attestation = get_valid_attestation(
        spec, state, signed=True, shard_transition=shard_transition)
    transition_to(spec, state,
                  state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    spec.process_attestation(state, attestation)
    validator_index = spec.get_beacon_committee(
        state, attestation.data.slot, attestation.data.index)[0]
    _exit_validator(spec, state, validator_index)
    _reveal_all_periods_through_exit(spec, state, validator_index)

    challenge = get_valid_chunk_challenge(
        spec, state, attestation, shard_transition)
    spec.process_chunk_challenge(state, challenge)
    challenge_index = state.custody_chunk_challenge_index - 1
    response = get_valid_custody_chunk_response(
        spec, state, challenge, challenge_index, 2**15 // 3)
    spec.process_chunk_challenge_response(state, response)
    yield from run_process_custody_final_updates(spec, state)
    # NOTE: a cleared record keeps responder_index 0 in the frozen set
    # (spec quirk preserved from the reference; see
    # process_custody_final_updates) — so only non-zero indices resume.
    if validator_index != 0:
        assert state.validators[validator_index].withdrawable_epoch \
            < spec.FAR_FUTURE_EPOCH
