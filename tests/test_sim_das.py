"""Availability-sampling sim legs (sim/das.py): determinism, the
counted-fallback contract at the das sites, sentinel-audit quarantine
with a replayable artifact, and the engine-off byte-identity leg."""
import pytest

from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.sim import das, harness, repro


@pytest.fixture(scope="module")
def spec():
    return build_spec("eip7594", "minimal")


def test_scripts_are_deterministic_pure_data():
    for seed in range(4):
        a = das.build(seed)
        b = das.build(seed)
        assert a.script == b.script
        assert a.name.startswith(das.DAS_PREFIX)
        import json
        json.dumps(a.script)    # replayable artifacts need JSON scripts


def test_catalog_covers_all_shapes():
    names = {das.build(seed).name for seed in range(8)}
    assert names == set(das.NAMES)


def test_baseline_replays_identical(spec):
    scenario = das.build(0)
    a, census_a = das.run_baseline(spec, scenario)
    b, census_b = das.run_baseline(spec, scenario)
    assert a.digest() == b.digest()
    assert census_a == census_b
    assert set(census_a) <= set(das.DAS_SITES)


def test_boundary_scenario_semantics(spec):
    """recovery_boundary: the exactly-half recover succeeds (hash
    event), the one-short recover refuses loudly (rejected count)."""
    scenario = das.build(1, name="recovery_boundary")
    result, _ = das.run_baseline(spec, scenario)
    recovers = [e for e in result.events if e.startswith("recover|")]
    assert len(recovers) == 2
    assert "refused" not in recovers[0]
    assert "refused" in recovers[1]
    assert result.rejected == 1


def test_withheld_sampling_flags_unavailable(spec):
    """A sampled withheld column marks the block unavailable; the
    tampered adversarial sample fails closed."""
    scenario = das.build(3, name="nonfinality_sampling")
    result, _ = das.run_baseline(spec, scenario)
    samples = [e for e in result.events if e.startswith("sample|")]
    assert samples, result.events
    # the final scripted sample is the tampered one: must be unavailable
    assert samples[-1].endswith("unavailable")


def test_injected_legs_satisfy_contract(spec):
    scenario = das.build(0)
    baseline, census = das.run_baseline(spec, scenario)
    assert census
    for site, calls in sorted(census.items()):
        das.run_injected(spec, scenario, baseline, site, calls)


def test_silent_fallback_detected(spec, monkeypatch):
    """A das fallback that books nothing must fail the injected leg
    with the silent-fallback category (the contract the legs exist to
    enforce)."""
    from consensus_specs_tpu.das import engine

    class _Mute:
        def add(self, *a):
            pass

    scenario = das.build(0)
    baseline, census = das.run_baseline(spec, scenario)
    site = sorted(census)[0]
    monkeypatch.setitem(engine._C_FALLBACKS, "injected", _Mute())
    with pytest.raises(harness.LegFailure) as err:
        das.run_injected(spec, scenario, baseline, site, 1)
    assert err.value.category == "silent-fallback"


def test_engine_off_leg_byte_identical(spec):
    scenario = das.build(0)
    baseline, _ = das.run_baseline(spec, scenario)
    das.run_engine_off(spec, scenario, baseline)


def test_corrupt_leg_quarantines_and_replays(spec, tmp_path):
    """End to end: the corrupt leg quarantines das.recover, dumps an
    artifact, and sim.repro re-arms it and reproduces (exit 1).  A
    hand-minimal one-recover script keeps the rate-1 audit replays
    affordable; the sweep runs the full catalog shapes."""
    from consensus_specs_tpu.sim.scenarios import Scenario
    scenario = Scenario("das/recovery_boundary", 0, [
        {"op": "publish", "blob_seeds": [123], "zero_blobs": 0},
        {"op": "withhold", "columns": list(range(0, das.N_COLUMNS, 2))},
        {"op": "recover"},
    ], 0, None)
    baseline, census = das.run_baseline(spec, scenario)
    assert census.get("das.recover", 0) >= 1
    result, artifact = das.run_corrupt(
        spec, scenario, baseline, "das.recover", out_dir=str(tmp_path))
    assert result.digest() == baseline.digest()
    # the replay's re-dumped quarantine evidence must land NEXT TO the
    # artifact, never in the process-default artifact dir (regression)
    import os
    sentinel = tmp_path / "default-dir"
    saved = os.environ.get("CS_TPU_SIM_ARTIFACTS")
    os.environ["CS_TPU_SIM_ARTIFACTS"] = str(sentinel)
    try:
        assert repro.replay(artifact) == 1
    finally:
        if saved is None:
            os.environ.pop("CS_TPU_SIM_ARTIFACTS", None)
        else:
            os.environ["CS_TPU_SIM_ARTIFACTS"] = saved
    assert not sentinel.exists() or not any(sentinel.iterdir())


def test_failure_artifact_records_das_spec(spec, tmp_path):
    """Leg-failure artifacts from the das phase must replay against
    the das spec: the sweep records eip7594/minimal (not its --fork),
    and replay_artifact refuses to rebuild a chain fork even from a
    stale artifact (regression: a phase0-recorded das artifact crashed
    replay with an AttributeError)."""
    from consensus_specs_tpu import faults
    scenario = das.build(2, name="custody_rotation")
    schedule = faults.FaultSchedule({"das.verify": [1]})
    # the shape run_das_phase dumps for a non-corrupt leg failure
    path = repro.dump_artifact(scenario, "inject[das.verify@1]",
                               "synthetic", schedule=schedule,
                               out_dir=str(tmp_path), fork="eip7594",
                               preset="minimal")
    assert repro.replay(path) == 0      # healthy leg: no reproduction
    # stale artifact with a chain fork recorded: still replays
    stale = repro.dump_artifact(scenario, "das-engine-off", "synthetic",
                                out_dir=str(tmp_path), fork="phase0",
                                preset="minimal")
    assert repro.replay(stale) == 0


def test_quarantine_replay_contract_violation_distinct_exit(
        spec, tmp_path, monkeypatch):
    """If the quarantine pipeline regresses between dump and replay
    (run_corrupt raises a LegFailure), the replay reports exit 2 — a
    distinct verdict, not a hollow 'reproduced' (regression)."""
    from consensus_specs_tpu import faults
    from consensus_specs_tpu.sim.scenarios import Scenario
    scenario = Scenario("das/recovery_boundary", 0, [
        {"op": "publish", "blob_seeds": [5], "zero_blobs": 0},
        {"op": "withhold", "columns": list(range(0, das.N_COLUMNS, 2))},
        {"op": "recover"},
    ], 0, None)
    schedule = faults.FaultSchedule(corrupt={"das.recover": [1]})
    path = repro.dump_artifact(scenario, "audit[das.recover]", "x",
                               schedule=schedule, out_dir=str(tmp_path),
                               fork="eip7594", preset="minimal")

    def broken_run_corrupt(*a, **kw):
        raise harness.LegFailure("audit[das.recover]", scenario,
                                 "SILENT CORRUPTION (simulated)",
                                 category="silent-fallback")

    monkeypatch.setattr(das, "run_corrupt", broken_run_corrupt)
    assert repro.replay(path) == 2


@pytest.mark.slow
def test_corrupt_verify_leg(spec, tmp_path):
    scenario = das.build(2, name="custody_rotation")
    baseline, census = das.run_baseline(spec, scenario)
    assert census.get("das.verify", 0) >= 1
    result, artifact = das.run_corrupt(
        spec, scenario, baseline, "das.verify", out_dir=str(tmp_path))
    assert result.digest() == baseline.digest()
    import json
    payload = json.load(open(artifact))
    assert payload["scenario"].startswith("das/")
    assert payload["schedule"]["corrupt"] == {"das.verify": 1}
