"""EIP-6914 validator-index reuse.

Reference model: ``specs/_features/eip6914/beacon-chain.md`` — the
reference carries no tests for this fork; these pin the predicate, the
deposit-path override, and the fork-choice handler.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases,
)
from consensus_specs_tpu.test_infra.deposits import (
    prepare_state_and_deposit,
)
from consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store_and_block,
)


def _retire_validator(spec, state, index):
    """Make index fully withdrawn long enough ago to be reusable."""
    v = state.validators[index]
    v.exit_epoch = 0
    v.withdrawable_epoch = 0
    v.effective_balance = 0
    state.balances[index] = 0
    state.slot = spec.SLOTS_PER_EPOCH * (spec.SAFE_EPOCHS_TO_REUSE_INDEX + 2)


@with_phases(["eip6914"])
@spec_state_test
def test_is_reusable_validator_windows(spec, state):
    v = state.validators[0]
    epoch = spec.get_current_epoch(state)
    # active validator: not reusable
    assert not spec.is_reusable_validator(v, state.balances[0], epoch)
    _retire_validator(spec, state, 0)
    epoch = spec.get_current_epoch(state)
    assert spec.is_reusable_validator(
        state.validators[0], state.balances[0], epoch)
    # nonzero balance blocks reuse
    state.balances[0] = 1
    assert not spec.is_reusable_validator(
        state.validators[0], state.balances[0], epoch)
    # too-recent withdrawability blocks reuse
    state.balances[0] = 0
    state.validators[0].withdrawable_epoch = epoch - 1
    assert not spec.is_reusable_validator(
        state.validators[0], state.balances[0], epoch)
    yield


@with_phases(["eip6914"])
@spec_state_test
def test_deposit_reuses_retired_index(spec, state):
    # plant stale records a leaked reuse would inherit
    state.current_epoch_participation[0] = 7
    state.inactivity_scores[0] = 99
    _retire_validator(spec, state, 0)
    pre_count = len(state.validators)
    assert spec.get_index_for_new_validator(state) == 0
    # a fresh-pubkey deposit takes over slot 0 instead of appending
    deposit = prepare_state_and_deposit(
        spec, state, validator_index=pre_count,
        amount=spec.MAX_EFFECTIVE_BALANCE, signed=True)
    yield "pre", state
    spec.process_deposit(state, deposit)
    yield "post", state
    assert len(state.validators) == pre_count
    assert state.validators[0].pubkey == deposit.data.pubkey
    assert state.balances[0] == spec.MAX_EFFECTIVE_BALANCE
    # the previous owner's per-validator records must not leak
    assert state.previous_epoch_participation[0] == 0
    assert state.current_epoch_participation[0] == 0
    assert state.inactivity_scores[0] == 0


@with_phases(["eip6914"])
@spec_state_test
def test_deposit_appends_when_no_reusable_index(spec, state):
    pre_count = len(state.validators)
    assert spec.get_index_for_new_validator(state) == pre_count
    deposit = prepare_state_and_deposit(
        spec, state, validator_index=pre_count,
        amount=spec.MAX_EFFECTIVE_BALANCE, signed=True)
    yield "pre", state
    spec.process_deposit(state, deposit)
    yield "post", state
    assert len(state.validators) == pre_count + 1
    # every per-validator list must have grown in lockstep, or the next
    # epoch transition would IndexError
    assert len(state.previous_epoch_participation) == pre_count + 1
    assert len(state.inactivity_scores) == pre_count + 1
    spec.process_slots(
        state, state.slot + spec.SLOTS_PER_EPOCH
        - state.slot % spec.SLOTS_PER_EPOCH)


@with_phases(["eip6914"])
@spec_state_test
def test_on_reused_index_clears_equivocation(spec, state):
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    store.equivocating_indices.add(0)
    spec.on_reused_index(store, 0)
    assert 0 not in store.equivocating_indices
    # discarding an absent index is a no-op
    spec.on_reused_index(store, 5)
    yield
