"""Multi-device sharding tests (8-device virtual CPU mesh from conftest).

Covers the distributed axis of the framework: the pubkey-aggregation
tree split over a device mesh with an ``all_gather`` combine — the TPU
analog of the reference's per-attestation serial FFI loop
(``specs/phase0/beacon-chain.md:1757-1774``) — plus the driver-facing
``__graft_entry__.dryrun_multichip`` path itself.
"""
import os
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.utils.env_flags import HEAVY  # noqa: E402


def _require_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices, have {len(jax.devices())}")


@pytest.mark.skipif(not HEAVY, reason="shard_map compile is ~90 s on a "
                    "1-core host; the collective is default-covered by "
                    "test_sharded_sum_collective_layout and the driver "
                    "dryrun (CS_TPU_HEAVY=1)")
def test_sharded_g1_aggregate_matches_host():
    """Partial G1 sums per shard + all_gather combine == host aggregation."""
    _require_devices(8)
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from consensus_specs_tpu.utils import bls
    from consensus_specs_tpu.ops.jax_bls import points as PT
    from consensus_specs_tpu.ops import bls_jax

    n_shards = 8
    keys_per_shard = 2
    n_keys = n_shards * keys_per_shard
    bls.use_py()
    pks = [bls_jax._decompress_g1(bls.SkToPk(sk)) for sk in range(1, n_keys + 1)]
    expected = bls.AggregatePKs([bls.SkToPk(sk) for sk in range(1, n_keys + 1)])

    packed = PT.g1_pack(pks)
    mesh = Mesh(np.array(jax.devices()[:n_shards]), ("agg",))

    def local(pk_pts):
        part = PT.g1_tree_sum(pk_pts)
        gathered = jax.tree_util.tree_map(
            lambda a: jax.lax.all_gather(a, "agg"), part)
        total = jax.tree_util.tree_map(lambda a: a[0], gathered)
        for i in range(1, n_shards):  # noqa: J203 (static unroll: mesh size)
            total = PT.g1_add(
                total, jax.tree_util.tree_map(lambda a: a[i], gathered))
        return PT.g1_normalize(total)

    step = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P("agg"), packed),),
        out_specs=P(), check_rep=False))
    out = step(packed)
    got = PT.g1_unpack(jax.tree_util.tree_map(lambda a: a[None], out))
    assert got.to_compressed() == expected


@pytest.mark.skipif(not HEAVY, reason="full pairing execution (CS_TPU_HEAVY=1)")
def test_sharded_verify_module_end_to_end():
    """consensus_specs_tpu.parallel: the library sharded-verify step
    accepts valid aggregates and rejects a wrong message."""
    _require_devices(8)
    import __graft_entry__ as ge
    from consensus_specs_tpu.parallel import build_mesh, \
        make_sharded_agg_verify

    mesh = build_mesh(jax.devices()[:8], 2, 4)
    pk_pts, u0, u1, sig_q, agg_degen, sig_degen = ge._example_inputs(
        batch=4, n_keys=8)
    step = make_sharded_agg_verify(mesh)
    out = np.asarray(step(pk_pts, u0, u1, sig_q, agg_degen, sig_degen))
    assert out.shape == (4,) and bool(out.all())
    # wrong message: duplicate u0 — H = map(u0) + map(u0) != map(u0)+map(u1)
    # (swapping u0/u1 would be a no-op: the two mapped points are summed)
    out_bad = np.asarray(step(pk_pts, u0, u0, sig_q, agg_degen, sig_degen))
    assert not bool(out_bad.any())


def test_sharded_sum_collective_layout():
    """Sanity: the mesh really has 8 addressable devices and psum runs."""
    _require_devices(8)
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.array(jax.devices()[:8]), ("d",))
    x = jnp.arange(8.0)
    f = jax.jit(shard_map(
        lambda v: jax.lax.psum(v, "d"), mesh=mesh,
        in_specs=P("d"), out_specs=P()))
    assert float(f(x)[0]) == 28.0


@pytest.mark.skipif(not HEAVY, reason="full pairing dryrun (CS_TPU_HEAVY=1)")
def test_dryrun_multichip_full():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


@pytest.mark.skipif(not HEAVY, reason="MSM shard_map compile on a 1-core "
                    "host (CS_TPU_HEAVY=1)")
def test_sharded_msm_matches_host():
    """Points-sharded MSM over the 8-device mesh equals the host
    Pippenger result (SURVEY 2.4: shard MSM over devices, reduce over
    the mesh collective)."""
    _require_devices(4)
    from consensus_specs_tpu.parallel.sharded_verify import sharded_g1_msm
    from consensus_specs_tpu.ops.bls12_381.curve import G1_GENERATOR, G1Point

    from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER

    pts = [G1_GENERATOR.mult(k) for k in (1, 3, 7, 11, 13, 17, 19, 23)]
    # non-canonical scalars ride along: a negative and a >= 2**256 value
    # must be reduced mod the group order before digit extraction
    # (regression: unreduced two's-complement bits gave a wrong MSM)
    scalars = [5, -9, 2**256 + 2, 31, R_ORDER + 1, 8, 27, 4]
    expect = G1Point.inf()
    for p, s in zip(pts, scalars):
        expect = expect + p.mult(s % R_ORDER)
    got = sharded_g1_msm(pts, scalars, jax.devices()[:4])
    assert got == expect

    # ragged size: padding with infinity points must not change the sum
    got2 = sharded_g1_msm(pts[:5], scalars[:5], jax.devices()[:4])
    expect2 = G1Point.inf()
    for p, s in zip(pts[:5], scalars[:5]):
        expect2 = expect2 + p.mult(s)
    assert got2 == expect2


@pytest.mark.skipif(not HEAVY, reason="G2 MSM shard_map compile on a "
                    "1-core host (CS_TPU_HEAVY=1)")
def test_sharded_g2_msm_matches_host():
    """Points-sharded G2 MSM (the RLC signature fold
    ``sum_i [r_i] sig_i``) over the virtual mesh equals the oracle
    Pippenger result."""
    _require_devices(4)
    from consensus_specs_tpu.parallel.sharded_verify import sharded_g2_msm_for
    from consensus_specs_tpu.ops import bls_jax
    from consensus_specs_tpu.ops.jax_bls import points as PT
    from consensus_specs_tpu.ops.bls12_381.curve import (
        g2_from_compressed, msm as oracle_msm)
    from consensus_specs_tpu.utils import bls

    bls.use_py()
    sigs = [g2_from_compressed(bls.Sign(i, bytes([i]) * 32))
            for i in range(1, 9)]
    rng = np.random.RandomState(42)
    rs = [int.from_bytes(rng.bytes(16), "little") | 1 for _ in sigs]
    prog = sharded_g2_msm_for(tuple(jax.devices()[:4]))
    out = prog(PT.g2_pack(sigs),
               jnp.asarray(bls_jax._bits_msb(rs, bls_jax.RLC_SCALAR_BITS)))
    got = PT.g2_unpack(jax.tree_util.tree_map(lambda a: a[None], out))
    assert got == oracle_msm(sigs, rs)
