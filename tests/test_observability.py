"""Unified telemetry subsystem tests (``consensus_specs_tpu/obs``):
registry semantics, span-tree shape on a real replay, exporter golden
checks, and the counter-diff fixture attributing engine-on vs
engine-off paths to different labels."""
import json

import pytest

from consensus_specs_tpu import obs
from consensus_specs_tpu.obs import export, registry, tracing
from consensus_specs_tpu.test_infra.metrics import counting
from consensus_specs_tpu.utils import env_flags


@pytest.fixture(autouse=True)
def _quiet_spans():
    """Spans off around every test here (individual tests enable as
    needed); teardown restores the env-derived gate state so a
    CS_TPU_PROFILE=1 pytest process keeps tracing the suites collected
    after this module."""
    tracing.enable(False)
    tracing.reset()
    yield
    tracing.enable(env_flags.PROFILE or env_flags.TRACE,
                   counters=env_flags.TRACE)
    tracing.reset()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_counter_labels_and_identity():
    c = registry.counter("t.obs.requests")
    assert registry.counter("t.obs.requests") is c
    a = c.labels(path="engine")
    b = c.labels(path="spec")
    assert c.labels(path="engine") is a        # bound series are stable
    a.add()
    a.add(2)
    b.add(5)
    assert c.value(path="engine") == 3
    assert c.value(path="spec") == 5
    assert c.total() == 8
    # label order does not split series
    c2 = registry.counter("t.obs.multi")
    c2.inc(a="1", b="2")
    assert c2.labels(b="2", a="1").n == 1


def test_metric_kind_conflict_raises():
    registry.counter("t.obs.kind")
    with pytest.raises(TypeError):
        registry.gauge("t.obs.kind")


def test_reset_keeps_bound_series_live():
    c = registry.counter("t.obs.reset")
    s = c.labels(backend="x")
    s.add(7)
    registry.reset("t.obs.")
    assert s.n == 0
    s.add()                                    # the old handle still counts
    assert c.value(backend="x") == 1


def test_prefix_reset_scopes():
    a = registry.counter("t.scope.a").labels()
    b = registry.counter("t.other.b").labels()
    a.add(3)
    b.add(4)
    registry.reset("t.scope.")
    assert a.n == 0 and b.n == 4


def test_snapshot_isolation():
    c = registry.counter("t.obs.iso")
    c.labels(k="v").add(2)
    snap = registry.snapshot()
    snap["t.obs.iso"]["series"]["{k=v}"] = 999
    snap["t.obs.iso"]["type"] = "gauge"
    fresh = registry.snapshot()
    assert fresh["t.obs.iso"]["series"]["{k=v}"] == 2
    assert fresh["t.obs.iso"]["type"] == "counter"


def test_gauge_set_and_max():
    g = registry.gauge("t.obs.gauge")
    g.set(5, lane="a")
    g.labels(lane="a").set_max(3)
    assert g.value(lane="a") == 5
    g.labels(lane="a").set_max(9)
    assert g.value(lane="a") == 9


def test_histogram_buckets():
    h = registry.histogram("t.obs.hist", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    val = h.labels()._value()
    assert val["count"] == 4
    assert val["min"] == 0.05 and val["max"] == 5.0
    assert val["buckets"] == {"0.1": 1, "1.0": 2, "+Inf": 1}


def test_histogram_quantile_summaries():
    h = registry.histogram("t.obs.quant", buckets=(0.1, 1.0))
    empty = h.labels()._value()
    assert empty["p50"] is None and empty["p90"] is None \
        and empty["p99"] is None
    for v in (0.05, 0.2, 0.4, 0.9, 5.0):
        h.observe(v)
    val = h.labels()._value()
    assert val["min"] <= val["p50"] <= val["p90"] <= val["p99"] <= val["max"]
    # p50 interpolates within its landing bucket; p99 within the
    # overflow bucket, sharpened toward the tracked max
    assert 0.1 <= val["p50"] <= 1.0
    assert 1.0 <= val["p99"] <= 5.0


def test_histogram_quantile_single_observation_collapses():
    h = registry.histogram("t.obs.quant1", buckets=(1.0,))
    h.observe(0.3)
    val = h.labels()._value()
    assert val["p50"] == val["p90"] == val["p99"] == 0.3


def test_counting_delta_missing_keys_read_zero():
    c = registry.counter("t.obs.delta").labels()
    with counting() as delta:
        c.add(3)
    assert delta["t.obs.delta"] == 3
    assert delta["t.obs.never_bumped"] == 0
    assert delta.nonzero().get("t.obs.delta") == 3


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_disabled_spans_record_nothing():
    with tracing.span("t.span.off"):
        pass
    assert tracing.stats() == {}
    assert tracing.span_tree() == {}


def test_nested_spans_self_vs_cumulative():
    tracing.enable(True, counters=False)
    with tracing.span("outer"):
        for _ in range(3):
            with tracing.span("inner"):
                pass
    st = tracing.stats()
    assert st["outer"]["count"] == 1
    assert st["inner"]["count"] == 3
    # cumulative >= self; the parent's self excludes child time, so the
    # self column sums to <= wall-clock (the nesting double-count fix)
    assert st["outer"]["total_s"] >= st["outer"]["self_s"]
    assert abs(st["outer"]["self_s"]
               + st["inner"]["total_s"] - st["outer"]["total_s"]) < 1e-3
    tree = tracing.span_tree()
    assert tree["outer"]["children"]["inner"]["count"] == 3


def test_span_counter_deltas_attach():
    c = registry.counter("t.span.work").labels(kind="unit")
    tracing.enable(True, counters=True)
    with tracing.span("t.span.cd"):
        c.add(4)
    node = tracing.span_tree()["t.span.cd"]
    assert node["counters"]["t.span.work{kind=unit}"] == 4


def test_span_exception_still_recorded():
    tracing.enable(True, counters=False)
    with pytest.raises(ValueError):
        with tracing.span("t.span.err"):
            raise ValueError("boom")
    assert tracing.stats()["t.span.err"]["count"] == 1


# ---------------------------------------------------------------------------
# real replay: span-tree shape + label attribution
# ---------------------------------------------------------------------------

def _replayed_snapshot(slots=8, validators=32):
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.tools.obs_report import build_state, replay
    from consensus_specs_tpu.utils import bls
    spec = build_spec("phase0", "minimal")
    state = build_state(spec, validators)
    was_active = bls.bls_active
    bls.bls_active = False
    obs.reset_all()
    obs.enable(True, counters=True)
    try:
        replay(spec, state, slots)
    finally:
        obs.enable(False)
        bls.bls_active = was_active
    return export.snapshot()


def test_state_transition_span_tree_shape():
    snap = _replayed_snapshot()
    tree = snap["spans"]
    st = tree["state_transition"]
    assert st["count"] == 8
    slots_node = st["children"]["process_slots"]
    assert slots_node["count"] == 8
    assert "process_slot" in slots_node["children"]
    assert "process_epoch" in slots_node["children"]
    assert "process_block" in st["children"]
    # the batched merkleization shows up inside the transition
    assert "hash_forest.flush" in st["children"] \
        or "hash_forest.flush" in slots_node["children"]["process_slot"][
            "children"]
    # fork-choice handlers traced too (replay feeds a store)
    assert tree["on_block"]["count"] == 8
    # per-span counter deltas attached under CS_TPU_TRACE semantics
    assert any(st["counters"].values())


def test_replay_snapshot_has_labeled_engine_counters():
    snap = _replayed_snapshot()
    metrics = snap["metrics"]
    pairs = metrics["merkle.pairs_hashed"]["series"]
    assert sum(pairs.values()) > 0
    assert set(pairs) <= {"{backend=native}", "{backend=jax}",
                          "{backend=hashlib}"}
    heads = metrics["forkchoice.head"]["series"]
    assert sum(heads.values()) == 8
    epochs = metrics["epoch.transition"]["series"]
    assert sum(epochs.values()) > 0
    assert metrics["cache.hit"]["series"]["{cache=root}"] > 0
    # the state-arrays store answered the replay's column reads: its
    # cache series populate and every extraction is column-attributed
    sa_series = metrics["state_arrays.extracts"]["series"]
    assert set(sa_series) <= {"{column=registry}", "{column=balances}",
                              "{column=inactivity_scores}",
                              "{column=participation}"}
    assert metrics["cache.hit"]["series"].get("{cache=state_arrays}", 0) > 0
    # (an 8-slot replay only crosses the genesis-epoch transition, which
    # writes nothing — the commit census lives in bench_state_arrays)
    assert "state_arrays.commits" in metrics
    assert not export.schema_problems(snap)


def test_engine_on_vs_off_attribute_to_different_labels():
    """The counter-diff fixture regression: the same epoch transition
    books under path=vectorized with the engine on and path=loop with
    the engine off."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.ops import epoch_kernels as ek
    from consensus_specs_tpu.test_infra.block import next_epoch
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    from consensus_specs_tpu.utils import bls
    spec = build_spec("phase0", "minimal")
    was_active = bls.bls_active
    bls.bls_active = False
    try:
        state = create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * 32,
            spec.MAX_EFFECTIVE_BALANCE)
        next_epoch(spec, state)
        s_on, s_off = state.copy(), state.copy()
        ek.use_vectorized()
        try:
            with counting() as delta_on:
                spec.process_epoch(s_on)
        finally:
            ek.use_loops()
        try:
            with counting() as delta_off:
                spec.process_epoch(s_off)
        finally:
            ek.use_auto()
    finally:
        bls.bls_active = was_active
    assert delta_on["epoch.transition{path=vectorized}"] > 0
    assert delta_on["epoch.transition{path=loop}"] == 0
    assert delta_off["epoch.transition{path=vectorized}"] == 0
    assert delta_off["epoch.transition{path=loop}"] > 0


def test_metrics_diff_fixture(metrics_diff):
    c = registry.counter("t.obs.fixture").labels()
    with metrics_diff() as delta:
        c.add(2)
    assert delta["t.obs.fixture"] == 2


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_prometheus_export_format():
    registry.counter("t.prom.hits").labels(backend="native").add(3)
    registry.gauge("t.prom.depth").set(7)
    registry.histogram("t.prom.lat", buckets=(1.0,)).observe(0.5)
    text = export.to_prometheus()
    assert "# TYPE cs_tpu_t_prom_hits counter" in text
    assert 'cs_tpu_t_prom_hits{backend="native"} 3' in text
    assert "# TYPE cs_tpu_t_prom_depth gauge" in text
    assert "cs_tpu_t_prom_depth 7" in text
    assert 'cs_tpu_t_prom_lat_bucket{le="1.0"} 1' in text
    # buckets are cumulative in the exposition: +Inf must equal _count
    assert 'cs_tpu_t_prom_lat_bucket{le="+Inf"} 1' in text
    assert "cs_tpu_t_prom_lat_count 1" in text
    # per-q quantile gauge lines (single observation collapses all
    # three to the observed value)
    for q in ("0.5", "0.9", "0.99"):
        assert f'cs_tpu_t_prom_lat_quantile{{q="{q}"}} 0.5' in text


def test_json_snapshot_round_trips():
    registry.counter("t.json.c").labels(x="y").add(1)
    parsed = json.loads(export.to_json())
    assert parsed["metrics"]["t.json.c"]["series"]["{x=y}"] == 1
    assert "spans" in parsed and "flags" in parsed


def test_schema_check_accepts_real_and_rejects_corrupt():
    snap = export.snapshot()
    assert export.schema_problems(snap) == []
    bad = json.loads(json.dumps(snap))
    bad["metrics"]["broken"] = {"type": "wat", "series": {"oops": "nan"}}
    probs = export.schema_problems(bad)
    assert any("unknown type" in p for p in probs)
    assert any("non-numeric" in p for p in probs)
    assert export.schema_problems({"metrics": 3}) != []
    with pytest.raises(AssertionError):
        export.assert_schema(snap, require_nonempty=("no.such.metric",))


def test_schema_flags_quantile_violations():
    registry.histogram("t.schema.q", buckets=(1.0,)).observe(0.5)
    bad = json.loads(json.dumps(export.snapshot()))
    v = bad["metrics"]["t.schema.q"]["series"][""]
    v["p50"] = None
    assert any("missing quantile" in p for p in export.schema_problems(bad))
    v["p50"] = 99.0
    assert any("quantile ordering" in p
               for p in export.schema_problems(bad))


def test_report_includes_quantile_columns():
    registry.histogram("t.report.q").observe(0.25)
    text = export.report()
    assert "p50=" in text and "p99=" in text


def test_report_renders_tree_and_metrics():
    registry.counter("t.report.c").labels().add(2)
    tracing.enable(True, counters=False)
    with tracing.span("t.report.outer"):
        with tracing.span("t.report.inner"):
            pass
    text = export.report()
    assert "t.report.outer" in text
    assert "  t.report.inner" in text       # indented under its parent
    assert "t.report.c" in text


# ---------------------------------------------------------------------------
# env gates / profiling alias surface
# ---------------------------------------------------------------------------

def test_env_flags_registered():
    assert hasattr(env_flags, "PROFILE")
    assert hasattr(env_flags, "TRACE")
    assert hasattr(env_flags, "STATE_ARRAYS")


def test_state_arrays_commit_span_recorded():
    """The deferred column flush books a ``state_arrays.commit`` span
    (profiling on) and a ``state_arrays.commits`` counter tick."""
    import numpy as np
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.state import arrays
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    spec = build_spec("phase0", "minimal")
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 32, spec.MAX_EFFECTIVE_BALANCE)
    arrays.use_arrays()
    tracing.enable(True)
    try:
        with counting() as delta:
            sa = arrays.of(state)
            sa.set_balances(sa.balances() + np.uint64(1))
        assert delta["state_arrays.commits"] == 1
        assert tracing.stats()["state_arrays.commit"]["count"] == 1
    finally:
        tracing.enable(False)
        arrays.use_auto()


def test_profiling_module_is_thin_alias():
    from consensus_specs_tpu.utils import profiling
    assert profiling.span is tracing.span
    assert profiling.stats is tracing.stats
    profiling.enable(True)
    try:
        with profiling.span("t.alias"):
            pass
        st = profiling.stats()["t.alias"]
        assert {"count", "total_s", "self_s", "mean_s", "max_s"} \
            <= set(st)
        assert "t.alias" in profiling.report()
    finally:
        profiling.enable(False)
        profiling.reset()


# ---------------------------------------------------------------------------
# reason-labeled fallback accounting (the fault-injection contract)
# ---------------------------------------------------------------------------

def test_fallback_counters_carry_reason_labels():
    """Injected vs organic fallbacks must stay distinguishable in
    ``obs_report``: every engine fallback counter is reason-labeled,
    the full label set is pre-bound at engine import, and no unlabeled
    twin series exists for the harness to miscount into."""
    # importing the engines binds their series at module scope
    import consensus_specs_tpu.forkchoice.proto_array  # noqa: F401
    import consensus_specs_tpu.ops.epoch_kernels  # noqa: F401
    import consensus_specs_tpu.parallel.mesh_epoch  # noqa: F401
    import consensus_specs_tpu.parallel.mesh_merkle  # noqa: F401
    import consensus_specs_tpu.recovery.checkpoint  # noqa: F401
    import consensus_specs_tpu.state.arrays  # noqa: F401
    import consensus_specs_tpu.utils.bls  # noqa: F401
    import consensus_specs_tpu.utils.ssz.merkle  # noqa: F401

    assert set(registry.counter("forkchoice.fallbacks").series_values()) \
        == {"{reason=guard}", "{reason=injected}", "{reason=deadline}"}
    assert set(registry.counter("epoch.fallbacks").series_values()) \
        == {"{reason=guard}", "{reason=injected}", "{reason=deadline}"}
    # engines whose fast path has no organic guard: injected + deadline
    assert set(registry.counter("merkle.fallbacks").series_values()) \
        == {"{reason=injected}", "{reason=deadline}"}
    assert set(registry.counter("state_arrays.fallbacks").series_values()) \
        == {"{reason=injected}", "{reason=deadline}"}
    # the mesh epoch engine declines organically (guards); the merkle
    # leaf-span path has no organic guard of its own; both re-shard
    # elastically on a device loss (counted reason=device_loss)
    assert set(registry.counter("mesh.epoch.fallbacks").series_values()) \
        == {"{reason=guard}", "{reason=injected}", "{reason=deadline}",
            "{reason=device_loss}"}
    assert set(registry.counter("mesh.merkle.fallbacks").series_values()) \
        == {"{reason=injected}", "{reason=deadline}",
            "{reason=device_loss}"}
    # the durability subsystem: injected/deadline skip a checkpoint,
    # io is the organic rung, the rest name recovery-ladder rungs
    assert set(registry.counter("recovery.fallbacks").series_values()) \
        == {"{reason=injected}", "{reason=deadline}", "{reason=io}",
            "{reason=manifest}", "{reason=blob}",
            "{reason=journal_corrupt}", "{reason=torn_record}",
            "{reason=divergence}"}
    assert set(registry.counter("recovery.checkpoints").series_values()) \
        == {"{result=saved}", "{result=skipped}", "{result=refused}"}
    assert set(registry.counter("recovery.restores").series_values()) \
        == {"{path=checkpoint}", "{path=genesis}"}
    flush = set(registry.counter("bls.flush").series_values())
    assert {"{path=fallback,reason=bisect}",
            "{path=fallback,reason=injected}",
            "{path=fallback,reason=deadline}"} <= flush
    assert "{path=fallback}" not in flush


def test_injected_fault_books_injected_reason_only():
    """``faults.count_fallback`` routes an InjectedFault to the
    ``reason=injected`` series and anything else to the organic one —
    an injected trip must never hide in the guard noise."""
    from consensus_specs_tpu import faults
    series = {
        "guard": registry.counter("test.fallbacks").labels(reason="guard"),
        "injected": registry.counter(
            "test.fallbacks").labels(reason="injected"),
    }
    with counting() as delta:
        faults.count_fallback(series, faults.InjectedFault("test.site", 1))
        faults.count_fallback(series, RuntimeError("organic trip"))
        faults.count_fallback(series, None)
    assert delta["test.fallbacks{reason=injected}"] == 1
    assert delta["test.fallbacks{reason=guard}"] == 2


def test_gen_runner_case_errors_are_obs_accounted():
    """The generator's narrowed per-case handler books swallowed
    failures on ``gen.case_errors{error=...}`` instead of vanishing
    them (a fault-injection run must not disappear into a catch-all —
    InjectedFault, a BaseException, escapes it entirely)."""
    from consensus_specs_tpu import faults
    from consensus_specs_tpu.gen import gen_runner

    class _Case:
        preset_name = "minimal"
        fork_name = "phase0"

        def __init__(self, fn):
            self.case_fn = fn
            self.exec_fork = "phase0"

        def dir_path(self):
            return "minimal/phase0/test/test/test/case"

    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        log = []
        with counting() as delta:
            result, _elapsed = gen_runner.generate_test_vector(
                _Case(lambda: (_ for _ in ()).throw(
                    AssertionError("spec invalidity"))), tmp, log)
        assert result == "error"
        assert len(log) == 1
        assert delta["gen.case_errors{error=AssertionError}"] == 1
        # an injected fault is NOT swallowed: it kills the case loudly
        with pytest.raises(faults.InjectedFault):
            gen_runner.generate_test_vector(
                _Case(lambda: (_ for _ in ()).throw(
                    faults.InjectedFault("bls.flush", 1))), tmp, [])


# ---------------------------------------------------------------------------
# cross-thread trace context (obs.tracing.capture_context/adopt_context)
# ---------------------------------------------------------------------------

def test_capture_context_disabled_returns_none():
    assert tracing.capture_context() is None
    # adopting a None context is a no-op (the disabled fast path), not
    # an error — callers never branch on the gate themselves
    with tracing.adopt_context(None):
        pass
    assert tracing.span_tree() == {}


def test_adopted_worker_spans_join_the_request_tree():
    import threading
    tracing.enable(True, counters=False)
    with tracing.span("req"):
        ctx = tracing.capture_context()
        assert ctx is not None and ctx.trace_id >= 1

        def _work():
            with tracing.adopt_context(ctx):
                with tracing.span("work"):
                    pass

        t = threading.Thread(target=_work)
        t.start()
        t.join()
    tree = tracing.span_tree()
    # ONE causal tree: the worker span is a child of the request span,
    # not a disjoint root, and nothing is orphan-flagged
    assert tree["req"]["children"]["work"]["count"] == 1
    assert "work" not in tree
    assert "orphan" not in tree["req"]
    assert "orphan" not in tree["req"]["children"]["work"]


def test_adopt_context_exception_unwinds_cleanly():
    import threading
    tracing.enable(True, counters=False)
    caught = []
    with tracing.span("req"):
        ctx = tracing.capture_context()

        def _work():
            try:
                with tracing.adopt_context(ctx):
                    with tracing.span("boom"):
                        raise ValueError("worker failure")
            except ValueError:
                caught.append(True)

        t = threading.Thread(target=_work)
        t.start()
        t.join()
    assert caught == [True]
    tree = tracing.span_tree()
    assert tree["req"]["children"]["boom"]["count"] == 1
    assert "boom" not in tree


def test_adopt_context_pops_leaked_spans():
    """A worker that hand-enters a span inside ``adopt_context`` and
    never exits it must not poison the thread's stack: the adopt exit
    pops every frame above (and including) the adopted node."""
    import threading
    tracing.enable(True, counters=False)
    with tracing.span("req"):
        ctx = tracing.capture_context()

        def _work():
            with tracing.adopt_context(ctx):
                leaked = tracing.span("leaked")
                leaked.__enter__()          # deliberately never exited
            # stack healed: a fresh span roots at the worker's own root
            # (an orphan, since this thread holds no context now)
            with tracing.span("after"):
                pass

        t = threading.Thread(target=_work)
        t.start()
        t.join()
    tree = tracing.span_tree()
    assert "leaked" in tree["req"]["children"]
    assert tree["after"]["orphan"] is True
    assert "after" not in tree["req"]["children"]


def test_double_adopt_same_thread_refused():
    tracing.enable(True, counters=False)
    with tracing.span("req"):
        ctx = tracing.capture_context()
        with tracing.adopt_context(ctx):
            with pytest.raises(RuntimeError, match="double-adopt"):
                with tracing.adopt_context(ctx):
                    pass
        # the refusal must not have broken the outer adoption: the
        # stack still carries the request node
        with tracing.span("again"):
            pass
    tree = tracing.span_tree()
    assert "again" in tree["req"]["children"]


def test_nested_adoption_of_inner_span_context():
    """Capturing deeper inside the tree parents worker spans at that
    depth, not at the root."""
    import threading
    tracing.enable(True, counters=False)
    with tracing.span("outer"):
        with tracing.span("inner"):
            ctx = tracing.capture_context()

            def _work():
                with tracing.adopt_context(ctx), tracing.span("deep"):
                    pass

            t = threading.Thread(target=_work)
            t.start()
            t.join()
    tree = tracing.span_tree()
    inner = tree["outer"]["children"]["inner"]
    assert inner["children"]["deep"]["count"] == 1


def test_orphan_thread_spans_flagged_in_tree_and_report():
    """Satellite regression: a thread that opens spans WITHOUT adopting
    a context roots a flagged ``[orphan thread]`` tree — visible, never
    silently merged with the main tree."""
    import threading
    tracing.enable(True, counters=False)
    with tracing.span("main.work"):
        pass

    def _work():
        with tracing.span("stray"):
            pass

    t = threading.Thread(target=_work)
    t.start()
    t.join()
    tree = tracing.span_tree()
    assert tree["stray"]["orphan"] is True
    assert "orphan" not in tree["main.work"]
    text = export.report()
    assert "[orphan thread]" in text
    # the schema tolerates the flag (snapshot stays exporter-valid)
    assert export.schema_problems(export.snapshot()) == []


# ---------------------------------------------------------------------------
# thread model (the registry.py contract)
# ---------------------------------------------------------------------------

def test_counter_hammer_two_threads():
    """The zero-lost-increment contract documented in obs/registry.py:
    bound-series ``add()`` is a single eval run on this interpreter, so
    two threads hammering one series under a 1µs switch interval lose
    nothing."""
    import sys
    import threading
    series = registry.counter("t.hammer").labels()
    n = 200_000
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)
    try:
        def _work():
            add = series.add
            for _ in range(n):
                add()

        threads = [threading.Thread(target=_work) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        sys.setswitchinterval(old)
    assert series.n == 2 * n


def test_histogram_concurrent_observe_consistent():
    """Histogram ``observe`` takes the per-series lock (multi-field
    update): concurrent observers lose no events and the bucket counts
    sum to the total."""
    import sys
    import threading
    h = registry.histogram("t.hammer.hist", buckets=(0.5,)).labels()
    n = 50_000
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)
    try:
        def _work(v):
            for _ in range(n):
                h.observe(v)

        threads = [threading.Thread(target=_work, args=(v,))
                   for v in (0.1, 0.9)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        sys.setswitchinterval(old)
    val = h._value()
    assert val["count"] == 2 * n
    assert val["buckets"] == {"0.5": n, "+Inf": n}
    assert val["min"] == 0.1 and val["max"] == 0.9


# ---------------------------------------------------------------------------
# flight recorder (obs.flight)
# ---------------------------------------------------------------------------

@pytest.fixture()
def _flight():
    from consensus_specs_tpu.obs import flight
    flight.reset(refresh_env=True)
    flight.enable(True)
    yield flight
    flight.reset(refresh_env=True)


def test_flight_ring_wraparound(_flight, monkeypatch):
    monkeypatch.setenv("CS_TPU_FLIGHT_SIZE", "8")
    _flight.reset(refresh_env=True)
    _flight.enable(True)        # the off-leg (CS_TPU_FLIGHT=0) pins
    #                             the env default off; force-arm
    for i in range(20):
        _flight.record("note", f"n{i}")
    d = _flight.dump(trigger="manual")
    recs = d["threads"]["MainThread"]
    # the ring keeps exactly the LAST size records, in sequence order
    assert len(recs) == 8
    assert d["dropped"] == 12
    assert [r[3] for r in recs] == [f"n{i}" for i in range(12, 20)]
    seqs = [r[0] for r in recs]
    assert seqs == sorted(seqs)


def test_flight_disabled_records_nothing(_flight):
    _flight.enable(False)
    _flight.record("note", "dropped-on-floor")
    assert _flight.record_count() == 0
    d = _flight.dump(trigger="manual")
    assert d["enabled"] is False
    assert d["threads"] == {}


def test_flight_dump_counters_and_format(_flight):
    with counting() as delta:
        _flight.record("note", "hello", 1.5)
        d = _flight.dump(trigger="manual")
    assert delta["obs.flight.records"] == 1
    assert delta["obs.flight.dumps{trigger=manual}"] == 1
    text = _flight.format_dump(d)
    assert "hello" in text and "MainThread" in text


def test_flight_spans_recorded_and_chrome_export(_flight, tmp_path):
    tracing.enable(True, counters=False)
    with tracing.span("t.flight.outer"):
        with tracing.span("t.flight.inner"):
            pass
    d = _flight.dump(trigger="manual")
    codes = [(r[2], r[3]) for r in d["threads"]["MainThread"]]
    assert ("span>", "t.flight.outer") in codes
    assert ("span<", "t.flight.inner") in codes
    # enters before exits, outer brackets inner
    assert codes.index(("span>", "t.flight.outer")) \
        < codes.index(("span>", "t.flight.inner")) \
        < codes.index(("span<", "t.flight.inner")) \
        < codes.index(("span<", "t.flight.outer"))
    out = tmp_path / "trace.json"
    _flight.write_chrome_trace(str(out), d)
    trace = json.loads(out.read_text())
    events = trace["traceEvents"]
    names = {e["name"] for e in events if e["ph"] == "X"}
    assert {"t.flight.outer", "t.flight.inner"} <= names
    assert any(e["ph"] == "M" for e in events)


def test_flight_cross_thread_dump_merged_by_thread(_flight):
    import threading

    def _work():
        _flight.record("note", "from-worker")

    t = threading.Thread(target=_work, name="t-flight-worker")
    t.start()
    t.join()
    _flight.record("note", "from-main")
    d = _flight.dump(trigger="manual")
    assert [r[3] for r in d["threads"]["t-flight-worker"]] \
        == ["from-worker"]
    assert "from-main" in [r[3] for r in d["threads"]["MainThread"]]


# ---------------------------------------------------------------------------
# live telemetry plane (obs.serve)
# ---------------------------------------------------------------------------

def _http_get(url):
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


def test_http_plane_endpoints_and_health_flip():
    from consensus_specs_tpu import supervisor
    registry.counter("t.http.seen").labels().add(3)
    supervisor.reset()
    try:
        with obs.serve(0) as srv:
            code, body = _http_get(srv.url + "/metrics")
            assert code == 200
            assert b"cs_tpu_t_http_seen" in body
            code, body = _http_get(srv.url + "/healthz")
            assert code == 200 and json.loads(body)["ok"] is True
            code, body = _http_get(srv.url + "/snapshot")
            assert code == 200
            snap = json.loads(body)
            assert export.schema_problems(snap) == []
            code, _ = _http_get(srv.url + "/nope")
            assert code == 404
            # forced quarantine flips /healthz non-200, naming the site
            with supervisor.quarantine_hook(lambda s, d: None):
                supervisor.quarantine("t.http.site", "forced by test")
            code, body = _http_get(srv.url + "/healthz")
            health = json.loads(body)
            assert code == 503 and health["ok"] is False
            assert "t.http.site" in health["quarantined"]
            supervisor.reset()
            code, _ = _http_get(srv.url + "/healthz")
            assert code == 200
        assert registry.counter("obs.http.requests").total() >= 6
    finally:
        supervisor.reset()
