"""CS_TPU_SANITIZER runtime effect sanitizer (docs/static-analysis.md).

The acceptance bar: every effect contract has a static proof (speclint
E12xx) AND a runtime enforcement twin — and a seeded violation is
caught by BOTH.  This suite drives the runtime half end-to-end against
real states/checkpoints and pins the twin property explicitly.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu import sanitizer
from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.state import arrays
from consensus_specs_tpu.test_infra.genesis import create_genesis_state
from consensus_specs_tpu.utils import bls

N = 16


@pytest.fixture(autouse=True)
def _lifecycle():
    prev_bls = bls.bls_active
    bls.bls_active = False
    sanitizer.reset()
    yield
    bls.bls_active = prev_bls
    sanitizer.use_auto()
    arrays.use_auto()
    sanitizer.reset()


def _spec(fork="phase0"):
    return build_spec(fork, "minimal")


def _genesis(spec):
    return create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * N, spec.MAX_EFFECTIVE_BALANCE)


def _snap():
    return sanitizer.snapshot()


# ---------------------------------------------------------------------------
# mode / plumbing
# ---------------------------------------------------------------------------

def test_disabled_by_default_and_knob_armed(monkeypatch):
    assert not sanitizer.enabled()
    monkeypatch.setenv("CS_TPU_SANITIZER", "1")
    assert sanitizer.enabled()
    sanitizer.disarm()
    assert not sanitizer.enabled()


def test_effect_error_surface_matches_mode():
    sanitizer.disarm()
    err = sanitizer.effect_error("E1201", "boom")
    assert type(err) is RuntimeError
    sanitizer.arm()
    err = sanitizer.effect_error("E1201", "boom")
    assert isinstance(err, sanitizer.EffectViolation)
    assert err.rule == "E1201" and "E1201" in str(err)
    # EffectViolation stays a RuntimeError: existing except clauses in
    # callers keep working when the sanitizer is armed
    assert isinstance(err, RuntimeError)


# ---------------------------------------------------------------------------
# E1201: direct SSZ write under a pending deferred column
# ---------------------------------------------------------------------------

def _seed_e1201(spec, state):
    sa = arrays.of(state)
    with arrays.commit_scope(state):
        bal = sa.balances().copy()
        bal[0] += np.uint64(1)
        sa.set_balances(bal)                      # deferred engine write
        state.balances[1] = int(state.balances[1]) + 2   # direct SSZ write


def test_e1201_runtime_violation_names_rule():
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sanitizer.arm()
    before = _snap()
    with pytest.raises(sanitizer.EffectViolation) as exc:
        _seed_e1201(spec, state)
    assert exc.value.rule == "E1201"
    after = _snap()
    assert after["E1201"]["violations"] \
        == before["E1201"]["violations"] + 1
    assert after["E1201"]["checks"] > before["E1201"]["checks"]


def test_e1201_disarmed_keeps_plain_runtime_error():
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sanitizer.disarm()
    with pytest.raises(RuntimeError) as exc:
        _seed_e1201(spec, state)
    assert not isinstance(exc.value, sanitizer.EffectViolation)


def test_e1201_twin_caught_statically_and_at_runtime(tmp_path):
    """THE twin acceptance criterion: ONE seeded contract violation —
    a direct SSZ balances write while a deferred column write is
    pending in an open commit scope — is caught by the static pass on
    a fixture AND by the armed sanitizer at runtime."""
    # static half: the speclint effects pass flags the same class
    from consensus_specs_tpu.tools.speclint.passes import (
        effects as effects_pass)
    root = tmp_path / "repo"
    src = (
        "from consensus_specs_tpu.state import arrays as state_arrays\n"
        "class DemoSpec:\n"
        "    def process_slots(self, state):\n"
        "        with state_arrays.commit_scope(state):\n"
        "            self.process_epoch(state)\n"
        "    def process_epoch(self, state):\n"
        "        state.balances[1] += 2\n")
    path = root / "consensus_specs_tpu" / "forks" / "demo.py"
    os.makedirs(path.parent)
    path.write_text(src)
    (root / "consensus_specs_tpu" / "state").mkdir()
    (root / "consensus_specs_tpu" / "state" / "arrays.py").write_text(
        "def commit_scope(state):\n    pass\n"
        "def flush(state):\n    pass\n")
    static = effects_pass.check_tree(str(root))
    assert [f.code for f in static] == ["E1201"]
    # runtime half: the sanitizer catches the same violation live
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sanitizer.arm()
    with pytest.raises(sanitizer.EffectViolation) as exc:
        _seed_e1201(spec, state)
    assert exc.value.rule == "E1201" == static[0].code


# ---------------------------------------------------------------------------
# E1202: fork inside an open scope (counted, not raised)
# ---------------------------------------------------------------------------

def test_e1202_fork_during_scope_counted_not_raised():
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sanitizer.arm()
    sa = arrays.of(state)
    before = _snap()
    with arrays.commit_scope(state):
        bal = sa.balances().copy()
        bal[0] += np.uint64(3)
        sa.set_balances(bal)
        child = arrays.fork_state(state)     # legal early commit
    after = _snap()
    assert after["E1202"]["violations"] \
        == before["E1202"]["violations"] + 1
    # and the fork really committed-into-child (behavior unchanged)
    assert int(child.balances[0]) == int(state.balances[0])


def test_e1202_clean_fork_outside_scope_books_no_violation():
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sanitizer.arm()
    before = _snap()
    arrays.fork_state(state)
    after = _snap()
    assert after["E1202"]["violations"] == before["E1202"]["violations"]


# ---------------------------------------------------------------------------
# E1203: checkpoint refused under an open scope
# ---------------------------------------------------------------------------

def test_e1203_checkpoint_refusal_booked():
    from types import SimpleNamespace
    from consensus_specs_tpu.recovery import checkpoint
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sanitizer.arm()
    store = SimpleNamespace(block_states={b"r": state},
                            checkpoint_states={})
    before = _snap()
    sa = arrays.of(state)
    sa._deferred = True
    try:
        with pytest.raises(checkpoint.CheckpointRefused) as exc:
            checkpoint._refuse_open_scopes(store)
    finally:
        sa._deferred = False
    assert "E1203" in str(exc.value)
    after = _snap()
    assert after["E1203"]["violations"] \
        == before["E1203"]["violations"] + 1
    assert after["E1203"]["checks"] > before["E1203"]["checks"]


# ---------------------------------------------------------------------------
# E1221: checkpoint blob/manifest ordering ledger
# ---------------------------------------------------------------------------

def test_e1221_ledger_orders_blobs_before_manifest():
    sanitizer.arm()
    sanitizer.blob_written("/d1", 1, "a.bin")
    sanitizer.blob_written("/d1", 1, "b.bin")
    sanitizer.manifest_written("/d1", 1, ["a.bin", "b.bin"])
    with pytest.raises(sanitizer.EffectViolation) as exc:
        sanitizer.blob_written("/d1", 1, "late.bin")
    assert exc.value.rule == "E1221"


def test_e1221_manifest_recording_unwritten_blob_raises():
    sanitizer.arm()
    sanitizer.blob_written("/d2", 1, "a.bin")
    with pytest.raises(sanitizer.EffectViolation):
        sanitizer.manifest_written("/d2", 1, ["a.bin", "ghost.bin"])


def test_e1221_ledger_scoped_by_directory_and_discard():
    sanitizer.arm()
    sanitizer.blob_written("/d3", 1, "a.bin")
    sanitizer.manifest_written("/d3", 1, ["a.bin"])
    # a DIFFERENT directory reusing generation numbers is independent
    sanitizer.blob_written("/d4", 1, "a.bin")
    sanitizer.manifest_written("/d4", 1, ["a.bin"])
    # a discarded generation resets its ledger entry
    sanitizer.generation_discarded("/d3", 1)
    sanitizer.blob_written("/d3", 1, "a.bin")     # no raise


def test_e1221_real_checkpoint_save_is_clean(tmp_path):
    from consensus_specs_tpu.recovery.checkpoint import CheckpointStore
    from consensus_specs_tpu.sim.driver import ChainSim
    spec = _spec()
    sanitizer.arm()
    sim = ChainSim(spec, N)
    cs = CheckpointStore(str(tmp_path / "ckpt"))
    before = _snap()
    gen = cs.save(spec, sim, 0, fork="phase0", preset="minimal")
    assert gen == 1
    after = _snap()
    assert after["E1221"]["checks"] > before["E1221"]["checks"]
    assert after["E1221"]["violations"] == before["E1221"]["violations"]


# ---------------------------------------------------------------------------
# E1222 / E1223: journal + rename ordering facts
# ---------------------------------------------------------------------------

def test_e1222_unfsynced_step_marker_raises():
    sanitizer.arm()
    with pytest.raises(sanitizer.EffectViolation) as exc:
        sanitizer.step_committed(None, fsynced=False)
    assert exc.value.rule == "E1222"


def test_e1222_real_journal_commit_is_clean(tmp_path):
    from consensus_specs_tpu.recovery.journal import Journal, BLOCK
    sanitizer.arm()
    before = _snap()
    j = Journal(str(tmp_path / "wal.log"), fresh=True)
    j.append(BLOCK, b"payload")
    j.commit_step(0, {"op": "noop"})
    j.close()
    after = _snap()
    assert after["E1222"]["checks"] >= before["E1222"]["checks"] + 2
    assert after["E1222"]["violations"] == before["E1222"]["violations"]


def test_e1223_unfsynced_rename_raises_exempt_passes(tmp_path):
    from consensus_specs_tpu.recovery.atomic import (
        atomic_replace_bytes, atomic_write_bytes)
    sanitizer.arm()
    with pytest.raises(sanitizer.EffectViolation) as exc:
        sanitizer.rename_event("/tmp/x", fsynced=False)
    assert exc.value.rule == "E1223"
    # the real helpers: full-fsync and the sanctioned exempt variant
    before = _snap()
    atomic_write_bytes(str(tmp_path / "a"), b"1")
    atomic_replace_bytes(str(tmp_path / "b"), b"2")
    after = _snap()
    assert after["E1223"]["checks"] == before["E1223"]["checks"] + 2
    assert after["E1223"]["violations"] == before["E1223"]["violations"]


# ---------------------------------------------------------------------------
# integration: armed epoch transitions are observation-only
# ---------------------------------------------------------------------------

def test_armed_epoch_transition_byte_identical():
    from consensus_specs_tpu.test_infra.block import next_epoch
    from consensus_specs_tpu.utils.ssz import hash_tree_root
    spec = _spec("altair")
    arrays.use_arrays()
    state_a = _genesis(spec)
    state_b = _genesis(spec)
    sanitizer.disarm()
    next_epoch(spec, state_a)
    sanitizer.arm()
    before = _snap()
    next_epoch(spec, state_b)
    after = _snap()
    assert bytes(hash_tree_root(state_a)) == bytes(hash_tree_root(state_b))
    assert after["E1201"]["checks"] > before["E1201"]["checks"]
    assert sum(v["violations"] for v in after.values()) \
        == sum(v["violations"] for v in before.values())


def test_e1221_generation_reuse_after_external_damage(tmp_path):
    """Sweep-found regression: the corruption legs delete a
    generation's manifest on disk, so the next save derives the SAME
    generation number from disk state — the ledger entry for it is
    stale and must restart with the new write, not false-positive."""
    from consensus_specs_tpu.recovery.checkpoint import CheckpointStore
    from consensus_specs_tpu.sim.driver import ChainSim
    spec = _spec()
    sanitizer.arm()
    sim = ChainSim(spec, N)
    cs = CheckpointStore(str(tmp_path / "ckpt"))
    gen = cs.save(spec, sim, 0, fork="phase0", preset="minimal")
    assert gen == 1
    os.unlink(cs.manifest_path(gen))      # external damage
    again = cs.save(spec, sim, 1, fork="phase0", preset="minimal")
    assert again == gen                   # same number, no EffectViolation


def test_scope_ledger_never_leaks_across_disarm():
    """Review regression: a scope opened while armed must not leave an
    id()-keyed ledger entry when the sanitizer is disarmed before the
    scope exits — CPython reuses ids, so a leaked entry could book a
    false E1202 against an unrelated later store."""
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sanitizer.arm()
    sa = arrays.of(state)
    with arrays.commit_scope(state):
        assert id(sa) in sanitizer._scopes()
        sanitizer.disarm()
    assert id(sa) not in sanitizer._scopes()


def test_e1201_message_names_clobbered_columns():
    """The scope ledger enriches the armed E1201 message with the
    deferred columns the direct write would clobber."""
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sanitizer.arm()
    with pytest.raises(sanitizer.EffectViolation) as exc:
        _seed_e1201(spec, state)
    assert "would clobber deferred: balances" in str(exc.value)
