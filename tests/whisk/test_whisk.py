"""Whisk (SSLE) feature-fork tests.

Reference model: ``test/whisk/`` against
``specs/_features/whisk/beacon-chain.md`` — opening-proof-gated block
headers, candidate/proposer tracker selection, shuffling, registration.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.block import (
    get_state_and_beacon_parent_root_at_slot, apply_randao_reveal,
)
from consensus_specs_tpu.ops import whisk_proofs


def _slot_proposer(spec, state, slot):
    """(validator index, k) matching the slot's proposer tracker.

    Genesis trackers are initial (r_G = G, k_r_G = k*G == commitment),
    so the owner is found by commitment equality."""
    tracker = state.whisk_proposer_trackers[
        slot % spec.WHISK_PROPOSER_TRACKERS_COUNT]
    for index in range(len(state.validators)):
        if bytes(state.whisk_k_commitments[index]) == bytes(tracker.k_r_G):
            return index, spec.get_initial_whisk_k(index, 0)
    raise AssertionError("no tracker owner found (non-initial tracker?)")


def _fill_shuffle(spec, state, block):
    """Satisfy process_shuffled_trackers for the block's randao reveal."""
    shuffle_epoch = spec.compute_epoch_at_slot(block.slot) \
        % spec.config.WHISK_EPOCHS_PER_SHUFFLING_PHASE
    if shuffle_epoch + spec.config.WHISK_PROPOSER_SELECTION_GAP + 1 \
            >= spec.config.WHISK_EPOCHS_PER_SHUFFLING_PHASE:
        return  # cooldown: leave zeroed
    indices = spec.get_shuffle_indices(block.body.randao_reveal)
    pre = [state.whisk_candidate_trackers[i] for i in indices]
    n = len(pre)
    post, proof = whisk_proofs.GenerateWhiskShuffleProof(
        pre, list(range(n)), 7)
    block.body.whisk_post_shuffle_trackers = [
        spec.WhiskTracker(r_G=r, k_r_G=krg) for r, krg in post]
    block.body.whisk_shuffle_proof = proof


def build_whisk_block(spec, state, register=True):
    """A valid whisk block for the next slot (proposer chosen by
    tracker, opening proof attached).  ``register=True`` is the only
    valid mode against a genesis state: every tracker is still initial,
    so the first-proposal registration branch always applies."""
    slot = state.slot + 1
    adv_state, parent_root = get_state_and_beacon_parent_root_at_slot(
        spec, state, slot)
    proposer_index, k = _slot_proposer(spec, adv_state, slot)

    block = spec.BeaconBlock()
    block.slot = slot
    block.proposer_index = proposer_index
    block.parent_root = parent_root
    block.body.eth1_data.deposit_count = adv_state.eth1_deposit_index
    block.body.sync_aggregate.sync_committee_signature = \
        spec.G2_POINT_AT_INFINITY
    from consensus_specs_tpu.test_infra.execution_payload import (
        build_empty_execution_payload)
    block.body.execution_payload = build_empty_execution_payload(
        spec, adv_state)
    apply_randao_reveal(spec, adv_state, block, proposer_index)

    # opening proof over the slot's proposer tracker
    tracker = adv_state.whisk_proposer_trackers[
        slot % spec.WHISK_PROPOSER_TRACKERS_COUNT]
    block.body.whisk_opening_proof = whisk_proofs.GenerateWhiskTrackerProof(
        tracker, k)
    _fill_shuffle(spec, adv_state, block)
    if register:
        r = 12345
        k_new = 67890
        new_tracker = spec.WhiskTracker(
            r_G=spec.BLSG1ScalarMultiply(r, spec.BLS_G1_GENERATOR),
            k_r_G=spec.BLSG1ScalarMultiply(
                (k_new * r) % spec.BLS_MODULUS, spec.BLS_G1_GENERATOR))
        block.body.whisk_tracker = new_tracker
        block.body.whisk_k_commitment = spec.get_k_commitment(k_new)
        block.body.whisk_registration_proof = \
            whisk_proofs.GenerateWhiskTrackerProof(new_tracker, k_new)
    return block


def _transition(spec, state, block):
    spec.process_slots(state, block.slot)
    spec.process_block(state, block)


@with_phases(["whisk"])
@spec_state_test
def test_whisk_genesis_shape(spec, state):
    assert len(state.whisk_trackers) == len(state.validators)
    assert len(state.whisk_k_commitments) == len(state.validators)
    # genesis trackers are initial: r_G == G
    assert all(bytes(t.r_G) == spec.BLS_G1_GENERATOR
               for t in state.whisk_trackers)
    # selections populated (non-zero trackers)
    assert any(bytes(t.k_r_G) != bytes(spec.BLSG1Point())
               for t in state.whisk_proposer_trackers)


@with_phases(["whisk"])
@spec_state_test
def test_whisk_block_with_registration(spec, state):
    block = build_whisk_block(spec, state, register=True)
    proposer = block.proposer_index
    yield "pre", state
    _transition(spec, state, block)
    yield "post", state
    # tracker re-registered away from the initial form
    assert bytes(state.whisk_trackers[proposer].r_G) != \
        spec.BLS_G1_GENERATOR
    assert bytes(state.whisk_k_commitments[proposer]) == \
        bytes(block.body.whisk_k_commitment)


@with_phases(["whisk"])
@spec_state_test
def test_whisk_invalid_opening_proof(spec, state):
    block = build_whisk_block(spec, state, register=True)
    bad = bytearray(bytes(block.body.whisk_opening_proof))
    bad[-1] ^= 1
    block.body.whisk_opening_proof = bytes(bad)
    expect_assertion_error(lambda: _transition(spec, state.copy(), block))


@with_phases(["whisk"])
@spec_state_test
def test_whisk_invalid_wrong_proposer(spec, state):
    """A proposer whose tracker doesn't match the slot fails the proof."""
    block = build_whisk_block(spec, state, register=True)
    block.proposer_index = (block.proposer_index + 1) \
        % len(state.validators)
    expect_assertion_error(lambda: _transition(spec, state.copy(), block))


@with_phases(["whisk"])
@spec_state_test
def test_whisk_invalid_shuffle_proof(spec, state):
    block = build_whisk_block(spec, state, register=True)
    if len(bytes(block.body.whisk_shuffle_proof)) == 0:
        return  # cooldown phase: no shuffle to corrupt
    bad = bytearray(bytes(block.body.whisk_shuffle_proof))
    bad[9] ^= 1  # corrupt a rerandomization scalar
    block.body.whisk_shuffle_proof = bytes(bad)
    expect_assertion_error(lambda: _transition(spec, state.copy(), block))


@with_phases(["whisk"])
@spec_state_test
def test_whisk_invalid_duplicate_registration_commitment(spec, state):
    """Registering an already-used k commitment must fail."""
    block = build_whisk_block(spec, state, register=True)
    existing = bytes(state.whisk_k_commitments[0])
    k0 = spec.get_initial_whisk_k(0, 0)
    r = 999
    dup_tracker = spec.WhiskTracker(
        r_G=spec.BLSG1ScalarMultiply(r, spec.BLS_G1_GENERATOR),
        k_r_G=spec.BLSG1ScalarMultiply((k0 * r) % spec.BLS_MODULUS,
                                       spec.BLS_G1_GENERATOR))
    block.body.whisk_tracker = dup_tracker
    block.body.whisk_k_commitment = existing
    block.body.whisk_registration_proof = \
        whisk_proofs.GenerateWhiskTrackerProof(dup_tracker, k0)
    expect_assertion_error(lambda: _transition(spec, state.copy(), block))


@with_phases(["whisk"])
@spec_state_test
def test_whisk_shuffle_updates_candidates(spec, state):
    block = build_whisk_block(spec, state, register=True)
    if len(bytes(block.body.whisk_shuffle_proof)) == 0:
        return
    indices = spec.get_shuffle_indices(block.body.randao_reveal)
    _transition(spec, state, block)
    for i, shuffle_index in enumerate(indices):
        assert state.whisk_candidate_trackers[shuffle_index] == \
            block.body.whisk_post_shuffle_trackers[i]


def test_opening_proof_roundtrip():
    """Unit: DLEQ proof verifies and rejects mismatched commitments."""
    from consensus_specs_tpu.ops.bls12_381.curve import G1_GENERATOR

    class T:
        pass
    k, r = 777, 555
    t = T()
    t.r_G = G1_GENERATOR.mult(r).to_compressed()
    t.k_r_G = G1_GENERATOR.mult(k * r).to_compressed()
    commitment = G1_GENERATOR.mult(k).to_compressed()
    proof = whisk_proofs.GenerateWhiskTrackerProof(t, k)
    assert whisk_proofs.IsValidWhiskOpeningProof(t, commitment, proof)
    wrong = G1_GENERATOR.mult(k + 1).to_compressed()
    assert not whisk_proofs.IsValidWhiskOpeningProof(t, wrong, proof)
    assert not whisk_proofs.IsValidWhiskOpeningProof(
        t, commitment, proof[:-1] + b"\x00")


def test_shuffle_proof_rejects_non_permutation():
    from consensus_specs_tpu.ops.bls12_381.curve import G1_GENERATOR

    class T:
        def __init__(self, r_G, k_r_G):
            self.r_G, self.k_r_G = r_G, k_r_G
    pre = [T(G1_GENERATOR.mult(i + 2).to_compressed(),
             G1_GENERATOR.mult(3 * i + 5).to_compressed())
           for i in range(4)]
    post, proof = whisk_proofs.GenerateWhiskShuffleProof(
        pre, [2, 0, 3, 1], 11)
    post_t = [T(r, k) for r, k in post]
    assert whisk_proofs.IsValidWhiskShuffleProof(pre, post_t, proof)
    # a proof is bound to its instance: swapping two post trackers
    # breaks the permutation relation and must fail
    swapped = [post_t[1], post_t[0]] + post_t[2:]
    assert not whisk_proofs.IsValidWhiskShuffleProof(pre, swapped, proof)
    # tampered proof bytes must fail
    bad = bytearray(proof)
    bad[60] ^= 0x01
    assert not whisk_proofs.IsValidWhiskShuffleProof(pre, post_t, bytes(bad))


@with_phases(["whisk"])
@spec_state_test
def test_whisk_invalid_identity_tracker_registration(spec, state):
    """First proposal must not re-register the identity form r_G == G."""
    block = build_whisk_block(spec, state, register=True)
    block.body.whisk_tracker = spec.WhiskTracker(
        r_G=spec.BLS_G1_GENERATOR,
        k_r_G=bytes(block.body.whisk_tracker.k_r_G))
    expect_assertion_error(lambda: _transition(spec, state.copy(), block))


@with_phases(["whisk"])
@spec_state_test
def test_whisk_invalid_non_unique_k_other(spec, state):
    """Registering another validator's k commitment is rejected."""
    block = build_whisk_block(spec, state, register=True)
    other = (block.proposer_index + 1) % len(state.validators)
    other_k = spec.get_initial_whisk_k(other, 0)
    r = 12345
    tracker = spec.WhiskTracker(
        r_G=spec.BLSG1ScalarMultiply(r, spec.BLS_G1_GENERATOR),
        k_r_G=spec.BLSG1ScalarMultiply(
            (other_k * r) % spec.BLS_MODULUS, spec.BLS_G1_GENERATOR))
    block.body.whisk_tracker = tracker
    block.body.whisk_k_commitment = spec.get_k_commitment(other_k)
    block.body.whisk_registration_proof = \
        whisk_proofs.GenerateWhiskTrackerProof(tracker, other_k)
    expect_assertion_error(lambda: _transition(spec, state.copy(), block))


@with_phases(["whisk"])
@spec_state_test
def test_whisk_second_proposal_empty_registration(spec, state):
    """A proposer with a non-initial tracker must leave the registration
    fields zeroed (second-proposal branch)."""
    # learn the slot's proposer on a throwaway copy, then mutate the
    # real state BEFORE building (the block binds the parent state root)
    probe = build_whisk_block(spec, state.copy(), register=False)
    k = spec.get_initial_whisk_k(probe.proposer_index, 0)
    r = 999
    state.whisk_trackers[probe.proposer_index] = spec.WhiskTracker(
        r_G=spec.BLSG1ScalarMultiply(r, spec.BLS_G1_GENERATOR),
        k_r_G=spec.BLSG1ScalarMultiply(
            (k * r) % spec.BLS_MODULUS, spec.BLS_G1_GENERATOR))
    block = build_whisk_block(spec, state, register=False)
    assert block.proposer_index == probe.proposer_index
    yield "pre", state
    _transition(spec, state, block)
    yield "post", state


@with_phases(["whisk"])
@spec_state_test
def test_whisk_invalid_second_proposal_with_registration(spec, state):
    """Re-registration by an already-registered proposer is rejected."""
    probe = build_whisk_block(spec, state.copy(), register=True)
    k = spec.get_initial_whisk_k(probe.proposer_index, 0)
    r = 999
    state.whisk_trackers[probe.proposer_index] = spec.WhiskTracker(
        r_G=spec.BLSG1ScalarMultiply(r, spec.BLS_G1_GENERATOR),
        k_r_G=spec.BLSG1ScalarMultiply(
            (k * r) % spec.BLS_MODULUS, spec.BLS_G1_GENERATOR))
    block = build_whisk_block(spec, state, register=True)
    expect_assertion_error(lambda: _transition(spec, state.copy(), block))


@with_phases(["whisk"])
@spec_state_test
def test_whisk_invalid_zeroed_shuffle_outside_cooldown(spec, state):
    """During the active shuffle window, zeroed post-trackers (the
    cooldown form) are rejected."""
    shuffle_epoch = spec.get_current_epoch(state) \
        % spec.config.WHISK_EPOCHS_PER_SHUFFLING_PHASE
    assert shuffle_epoch + spec.config.WHISK_PROPOSER_SELECTION_GAP + 1 \
        < spec.config.WHISK_EPOCHS_PER_SHUFFLING_PHASE
    block = build_whisk_block(spec, state, register=True)
    block.body.whisk_post_shuffle_trackers = type(
        block.body.whisk_post_shuffle_trackers)()
    block.body.whisk_shuffle_proof = spec.WhiskShuffleProof()
    expect_assertion_error(lambda: _transition(spec, state.copy(), block))


def _advance_to_cooldown(spec, state):
    """Advance so shuffle_epoch falls in the cooldown window."""
    phase = spec.config.WHISK_EPOCHS_PER_SHUFFLING_PHASE
    gap = spec.config.WHISK_PROPOSER_SELECTION_GAP
    while (spec.get_current_epoch(state) % phase) + gap + 1 < phase:
        spec.process_slots(state, state.slot + spec.SLOTS_PER_EPOCH)


@with_phases(["whisk"])
@spec_state_test
def test_whisk_cooldown_zeroed_shuffle_ok(spec, state):
    """In the cooldown window a zeroed shuffle is the only valid form."""
    _advance_to_cooldown(spec, state)
    block = build_whisk_block(spec, state, register=True)
    assert bytes(block.body.whisk_shuffle_proof) == \
        bytes(spec.WhiskShuffleProof())
    yield "pre", state
    _transition(spec, state, block)
    yield "post", state


@with_phases(["whisk"])
@spec_state_test
def test_whisk_invalid_cooldown_non_zero_shuffle(spec, state):
    """Shuffling during the cooldown window is rejected."""
    _advance_to_cooldown(spec, state)
    block = build_whisk_block(spec, state, register=True)
    indices = spec.get_shuffle_indices(block.body.randao_reveal)
    pre = [state.whisk_candidate_trackers[i] for i in indices]
    post, proof = whisk_proofs.GenerateWhiskShuffleProof(
        pre, list(range(len(pre))), 7)
    block.body.whisk_post_shuffle_trackers = [
        spec.WhiskTracker(r_G=r, k_r_G=krg) for r, krg in post]
    block.body.whisk_shuffle_proof = proof
    expect_assertion_error(lambda: _transition(spec, state.copy(), block))
