"""Suite for the adversarial chain simulator + fault-injection harness
(``consensus_specs_tpu/sim``, ``consensus_specs_tpu/faults``).

Covers the stack's load-bearing contracts:

* **driver determinism** — the same pure-data script replays to a
  byte-identical digest, including the accepted/rejected step pattern;
* **scenario catalog** — every shape builds JSON-able scripts, seeds
  reproduce, a forced name consumes aligned entropy;
* **fault schedules** — ordinal triggers fire exactly once, observing
  schedules never fire, arming is not reentrant, and ``InjectedFault``
  escapes ``except Exception`` catch-alls by construction;
* **harness legs** — injected/storm legs finish byte-identical with the
  ``reason=injected`` counter moving exactly as scheduled, the
  engines-off differential matches, and each LegFailure category
  (no-discharge, silent-fallback, organic-leak, diverged) actually
  trips when its failure mode is simulated;
* **repro** — the shrinker reduces scripts under a budget, artifacts
  round-trip through JSON, and ``replay`` re-runs a dumped leg.
"""
import json

import pytest

from consensus_specs_tpu import faults
from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.sim import driver, harness, repro, scenarios
from consensus_specs_tpu.test_infra.metrics import counting
from consensus_specs_tpu.utils import bls


@pytest.fixture(scope="module")
def spec():
    return build_spec("phase0", "minimal")


@pytest.fixture(autouse=True)
def _sim_mode():
    """Signatures off (scenario digests cover everything but sig bytes;
    the sweep's --bls-seeds legs and make sim-smoke run them on) and no
    schedule armed on entry/exit."""
    prev_bls = bls.bls_active
    bls.bls_active = False
    assert faults.active() is None
    yield
    assert faults.active() is None
    bls.bls_active = prev_bls


def _epoch(spec):
    return int(spec.SLOTS_PER_EPOCH)


def _short_script(spec, epochs=2):
    """A small deterministic healthy chain: enough to touch every epoch
    kernel without catalog-scale runtimes."""
    script = []
    for _ in range(epochs * _epoch(spec)):
        script.append({"op": "tick"})
        script.append({"op": "block", "tip": "head", "att_slots": 2,
                       "frac": 1.0})
    script.append({"op": "checks"})
    return script


def _scenario(spec, script, name="unit", seed=0):
    return scenarios.Scenario(name, seed, script,
                              _epoch(spec) * 8, None)


# ---------------------------------------------------------------------------
# faults module
# ---------------------------------------------------------------------------

def test_schedule_fires_at_exact_ordinals():
    sched = faults.FaultSchedule({"epoch.slashings": [2, 4]})
    fired = []
    for n in range(1, 6):
        try:
            sched.hit("epoch.slashings")
        except faults.InjectedFault as exc:
            fired.append((exc.site, exc.n))
    assert fired == [("epoch.slashings", 2), ("epoch.slashings", 4)]
    assert sched.fully_fired()
    assert sched.calls == {"epoch.slashings": 5}


def test_observing_schedule_counts_without_firing():
    sched = faults.observing()
    for _ in range(3):
        sched.hit("merkle.dispatch")
    assert sched.calls == {"merkle.dispatch": 3}
    assert sched.fired == []
    assert sched.planned == 0 and sched.fully_fired()


def test_check_is_noop_when_disarmed():
    faults.check("forkchoice.head")     # must not raise, no schedule


def test_injected_arming_is_not_reentrant():
    with faults.injected(faults.observing()):
        with pytest.raises(RuntimeError):
            with faults.injected(faults.observing()):
                pass
    assert faults.active() is None


def test_injected_fault_escapes_exception_catchalls():
    """The design point: ``except Exception`` cannot eat an injected
    fault, only the dedicated engine handlers may."""
    assert not issubclass(faults.InjectedFault, Exception)
    with pytest.raises(faults.InjectedFault):
        try:
            raise faults.InjectedFault("bls.flush", 1)
        except Exception:      # noqa: R702 — proving the escape
            pytest.fail("catch-all swallowed an InjectedFault")


def test_harness_site_map_covers_fault_vocabulary():
    assert set(harness.SITE_COUNTER) == set(faults.SITES)


# ---------------------------------------------------------------------------
# scenario catalog
# ---------------------------------------------------------------------------

def test_every_catalog_shape_builds_jsonable_scripts(spec):
    for name in scenarios.NAMES:
        s = scenarios.build(7, _epoch(spec), 64, name=name)
        assert s.name == name and s.script, name
        # pure data: the artifact format and the shrinker depend on it
        assert json.loads(json.dumps(s.script)) == s.script, name


def test_same_seed_same_script(spec):
    a = scenarios.build(123, _epoch(spec), 64)
    b = scenarios.build(123, _epoch(spec), 64)
    assert a.name == b.name and a.script == b.script


def test_forced_name_reproduces_weighted_draw(spec):
    """When the seed's weighted pick IS the forced name, forcing must
    not shift the entropy stream: the scripts come out identical."""
    free = scenarios.build(5, _epoch(spec), 64)
    forced = scenarios.build(5, _epoch(spec), 64, name=free.name)
    assert forced.script == free.script


def test_unknown_scenario_name_raises(spec):
    with pytest.raises(ValueError):
        scenarios.build(0, _epoch(spec), 64, name="nope")


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def test_driver_is_deterministic(spec):
    script = _short_script(spec)
    a = driver.execute(spec, script, 64)
    b = driver.execute(spec, script, 64)
    assert a.digest() == b.digest()
    assert a.accepted > 0


def test_driver_advances_and_finalizes(spec):
    """A healthy 4-epoch chain must march finality — the baseline the
    hostile scenarios deviate from."""
    result = driver.execute(spec, _short_script(spec, epochs=4), 64)
    assert result.slots >= 4 * _epoch(spec)
    assert result.finalized[0] >= 1
    assert result.rejected == 0


def test_driver_rejects_adversarial_garbage_deterministically(spec):
    """Unknown ops and impossible steps are recorded as rejections, and
    the rejection pattern is part of the replay-equality surface."""
    script = [{"op": "tick"},
              {"op": "warp_drive"},                     # unknown op
              {"op": "attester_slashing"},              # no evidence
              {"op": "block", "tip": "head", "att_slots": 1, "frac": 1.0}]
    a = driver.execute(spec, script, 64)
    assert a.statuses.count("rejected") == 2
    assert driver.execute(spec, script, 64).digest() == a.digest()


def test_driver_equivocating_siblings_queue_proposer_evidence(spec):
    """Two different blocks signed by one proposer at one slot must
    queue ProposerSlashing evidence, deliverable via include_evidence."""
    epoch = _epoch(spec)
    script = []
    for _ in range(epoch):
        script.append({"op": "tick"})
        script.append({"op": "block", "tip": "head", "att_slots": 1,
                       "frac": 1.0, "set": "base"})
    script.append({"op": "tick"})
    script.append({"op": "block", "tip": "base", "set": "a",
                   "att_slots": 1, "frac": 0.6, "graffiti": 1})
    script.append({"op": "block", "tip": "base", "set": "b",
                   "att_slots": 1, "frac": 0.6, "graffiti": 2})
    sim = driver.ChainSim(spec, 64)
    sim.run(script)
    assert len(sim.proposer_evidence) == 1
    ev = sim.proposer_evidence[0]
    assert ev.signed_header_1.message.slot \
        == ev.signed_header_2.message.slot


def test_driver_double_vote_queues_slashable_evidence(spec):
    epoch = _epoch(spec)
    script = []
    for _ in range(epoch):
        script.append({"op": "tick"})
        script.append({"op": "block", "tip": "head", "att_slots": 1,
                       "frac": 1.0, "set": "base"})
    script.append({"op": "tick"})
    script.append({"op": "block", "tip": "base", "set": "a",
                   "att_slots": 1, "frac": 0.5, "graffiti": 1})
    script.append({"op": "block", "tip": "base", "set": "b",
                   "att_slots": 1, "frac": 0.5, "graffiti": 2})
    script.append({"op": "double_vote", "tip_a": "a", "tip_b": "b",
                   "frac": 0.5})
    sim = driver.ChainSim(spec, 64)
    sim.run(script)
    assert len(sim.evidence) == 1
    ind = sim.evidence[0].attestation_1.attesting_indices
    assert len(ind) > 0


def test_driver_offline_validators_never_attest(spec):
    """The inactivity-leak primitive: offline indices drop out of every
    participant set, shrinking FFG weight below finality."""
    epoch = _epoch(spec)
    offline = list(range(32))           # half of 64: no 2/3 majority
    script = [{"op": "offline", "indices": offline}]
    for _ in range(4 * epoch):
        script.append({"op": "tick"})
        script.append({"op": "block", "tip": "head", "att_slots": 2,
                       "frac": 1.0})
    result = driver.execute(spec, script, 64)
    assert result.finalized[0] == 0     # justification stalled


# ---------------------------------------------------------------------------
# harness legs
# ---------------------------------------------------------------------------

def test_baseline_census_sees_engine_sites(spec):
    scenario = _scenario(spec, _short_script(spec))
    _, census = harness.run_baseline(spec, scenario)
    for site in ("epoch.rewards_and_penalties", "epoch.slashings",
                 "forkchoice.head", "merkle.dispatch",
                 "state_arrays.commit"):
        assert census.get(site, 0) > 0, f"census missed {site}"


def test_injected_leg_is_byte_identical_and_counted(spec):
    scenario = _scenario(spec, _short_script(spec))
    baseline, census = harness.run_baseline(spec, scenario)
    with counting() as delta:
        harness.run_injected(spec, scenario, baseline,
                             "epoch.rewards_and_penalties", 1)
    assert delta["epoch.fallbacks{reason=injected}"] == 1
    assert delta["epoch.fallbacks{reason=guard}"] == 0


def test_injected_leg_every_site_the_census_sees(spec):
    """Ordinal-1 injection at each exercised site: the full
    per-engine-fallback matrix in one test."""
    scenario = _scenario(spec, _short_script(spec))
    baseline, census = harness.run_baseline(spec, scenario)
    exercised = [s for s in faults.SITES if census.get(s, 0) > 0]
    assert len(exercised) >= 5
    for site in exercised:
        harness.run_injected(spec, scenario, baseline, site, 1)


def test_storm_leg_all_sites_at_once(spec):
    scenario = _scenario(spec, _short_script(spec))
    baseline, census = harness.run_baseline(spec, scenario)
    harness.run_storm(spec, scenario, baseline, census)


def test_spec_differential_leg(spec):
    scenario = _scenario(spec, _short_script(spec))
    baseline, _ = harness.run_baseline(spec, scenario)
    harness.run_spec_differential(spec, scenario, baseline)


def test_no_discharge_is_detected(spec):
    """An ordinal past the scenario's call count never fires: the leg
    must fail loudly instead of passing vacuously."""
    scenario = _scenario(spec, _short_script(spec))
    baseline, census = harness.run_baseline(spec, scenario)
    beyond = census["epoch.slashings"] + 100
    with pytest.raises(harness.LegFailure) as exc:
        harness.run_injected(spec, scenario, baseline,
                             "epoch.slashings", beyond)
    assert exc.value.category == "no-discharge"


def test_silent_fallback_is_detected(spec, monkeypatch):
    """Simulate the failure mode the harness exists to catch: a handler
    that absorbs the fault without counting it."""
    scenario = _scenario(spec, _short_script(spec))
    baseline, _ = harness.run_baseline(spec, scenario)
    monkeypatch.setattr(
        faults, "count_fallback",
        lambda series, exc=None, organic="guard", site=None: None)
    with pytest.raises(harness.LegFailure) as exc:
        harness.run_injected(spec, scenario, baseline,
                             "epoch.rewards_and_penalties", 1)
    assert exc.value.category == "silent-fallback"
    assert "SILENT FALLBACK" in str(exc.value)


def test_organic_leak_is_detected(spec, monkeypatch):
    """An injected trip miscounted under the organic reason must not
    hide in the guard noise."""
    scenario = _scenario(spec, _short_script(spec))
    baseline, _ = harness.run_baseline(spec, scenario)
    real = faults.count_fallback
    monkeypatch.setattr(
        faults, "count_fallback",
        lambda series, exc=None, organic="guard", site=None:
        real(series, None, organic=organic, site=site))
    with pytest.raises(harness.LegFailure) as exc:
        harness.run_injected(spec, scenario, baseline,
                             "epoch.rewards_and_penalties", 1)
    assert exc.value.category in ("silent-fallback", "organic-leak")


def test_organic_fallbacks_in_baseline_are_tolerated(spec, monkeypatch):
    """The organic-leak check is baseline-relative: a scenario whose
    replay organically trips a guard (identically in every leg — the
    script is pure data) must not fail its injected legs with a false
    organic-leak."""
    from consensus_specs_tpu.obs import registry
    guard = registry.counter("epoch.fallbacks").labels(reason="guard")
    real_leg = harness.run_leg

    def leg_with_organic_trip(*a, **kw):
        guard.add()
        return real_leg(*a, **kw)

    monkeypatch.setattr(harness, "run_leg", leg_with_organic_trip)
    scenario = _scenario(spec, _short_script(spec))
    baseline, _ = harness.run_baseline(spec, scenario)
    assert baseline.organic["epoch.fallbacks{reason=guard}"] == 1
    # must not raise: the injected leg sees the same one organic trip
    harness.run_injected(spec, scenario, baseline,
                         "epoch.rewards_and_penalties", 1)
    # an EXTRA organic bump beyond the baseline's still trips the check
    monkeypatch.setattr(
        harness, "run_leg",
        lambda *a, **kw: (guard.add(), leg_with_organic_trip(*a, **kw))[1])
    with pytest.raises(harness.LegFailure) as exc:
        harness.run_injected(spec, scenario, baseline,
                             "epoch.rewards_and_penalties", 1)
    assert exc.value.category == "organic-leak"


def test_divergence_is_detected(spec):
    """A doctored baseline digest must trip the byte-identity check."""
    scenario = _scenario(spec, _short_script(spec))
    baseline, _ = harness.run_baseline(spec, scenario)
    baseline.head = b"\x00" * 32        # corrupt the reference digest
    with pytest.raises(harness.LegFailure) as exc:
        harness.run_injected(spec, scenario, baseline,
                             "epoch.rewards_and_penalties", 1)
    assert exc.value.category == "diverged"


def test_draw_injections_covers_exercised_sites():
    import random
    census = {"epoch.slashings": 4, "forkchoice.head": 10,
              "bls.flush": 0}
    picks = harness.draw_injections(random.Random(0), census)
    sites = [s for s, _ in picks]
    assert sorted(sites) == ["epoch.slashings", "forkchoice.head"]
    for site, ordinal in picks:
        assert 1 <= ordinal <= census[site]
    assert len(harness.draw_injections(random.Random(0), census,
                                       max_sites=1)) == 1


# ---------------------------------------------------------------------------
# repro: shrinker + artifacts
# ---------------------------------------------------------------------------

def test_shrinker_reduces_to_minimal_script():
    script = [{"op": "tick", "i": i} for i in range(40)]
    script[23] = {"op": "block", "poison": True}

    def reproduces(cand):
        return any(s.get("poison") for s in cand)

    reduced = repro.shrink_script(script, reproduces)
    assert reduced == [{"op": "block", "poison": True}]


def test_shrinker_respects_budget():
    script = [{"i": i} for i in range(64)]
    calls = []

    def reproduces(cand):
        calls.append(1)
        return True

    repro.shrink_script(script, reproduces, budget=10)
    assert len(calls) <= 10


def test_shrinker_treats_predicate_crash_as_no_repro():
    script = [{"i": i} for i in range(8)]

    def reproduces(cand):
        if len(cand) < 8:
            raise RuntimeError("different failure")
        return True

    assert repro.shrink_script(script, reproduces) == script


def test_artifact_roundtrip(tmp_path, spec):
    scenario = _scenario(spec, _short_script(spec), name="steady", seed=42)
    sched = faults.FaultSchedule({"merkle.dispatch": [3]})
    try:
        for _ in range(3):
            sched.hit("merkle.dispatch")
    except faults.InjectedFault:
        pass
    path = repro.dump_artifact(scenario, "inject[merkle.dispatch@3]",
                               "unit-test failure", schedule=sched,
                               out_dir=str(tmp_path))
    loaded, triggers, payload = repro.load_artifact(path)
    assert loaded.name == "steady" and loaded.seed == 42
    assert loaded.script == scenario.script
    assert triggers == {"merkle.dispatch": [3]}
    assert payload["schedule"]["fired"] == [["merkle.dispatch", 3]]
    assert "env" in payload and "bls_backend" in payload["env"]


def test_replay_of_clean_artifact_returns_zero(tmp_path, spec,
                                               monkeypatch):
    """An artifact whose leg no longer fails replays to exit code 0,
    under the artifact's recorded spec and environment snapshot (a
    sentinel CS_TPU var recorded at dump time is applied for the replay
    and restored after)."""
    import os
    monkeypatch.setenv("CS_TPU_SIM_SENTINEL", "1")
    scenario = _scenario(spec, _short_script(spec), name="steady", seed=1)
    sched = faults.FaultSchedule({"epoch.slashings": [1]})
    path = repro.dump_artifact(scenario, "inject[epoch.slashings@1]",
                               "resolved failure", schedule=sched,
                               out_dir=str(tmp_path),
                               fork="phase0", preset="minimal")
    monkeypatch.delenv("CS_TPU_SIM_SENTINEL")
    payload = json.loads(open(path).read())
    assert payload["fork"] == "phase0" and payload["preset"] == "minimal"
    assert payload["env"]["CS_TPU_SIM_SENTINEL"] == "1"
    assert repro.replay(path) == 0
    # the snapshot was applied for the replay only, then restored
    assert "CS_TPU_SIM_SENTINEL" not in os.environ


def test_artifact_names_are_per_leg(tmp_path, spec):
    """One seed can fail several legs in a sweep round; each failure
    keeps its own artifact file."""
    scenario = _scenario(spec, _short_script(spec), name="steady", seed=2)
    p1 = repro.dump_artifact(scenario, "inject[merkle.dispatch@1]", "a",
                             out_dir=str(tmp_path))
    p2 = repro.dump_artifact(scenario, "storm", "b", out_dir=str(tmp_path))
    p3 = repro.dump_artifact(scenario, "spec-differential", "c",
                             out_dir=str(tmp_path))
    assert len({p1, p2, p3}) == 3


def test_minimize_failure_dumps_reduced_artifact(spec, monkeypatch,
                                                 tmp_path):
    """End-to-end failure workflow: a silent fallback (simulated) is
    minimized by the shrinker and dumped as a replayable artifact."""
    monkeypatch.setenv("CS_TPU_SIM_ARTIFACTS", str(tmp_path))
    scenario = _scenario(spec, _short_script(spec), name="steady", seed=9)
    baseline, _ = harness.run_baseline(spec, scenario)
    monkeypatch.setattr(
        faults, "count_fallback",
        lambda series, exc=None, organic="guard", site=None: None)
    with pytest.raises(harness.LegFailure) as exc:
        harness.run_injected(spec, scenario, baseline,
                             "epoch.rewards_and_penalties", 1)
    path = harness.minimize_failure(spec, exc.value, budget=12)
    payload = json.loads(open(path).read())
    assert payload["failure"]["kind"] == \
        "inject[epoch.rewards_and_penalties@1]"
    # the shrinker ran under its budget and never grew the script
    assert len(payload["script"]) <= payload["original_steps"]


def test_replay_of_storm_artifact_arms_the_full_storm(tmp_path, spec,
                                                      monkeypatch):
    """A storm artifact records a multi-site schedule; replay must
    re-run it as ONE storm leg (cross-site interaction preserved), not
    as a sequence of single-trigger legs that would each pass."""
    scenario = _scenario(spec, _short_script(spec), name="steady", seed=3)
    sched = faults.FaultSchedule({"epoch.slashings": [1],
                                  "merkle.dispatch": [1]})
    path = repro.dump_artifact(scenario, "storm", "storm failure",
                               schedule=sched, out_dir=str(tmp_path),
                               fork="phase0", preset="minimal")

    def storm_reproduces(spec_, scenario_, baseline_, census_):
        raise harness.LegFailure("storm", scenario_, "still diverges",
                                 category="diverged")

    def no_single_triggers(*a, **kw):
        raise RuntimeError("storm replay must not split into "
                           "single-trigger legs")

    monkeypatch.setattr(harness, "run_storm", storm_reproduces)
    monkeypatch.setattr(harness, "run_injected", no_single_triggers)
    assert repro.replay(path) == 1


def test_sweep_contains_leg_crashes(tmp_path, spec, monkeypatch, capsys):
    """A non-LegFailure crash inside an injected/storm/differential leg
    is contained as a category=crashed failure (artifact dumped, sweep
    exits 1) instead of aborting the sweep and discarding the failures
    already collected."""
    import argparse
    from consensus_specs_tpu.sim import sweep

    monkeypatch.setattr(
        harness, "run_spec_differential",
        lambda *a, **kw: (_ for _ in ()).throw(
            TypeError("spec loop exploded")))
    args = argparse.Namespace(
        seeds=2, start=0, fork="phase0", preset="minimal",
        inject_every=1000, max_sites=1, diff_every=1, bls_seeds=0,
        breaker_every=0, corrupt_every=0,
        min_scenarios=2, artifact_dir=str(tmp_path), shrink_budget=2,
        time_budget=None)
    code = sweep.run_sweep(args)
    out = capsys.readouterr().out
    assert code == 1
    # both baselines still completed despite every diff leg crashing
    assert "2 scenarios" in out
    names = sorted(p.name for p in tmp_path.iterdir())
    assert len(names) == 2 and all("spec-differential" in n
                                   for n in names)


# ---------------------------------------------------------------------------
# supervisor legs: breaker lifecycle + sentinel-audit corruption
# ---------------------------------------------------------------------------

def test_breaker_storm_leg_lifecycle(spec):
    """The acceptance storm: threshold-1 faults at every exercised site
    open every breaker (counter census), the run stays byte-identical,
    and the healing replay re-closes every breaker via probes."""
    from consensus_specs_tpu import supervisor
    scenario = _scenario(spec, _short_script(spec))
    baseline, census = harness.run_baseline(spec, scenario)
    assert not any(baseline.organic.values())
    result = harness.run_breaker_storm(spec, scenario, baseline, census)
    assert result is not None
    assert result.digest() == baseline.digest()
    assert all(st == "closed" for st in supervisor.states().values())


def test_breaker_storm_skips_organic_scenarios(spec):
    scenario = _scenario(spec, _short_script(spec))
    baseline, census = harness.run_baseline(spec, scenario)
    baseline.organic = {k: 1 for k in baseline.organic} or {"x": 1}
    assert harness.run_breaker_storm(spec, scenario, baseline,
                                     census) is None


def test_breaker_storm_detects_missing_breaker(spec, monkeypatch):
    """A supervisor that never opens (simulated: the count_fallback ->
    breaker hook lost) is a loud no-breaker failure, not a vacuous
    green."""
    scenario = _scenario(spec, _short_script(spec))
    baseline, census = harness.run_baseline(spec, scenario)
    monkeypatch.setattr(faults, "_failure_hook",
                        lambda site, reason="guard": None)
    with pytest.raises(harness.LegFailure) as exc:
        harness.run_breaker_storm(spec, scenario, baseline, census)
    assert exc.value.category == "no-breaker"


def test_corrupt_leg_quarantines_and_stays_identical(spec, tmp_path):
    """The acceptance corruption: a silently-wrong merkle dispatch is
    caught by the rate-1 sentinel, quarantined, dumped as an artifact,
    and the digest never sees the corruption."""
    scenario = _scenario(spec, _short_script(spec))
    baseline, census = harness.run_baseline(spec, scenario)
    site = harness.pick_corrupt_site(census)
    assert site == "merkle.dispatch"
    result, path = harness.run_corrupt(spec, scenario, baseline, site,
                                       out_dir=str(tmp_path))
    assert result.digest() == baseline.digest()
    payload = json.loads(open(path).read())
    assert payload["schedule"]["corrupt"] == {site: 1}
    assert payload["schedule"]["corrupted"]
    assert payload["failure"]["kind"] == f"audit[{site}]"
    assert payload["env"]["CS_TPU_AUDIT_RATE"] == "1"


def test_corrupt_leg_detects_missed_audit(spec, monkeypatch, tmp_path):
    """An audit layer that never samples (simulated: audit_due False)
    lets the corruption ride — the leg must fail silent-fallback, not
    pass vacuously."""
    from consensus_specs_tpu import supervisor
    scenario = _scenario(spec, _short_script(spec))
    baseline, census = harness.run_baseline(spec, scenario)
    monkeypatch.setattr(supervisor, "audit_due", lambda site: False)
    with pytest.raises(harness.LegFailure) as exc:
        harness.run_corrupt(spec, scenario, baseline, "merkle.dispatch",
                            out_dir=str(tmp_path))
    assert exc.value.category in ("silent-fallback", "diverged")


def test_corrupt_artifact_replays(spec, tmp_path, monkeypatch):
    """repro.replay on a quarantine artifact re-arms the corruption and
    reproduces the catch (exit 1 + the site quarantined again)."""
    monkeypatch.setenv("CS_TPU_SIM_ARTIFACTS", str(tmp_path))
    scenario = _scenario(spec, _short_script(spec))
    baseline, census = harness.run_baseline(spec, scenario)
    _, path = harness.run_corrupt(spec, scenario, baseline,
                                  "merkle.dispatch",
                                  out_dir=str(tmp_path), fork="phase0",
                                  preset="minimal")
    assert repro.replay(path) == 1


def test_run_leg_resets_supervisor_per_leg(spec):
    """Leg isolation: breaker state from one leg must not demote an
    engine in the next (the PR 8 legs replay cold)."""
    from consensus_specs_tpu import supervisor
    scenario = _scenario(spec, _short_script(spec))
    with supervisor.quarantine_hook(lambda s, d: None):
        supervisor.quarantine("merkle.dispatch", "leftover")
    with counting() as delta:
        harness.run_leg(spec, scenario)
    assert supervisor.states()["merkle.dispatch"] == "closed"
    assert delta["supervisor.breaker.skips{site=merkle.dispatch}"] == 0


def test_fault_schedule_loss_ordinals_fire_once():
    """Device-loss ordinals are CONSUMED on fire: the handler's
    elastic re-dispatch of the same call must not re-lose a device
    (or the mesh would drain one device per retry)."""
    sched = faults.FaultSchedule(loss={"mesh.epoch": [2]})
    with faults.injected(sched):
        faults.check("mesh.epoch")              # call 1
        assert not faults.loss_armed("mesh.epoch")
        faults.check("mesh.epoch")              # call 2: scheduled
        assert faults.loss_armed("mesh.epoch")
        assert not faults.loss_armed("mesh.epoch")   # consumed
    assert sched.losses_fired()
    assert sched.lost == [("mesh.epoch", 2)]
    # disarmed: the hook answers False at one-global-read cost
    assert not faults.loss_armed("mesh.epoch")
