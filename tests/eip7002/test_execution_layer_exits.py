"""EIP-7002 execution-layer exit tests.

Reference model: ``test/eip7002/block_processing/
test_process_execution_layer_exit.py`` against
``specs/_features/eip7002/beacon-chain.md:223``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases,
)


def _set_eth1_credentials(spec, state, index, address=b"\x42" * 20):
    state.validators[index].withdrawal_credentials = (
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + address)
    return address


def _age_validator(spec, state, index):
    state.validators[index].activation_epoch = 0
    state.slot = spec.SLOTS_PER_EPOCH * (
        spec.config.SHARD_COMMITTEE_PERIOD + 1)


@with_phases(["eip7002"])
@spec_state_test
def test_exit_success(spec, state):
    index = 0
    address = _set_eth1_credentials(spec, state, index)
    _age_validator(spec, state, index)
    exit_op = spec.ExecutionLayerExit(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey)
    yield "pre", state
    spec.process_execution_layer_exit(state, exit_op)
    yield "post", state
    assert state.validators[index].exit_epoch < spec.FAR_FUTURE_EPOCH


@with_phases(["eip7002"])
@spec_state_test
def test_exit_wrong_source_address_noop(spec, state):
    index = 0
    _set_eth1_credentials(spec, state, index)
    _age_validator(spec, state, index)
    exit_op = spec.ExecutionLayerExit(
        source_address=b"\x99" * 20,
        validator_pubkey=state.validators[index].pubkey)
    spec.process_execution_layer_exit(state, exit_op)
    assert state.validators[index].exit_epoch == spec.FAR_FUTURE_EPOCH


@with_phases(["eip7002"])
@spec_state_test
def test_exit_bls_credentials_noop(spec, state):
    """A validator still on BLS withdrawal credentials cannot be exited
    from the execution layer."""
    index = 0
    _age_validator(spec, state, index)
    exit_op = spec.ExecutionLayerExit(
        source_address=b"\x42" * 20,
        validator_pubkey=state.validators[index].pubkey)
    spec.process_execution_layer_exit(state, exit_op)
    assert state.validators[index].exit_epoch == spec.FAR_FUTURE_EPOCH


@with_phases(["eip7002"])
@spec_state_test
def test_exit_too_young_noop(spec, state):
    index = 0
    address = _set_eth1_credentials(spec, state, index)
    # not aged: SHARD_COMMITTEE_PERIOD has not passed
    exit_op = spec.ExecutionLayerExit(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey)
    spec.process_execution_layer_exit(state, exit_op)
    assert state.validators[index].exit_epoch == spec.FAR_FUTURE_EPOCH


@with_phases(["eip7002"])
@spec_state_test
def test_exit_already_initiated_noop(spec, state):
    index = 0
    address = _set_eth1_credentials(spec, state, index)
    _age_validator(spec, state, index)
    spec.initiate_validator_exit(state, index)
    first_exit_epoch = state.validators[index].exit_epoch
    exit_op = spec.ExecutionLayerExit(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey)
    spec.process_execution_layer_exit(state, exit_op)
    assert state.validators[index].exit_epoch == first_exit_epoch


@with_phases(["eip7002"])
@spec_state_test
def test_exit_unknown_pubkey_invalid(spec, state):
    """A request naming a pubkey outside the registry invalidates the
    block (the registry lookup raises), unlike the credential no-ops."""
    from consensus_specs_tpu.test_infra.keys import pubkeys
    exit_op = spec.ExecutionLayerExit(
        source_address=b"\x42" * 20,
        validator_pubkey=pubkeys[len(state.validators) + 5])
    try:
        spec.process_execution_layer_exit(state, exit_op)
    except ValueError:
        pass
    else:
        raise AssertionError("unknown pubkey must invalidate the block")


@with_phases(["eip7002"])
@spec_state_test
def test_exit_second_request_noop(spec, state):
    """A second request for an already-exiting validator changes nothing
    (exit_epoch pinned by the first)."""
    index = 0
    address = _set_eth1_credentials(spec, state, index)
    _age_validator(spec, state, index)
    exit_op = spec.ExecutionLayerExit(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey)
    spec.process_execution_layer_exit(state, exit_op)
    first_exit_epoch = state.validators[index].exit_epoch
    assert first_exit_epoch < spec.FAR_FUTURE_EPOCH
    spec.process_execution_layer_exit(state, exit_op)
    assert state.validators[index].exit_epoch == first_exit_epoch


@with_phases(["eip7002"])
@spec_state_test
def test_exit_sets_withdrawable_epoch(spec, state):
    """initiate_validator_exit pins withdrawable_epoch = exit_epoch +
    MIN_VALIDATOR_WITHDRAWABILITY_DELAY (phase0 semantics carried over)."""
    index = 0
    address = _set_eth1_credentials(spec, state, index)
    _age_validator(spec, state, index)
    spec.process_execution_layer_exit(state, spec.ExecutionLayerExit(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey))
    v = state.validators[index]
    assert v.withdrawable_epoch == \
        v.exit_epoch + spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY


@with_phases(["eip7002"])
@spec_state_test
def test_btec_then_el_exit_same_block(spec, state):
    """A BLSToExecutionChange rotating to 0x01 credentials earlier in
    the block enables an EL exit later in the SAME block (operations
    process in body order: btec before payload exits)."""
    from consensus_specs_tpu.test_infra.keys import pubkeys, privkeys
    from consensus_specs_tpu.utils.hash_function import hash as H
    from consensus_specs_tpu.utils import bls
    index = 0
    _age_validator(spec, state, index)
    # start on BLS credentials derived from a known withdrawal key
    wd_pubkey = pubkeys[index + 100]
    state.validators[index].withdrawal_credentials = \
        spec.BLS_WITHDRAWAL_PREFIX + H(wd_pubkey)[1:]
    address = b"\x42" * 20
    change = spec.BLSToExecutionChange(
        validator_index=index,
        from_bls_pubkey=wd_pubkey,
        to_execution_address=address)
    domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        genesis_validators_root=state.genesis_validators_root)
    from consensus_specs_tpu.utils.ssz import hash_tree_root
    signing_root = hash_tree_root(spec.SigningData(
        object_root=hash_tree_root(change), domain=domain))
    signed_change = spec.SignedBLSToExecutionChange(
        message=change,
        signature=bls.Sign(privkeys[index + 100], signing_root))
    yield "pre", state
    spec.process_bls_to_execution_change(state, signed_change)
    exit_op = spec.ExecutionLayerExit(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey)
    spec.process_execution_layer_exit(state, exit_op)
    yield "post", state
    assert state.validators[index].exit_epoch < spec.FAR_FUTURE_EPOCH


@with_phases(["eip7002"])
@spec_state_test
def test_cl_exit_op_then_el_exit_noop(spec, state):
    """A voluntary exit OPERATION (the CL path, process_voluntary_exit)
    processed first makes the EL exit for the same validator a no-op —
    distinct from test_exit_already_initiated_noop, which initiates the
    exit directly: this exercises the real cross-channel interplay."""
    from consensus_specs_tpu.test_infra.voluntary_exits import (
        prepare_signed_exits)
    index = 0
    address = _set_eth1_credentials(spec, state, index)
    _age_validator(spec, state, index)
    signed_exit = prepare_signed_exits(spec, state, [index])[0]
    yield "pre", state
    spec.process_voluntary_exit(state, signed_exit)
    first_epoch = state.validators[index].exit_epoch
    assert first_epoch < spec.FAR_FUTURE_EPOCH
    exit_op = spec.ExecutionLayerExit(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey)
    spec.process_execution_layer_exit(state, exit_op)
    yield "post", state
    assert state.validators[index].exit_epoch == first_epoch
