"""EIP-7594 (PeerDAS) fork + sampling-surface tests.

Reference model: ``specs/_features/eip7594/fork.md`` (upgrade, version
ladder) and ``test/eip7594/unittests`` (sampling surface, exercised here
through the spec object rather than the bare library - the library
itself is differential-tested in ``tests/deneb/kzg/test_kzg_7594.py``).
"""
import os

import pytest

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases,
)

# The preset trusted setup is 4096 field elements -> 128 cells per blob;
# multiproof computation over it is a host-Pippenger MSM per cell, which
# belongs in the gated crypto tier (the small-setup library versions of
# these paths run in tests/deneb/kzg/test_kzg_7594.py).
HEAVY = os.environ.get("CS_TPU_HEAVY") == "1"


@with_phases(["eip7594"])
@spec_state_test
def test_upgrade_rotates_fork_version_only(spec, state):
    pre = spec.BeaconState.decode_bytes(state.serialize())
    post = spec.upgrade_to_eip7594(pre)
    assert post.fork.current_version == spec.config.EIP7594_FORK_VERSION
    assert post.fork.previous_version == pre.fork.current_version
    # data-availability fork: every other field byte-identical
    post.fork = pre.fork
    assert post.serialize() == pre.serialize()


@with_phases(["eip7594"])
@spec_state_test
def test_fork_version_ladder(spec, state):
    cfg = spec.config
    assert spec.compute_fork_version(cfg.EIP7594_FORK_EPOCH) == \
        cfg.EIP7594_FORK_VERSION
    if cfg.DENEB_FORK_EPOCH < cfg.EIP7594_FORK_EPOCH:
        assert spec.compute_fork_version(cfg.DENEB_FORK_EPOCH) == \
            cfg.DENEB_FORK_VERSION
    yield  # part-less


@pytest.mark.skipif(not HEAVY, reason="set CS_TPU_HEAVY=1 (full-size setup)")
@with_phases(["eip7594"])
@spec_state_test
def test_cells_roundtrip_through_spec_surface(spec, state):
    """compute_cells -> drop half -> recover_polynomial round-trips."""
    import random
    rng = random.Random(7594)
    n = spec.FIELD_ELEMENTS_PER_BLOB
    blob = b"".join(
        rng.randrange(spec.BLS_MODULUS).to_bytes(32, "big")
        for _ in range(int(n)))
    cells = spec.compute_cells(blob)
    k = len(cells)
    # any half of the extended cells recovers the full extended data
    keep = sorted(rng.sample(range(k), k // 2))
    cells_bytes = [
        b"".join(int(x).to_bytes(32, "big") for x in c) for c in cells]
    rec = spec.recover_polynomial(keep, [cells_bytes[i] for i in keep])
    assert rec == [x for c in cells for x in c]
    yield  # part-less


@pytest.mark.skipif(not HEAVY, reason="set CS_TPU_HEAVY=1 (full-size setup)")
@with_phases(["eip7594"])
@spec_state_test
def test_cell_proofs_verify_through_spec_surface(spec, state):
    import random
    rng = random.Random(75941)
    n = spec.FIELD_ELEMENTS_PER_BLOB
    blob = b"".join(
        rng.randrange(spec.BLS_MODULUS).to_bytes(32, "big")
        for _ in range(int(n)))
    commitment = spec.blob_to_kzg_commitment(blob)
    cells, proofs = spec.compute_cells_and_proofs(blob)
    cell_bytes = [
        b"".join(int(x).to_bytes(32, "big") for x in c) for c in cells]
    cid = rng.randrange(len(cells))
    assert spec.verify_cell_proof(commitment, cid, cell_bytes[cid],
                                  proofs[cid])
    wrong = (cid + 1) % len(cells)
    assert not spec.verify_cell_proof(commitment, wrong, cell_bytes[cid],
                                      proofs[cid])
    yield  # part-less


@with_phases(["eip7594"])
@spec_state_test
def test_is_data_available_fallback_and_stub_precedence(spec, state):
    """Without a cell-retrieval stub the deneb full-blob path answers;
    a harness-provided ``retrieve_cells_and_proofs`` takes precedence
    (fork-choice stubbing pattern, deneb fork-choice.md:70)."""
    root = b"\x07" * 32
    # no commitments: both paths are trivially available
    assert spec.is_data_available(root, [])

    calls = []

    def fake_retrieve(block_root):
        calls.append(block_root)
        return []

    spec.retrieve_cells_and_proofs = fake_retrieve
    try:
        assert spec.is_data_available(root, [])
        assert calls == [root], "cell stub must take precedence"
    finally:
        del spec.retrieve_cells_and_proofs


@with_phases(["eip7594"])
@spec_state_test
def test_is_data_available_rejects_withheld_blob(spec, state):
    """A sampling response covering fewer blobs than the block commits
    to is data withholding, never availability — the check must not
    zip-truncate to the sampled prefix."""
    root = b"\x08" * 32
    commitments = [b"\xc0" + b"\x00" * 47]  # one committed blob

    def empty_retrieve(block_root):
        return []  # no cell-sets sampled at all

    spec.retrieve_cells_and_proofs = empty_retrieve
    try:
        assert not spec.is_data_available(root, commitments)
    finally:
        del spec.retrieve_cells_and_proofs
