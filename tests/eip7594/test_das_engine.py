"""DAS engine differentials: batched verify/recovery vs the markdown
spec loop, counted fallbacks, supervision, and the pairing census.

The spec surface under test is the eip7594 fork class — under a
``--compiled`` session the SAME tests run against the markdown-compiled
ladder, so "engine vs spec-markdown loop" really is byte-compared
across both ladders.
"""
import os
import random
from contextlib import contextmanager

import pytest

from consensus_specs_tpu import faults, supervisor
from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.test_infra.metrics import counting
from consensus_specs_tpu.utils import bls


@contextmanager
def _env(**kv):
    saved = {}
    for k, v in kv.items():
        saved[k] = os.environ.get(k)
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.fixture(autouse=True)
def _force_engine_on():
    """These are engine-vs-spec differentials: each test runs its own
    on AND off legs, so the module pins the switch on even under the
    CI-wide CS_TPU_DAS=0 off-leg (the live env re-read makes the pin
    effective per call)."""
    with _env(CS_TPU_DAS="1"):
        yield


@pytest.fixture(scope="module")
def spec():
    return build_spec("eip7594", "minimal")


@pytest.fixture(scope="module")
def blob_setup(spec):
    """One blob with commitment, all cells, and multiproofs for a small
    sample of cells (proof computation is the expensive part)."""
    rng = random.Random(7594_11)
    width = int(spec.FIELD_ELEMENTS_PER_BLOB)
    blob = b"".join(rng.randrange(int(spec.BLS_MODULUS)).to_bytes(32, "big")
                    for _ in range(width))
    commitment = spec.blob_to_kzg_commitment(blob)
    cells = spec.compute_cells(blob)
    # proofs via the ops library twin (identical outputs, less wall
    # clock than the spec-shaped O(L^3) interpolation per cell)
    from consensus_specs_tpu.ops import kzg_7594 as K7
    setup = spec.kzg_setup
    coeff = K7.polynomial_eval_to_coeff(
        __import__("consensus_specs_tpu.ops.kzg", fromlist=["kzg"])
        .blob_to_polynomial(blob, width), setup)
    sample_ids = [0, 100]
    proofs = {}
    for cid in sample_ids:
        proof, ys = K7.compute_kzg_proof_multi_impl(
            coeff, K7.coset_for_cell(cid, setup), setup)
        assert ys == cells[cid]
        proofs[cid] = proof
    cell_bytes = {cid: spec.cell_to_bytes(cells[cid]) for cid in sample_ids}
    return {
        "blob": blob, "commitment": commitment, "cells": cells,
        "sample_ids": sample_ids, "proofs": proofs,
        "cell_bytes": cell_bytes,
    }


def _batch_args(bs, n=2):
    ids = bs["sample_ids"][:n]
    return ([bs["commitment"]], [0] * len(ids), list(ids),
            [bs["cell_bytes"][c] for c in ids],
            [bs["proofs"][c] for c in ids])


# ---------------------------------------------------------------------------
# Batched verification
# ---------------------------------------------------------------------------

def test_engine_batch_is_one_pairing(spec, blob_setup):
    """The whole batch folds into ONE pairing check; the spec loop pays
    one per cell (counter-asserted on the shared bls.pairings census;
    bench_das.py asserts the same census at 3-blob x 3-column shape)."""
    args = _batch_args(blob_setup, 2)
    with counting() as delta:
        assert spec.verify_cell_proof_batch(*args)
    assert delta["das.verify{path=engine}"] == 1
    assert delta["das.cells{op=verified}"] == 2
    assert delta["bls.pairings"] == 1
    with _env(CS_TPU_DAS="0"):
        with counting() as delta:
            assert spec.verify_cell_proof_batch(*args)
    assert delta["das.verify{path=spec}"] == 1
    assert delta["bls.pairings"] == 2


def test_engine_defers_into_rlc_scope(spec, blob_setup):
    """Inside an assert-style batch scope the engine's pairs fold into
    the block's single RLC pairing: zero own pairings, one at flush."""
    args = _batch_args(blob_setup, 2)
    bls.clear_verify_memo()
    with counting() as delta:
        with bls.batched_verification() as batch:
            assert spec.verify_cell_proof_batch(*args) is True
            mid = dict(delta)
            batch.assert_valid()
    assert mid.get("bls.pairings", 0) == 0
    assert delta["bls.pairings"] == 1
    assert delta["bls.flush{path=rlc}"] == 1


def test_tampered_cell_verdict_parity(spec, blob_setup):
    """A tampered evaluation fails on BOTH paths (engine fold catches
    exactly what the per-cell spec loop catches)."""
    coms, rows, cols, cells, proofs = _batch_args(blob_setup, 2)
    bad = (int.from_bytes(cells[1][:32], "big") + 1) \
        % int(spec.BLS_MODULUS)
    cells = list(cells)
    cells[1] = bad.to_bytes(32, "big") + cells[1][32:]
    assert spec.verify_cell_proof_batch(coms, rows, cols, cells,
                                        proofs) is False
    with _env(CS_TPU_DAS="0"):
        assert spec.verify_cell_proof_batch(coms, rows, cols, cells,
                                            proofs) is False


def test_wrong_column_and_wrong_proof_parity(spec, blob_setup):
    coms, rows, cols, cells, proofs = _batch_args(blob_setup, 2)
    wrong_cols = [cols[1], cols[0]]     # cells swapped across cosets
    assert spec.verify_cell_proof_batch(
        coms, rows, wrong_cols, cells, proofs) is False
    assert spec.verify_cell_proof_batch(
        coms, rows, cols, cells, list(reversed(proofs))) is False
    # spec-loop parity on the swapped-coset shape (the wrong-proof
    # shape short-circuits identically; tamper parity covers it)
    with _env(CS_TPU_DAS="0"):
        assert spec.verify_cell_proof_batch(
            coms, rows, wrong_cols, cells, proofs) is False


def test_invalid_encoding_raises_on_both_paths(spec, blob_setup):
    """Non-canonical field element in a cell: the same AssertionError
    the spec's bytes_to_cell raises, engine on or off."""
    coms, rows, cols, cells, proofs = _batch_args(blob_setup, 2)
    cells = list(cells)
    cells[0] = int(spec.BLS_MODULUS).to_bytes(32, "big") + cells[0][32:]
    for env in ({}, {"CS_TPU_DAS": "0"}):
        with _env(**env):
            with pytest.raises(AssertionError):
                spec.verify_cell_proof_batch(coms, rows, cols, cells,
                                             proofs)


def test_empty_batch_true_both_paths(spec):
    for env in ({}, {"CS_TPU_DAS": "0"}):
        with _env(**env):
            assert spec.verify_cell_proof_batch([], [], [], [], []) is True


def test_same_commitment_fold_multi_row(spec, blob_setup):
    """Cells sharing a row commitment fold into one weighted RLC term;
    a duplicated commitment row keeps the verdict and the one-pairing
    census."""
    coms, rows, cols, cells, proofs = _batch_args(blob_setup, 2)
    # the same commitment listed twice; cells spread across both rows
    with counting() as delta:
        assert spec.verify_cell_proof_batch(
            [coms[0], coms[0]], [0, 1], cols, cells, proofs)
    assert delta["bls.pairings"] == 1


# ---------------------------------------------------------------------------
# Counted fallbacks + supervision at the new sites
# ---------------------------------------------------------------------------

def test_injected_fault_counts_and_matches(spec, blob_setup):
    args = _batch_args(blob_setup, 1)
    expected = spec.verify_cell_proof_batch(*args)
    with counting() as delta:
        with faults.injected(faults.FaultSchedule(
                {"das.verify": [1]})) as schedule:
            got = spec.verify_cell_proof_batch(*args)
    assert schedule.fully_fired()
    assert got == expected
    assert delta["das.fallbacks{reason=injected}"] == 1
    assert delta["das.fallbacks{reason=guard}"] == 0
    assert delta["das.verify{path=spec}"] == 1
    assert delta["das.verify{path=engine}"] == 0


def test_injected_recover_fault_counts_and_matches(spec, blob_setup):
    n_cells = spec.cells_per_blob()
    keep = list(range(0, n_cells, 2))
    cbs = [spec.cell_to_bytes(blob_setup["cells"][i]) for i in keep]
    # ground truth is the published cells themselves (the spec-loop
    # byte-identity is proven by the fuzz test; no extra spec run here)
    expected = [x for c in blob_setup["cells"] for x in c]
    with counting() as delta:
        with faults.injected(faults.FaultSchedule(
                {"das.recover": [1]})) as schedule:
            got = spec.recover_polynomial(keep, cbs)
    assert schedule.fully_fired()
    assert got == expected
    assert delta["das.fallbacks{reason=injected}"] == 1
    assert delta["das.recover{path=spec}"] == 1


def test_deadline_trip_degrades_to_spec_loop(spec, blob_setup):
    """A mid-work deadline trip inside the batched recovery (the
    cooperative phase boundaries) becomes a counted reason=deadline
    fallback; the spec loop serves the call byte-identically."""
    n_cells = spec.cells_per_blob()
    keep = list(range(n_cells // 2))
    cbs = [spec.cell_to_bytes(blob_setup["cells"][i]) for i in keep]
    with _env(CS_TPU_DEADLINE_MS="0.0001"):
        supervisor.reset()      # re-read the deadline knob
        with counting() as delta:
            got = spec.recover_polynomial(keep, cbs)
    assert got == [x for c in blob_setup["cells"] for x in c]
    assert delta["das.fallbacks{reason=deadline}"] == 1
    assert delta["supervisor.deadline.trips{site=das.recover}"] == 1
    assert delta["das.recover{path=spec}"] == 1
    supervisor.reset()


def test_breaker_opens_and_skips_das_engine(spec, blob_setup):
    """Threshold-1 supervisor: one injected trip opens das.verify; the
    next call runs the spec path without an engine attempt (skip
    counted), and the verdict still matches."""
    args = _batch_args(blob_setup, 1)
    with _env(CS_TPU_BREAKER_THRESHOLD="1",
              CS_TPU_BREAKER_BACKOFF_MS="60000"):
        supervisor.reset()
        with faults.injected(faults.FaultSchedule({"das.verify": [1]})):
            spec.verify_cell_proof_batch(*args)
        assert supervisor.states()["das.verify"] == "open"
        with counting() as delta:
            assert spec.verify_cell_proof_batch(*args)
        assert delta["supervisor.breaker.skips{site=das.verify}"] == 1
        assert delta["das.verify{path=spec}"] == 1
    supervisor.reset()


def test_corrupt_verify_caught_by_sentinel_audit(spec, blob_setup, tmp_path):
    """Silent verdict corruption at das.verify: the rate-1 audit books a
    fail, quarantines the site, and the SPEC answer is what callers
    see."""
    args = _batch_args(blob_setup, 1)
    with _env(CS_TPU_AUDIT_RATE="1",
              CS_TPU_SIM_ARTIFACTS=str(tmp_path)):
        supervisor.reset()
        with counting() as delta:
            with faults.injected(faults.FaultSchedule(
                    corrupt={"das.verify": [1]})) as schedule:
                got = spec.verify_cell_proof_batch(*args)
        assert schedule.corrupted
        assert got is True      # spec answer authoritative
        assert delta["supervisor.audits{result=fail,site=das.verify}"] == 1
        assert delta["supervisor.quarantines{site=das.verify}"] == 1
        assert supervisor.states()["das.verify"] == "quarantined"
    supervisor.reset()


def test_corrupt_recover_caught_by_sentinel_audit(spec, blob_setup,
                                                  tmp_path):
    n_cells = spec.cells_per_blob()
    keep = list(range(n_cells // 2))
    cbs = [spec.cell_to_bytes(blob_setup["cells"][i]) for i in keep]
    with _env(CS_TPU_AUDIT_RATE="1",
              CS_TPU_SIM_ARTIFACTS=str(tmp_path)):
        supervisor.reset()
        with counting() as delta:
            with faults.injected(faults.FaultSchedule(
                    corrupt={"das.recover": [1]})) as schedule:
                got = spec.recover_polynomial(keep, cbs)
        assert schedule.corrupted
        assert delta["supervisor.audits{result=fail,site=das.recover}"] == 1
        assert supervisor.states()["das.recover"] == "quarantined"
        # the served (spec-authoritative) answer is the true data
        assert got == [x for c in blob_setup["cells"] for x in c]
    supervisor.reset()


# ---------------------------------------------------------------------------
# Recovery edge cases + fuzz
# ---------------------------------------------------------------------------

def test_corrupt_recover_with_nothing_missing_still_caught(
        spec, blob_setup, tmp_path):
    """A corrupt-armed recovery with ALL cells present must still
    really corrupt the result (position 0 — there is no missing cell
    to perturb), or the sentinel-audit legs would flag a false silent
    corruption (regression)."""
    n_cells = spec.cells_per_blob()
    keep = list(range(n_cells))
    cbs = [spec.cell_to_bytes(blob_setup["cells"][i]) for i in keep]
    with _env(CS_TPU_AUDIT_RATE="1",
              CS_TPU_SIM_ARTIFACTS=str(tmp_path)):
        supervisor.reset()
        with counting() as delta:
            with faults.injected(faults.FaultSchedule(
                    corrupt={"das.recover": [1]})) as schedule:
                got = spec.recover_polynomial(keep, cbs)
        assert schedule.corrupted
        assert delta["supervisor.audits{result=fail,site=das.recover}"] == 1
        assert got == [x for c in blob_setup["cells"] for x in c]
    supervisor.reset()


def test_recover_exactly_half_boundary(spec, blob_setup):
    """Exactly CELLS_PER_BLOB/2 present succeeds on both paths,
    byte-identically."""
    n_cells = spec.cells_per_blob()
    keep = sorted(random.Random(1).sample(range(n_cells), n_cells // 2))
    cbs = [spec.cell_to_bytes(blob_setup["cells"][i]) for i in keep]
    full = [x for c in blob_setup["cells"] for x in c]
    got_engine = spec.recover_polynomial(keep, cbs)
    with _env(CS_TPU_DAS="0"):
        got_spec = spec.recover_polynomial(keep, cbs)
    assert got_engine == got_spec == full


def test_recover_one_short_fails_loud(spec, blob_setup):
    """One cell fewer than half: loud AssertionError, not garbage —
    engine on AND off."""
    n_cells = spec.cells_per_blob()
    keep = list(range(n_cells // 2 - 1))
    cbs = [spec.cell_to_bytes(blob_setup["cells"][i]) for i in keep]
    for env in ({}, {"CS_TPU_DAS": "0"}):
        with _env(**env):
            with pytest.raises(AssertionError):
                spec.recover_polynomial(keep, cbs)


def test_recover_duplicate_cell_ids_rejected(spec, blob_setup):
    n_cells = spec.cells_per_blob()
    keep = list(range(n_cells // 2))
    keep[1] = keep[0]   # duplicate id, count still n/2
    cbs = [spec.cell_to_bytes(blob_setup["cells"][i]) for i in keep]
    for env in ({}, {"CS_TPU_DAS": "0"}):
        with _env(**env):
            with pytest.raises(AssertionError):
                spec.recover_polynomial(keep, cbs)


def test_recover_randomized_missing_set_fuzz(spec, blob_setup):
    """Randomized missing sets: engine recovery byte-compared to the
    spec-markdown loop (the --compiled session runs this same fuzz
    against the compiled ladder)."""
    n_cells = spec.cells_per_blob()
    full = [x for c in blob_setup["cells"] for x in c]
    rng = random.Random(41)
    count = rng.randint(n_cells // 2, n_cells - 1)
    keep = sorted(rng.sample(range(n_cells), count))
    cbs = [spec.cell_to_bytes(blob_setup["cells"][i]) for i in keep]
    with counting() as delta:
        got_engine = spec.recover_polynomial(keep, cbs)
    assert delta["das.recover{path=engine}"] == 1
    with _env(CS_TPU_DAS="0"):
        got_spec = spec.recover_polynomial(keep, cbs)
    assert got_engine == got_spec == full
    # more seeds in the heavy tier (sim's engine-off legs fuzz this
    # same byte-identity every sweep)
    if os.environ.get("CS_TPU_HEAVY") == "1":
        for seed in (42, 43, 44):
            rng = random.Random(seed)
            keep = sorted(rng.sample(
                range(n_cells), rng.randint(n_cells // 2, n_cells - 1)))
            cbs = [spec.cell_to_bytes(blob_setup["cells"][i])
                   for i in keep]
            got_engine = spec.recover_polynomial(keep, cbs)
            with _env(CS_TPU_DAS="0"):
                assert got_engine == spec.recover_polynomial(keep, cbs) \
                    == full


def test_recover_many_shares_group_work(spec, blob_setup):
    """Multi-blob batched recovery: blobs missing the same columns
    recover in ONE engine dispatch, byte-identical to per-blob spec
    loops."""
    from consensus_specs_tpu.das import recover_many
    rng = random.Random(77)
    width = int(spec.FIELD_ELEMENTS_PER_BLOB)
    n_cells = spec.cells_per_blob()
    keep = sorted(rng.sample(range(n_cells), n_cells // 2))
    blobs = [blob_setup["blob"]]
    blobs.append(b"".join(
        rng.randrange(int(spec.BLS_MODULUS)).to_bytes(32, "big")
        for _ in range(width)))
    reqs = []
    fulls = []
    for blob in blobs:
        cells = spec.compute_cells(blob)
        fulls.append([x for c in cells for x in c])
        reqs.append((keep, [spec.cell_to_bytes(cells[i]) for i in keep]))
    with counting() as delta:
        got = recover_many(spec, reqs)
    assert delta["das.recover{path=engine}"] == 1
    assert got == fulls
    if os.environ.get("CS_TPU_HEAVY") == "1":
        with _env(CS_TPU_DAS="0"):
            assert recover_many(spec, reqs) == fulls


def test_domain_tables_content_keyed(spec):
    """Regression (speclint D1004 fix): the per-setup domain-table
    cache keys on CONTENT (blob width + the degree-L G2 monomial), not
    on id(setup) — two distinct-but-equal setup objects share one
    table, and a garbage-collected setup can never alias a fresh one
    into the wrong roots/shifts."""
    from consensus_specs_tpu.das import kernels

    class _SetupView:
        """Same content as the real setup, different object identity."""
        def __init__(self, base):
            self.FIELD_ELEMENTS_PER_BLOB = int(base.FIELD_ELEMENTS_PER_BLOB)
            self.KZG_SETUP_G2_MONOMIAL = list(base.KZG_SETUP_G2_MONOMIAL)

    base = spec.kzg_setup
    t1 = kernels.tables(base)
    t2 = kernels.tables(_SetupView(base))
    assert t1 is t2, "equal-content setups must share one table"
    # different content gets its own table (no key collision)
    half = _SetupView(base)
    half.FIELD_ELEMENTS_PER_BLOB //= 2
    assert kernels.tables(half) is not t1
    assert kernels._setup_key(base) == kernels._setup_key(_SetupView(base))


def test_limb_fft_knob_reads_through_env_flags(monkeypatch):
    """Regression (speclint D1003 fix): the CS_TPU_DAS_FFT knob is
    read through env_flags.knob — flipping it mid-process is seen."""
    from consensus_specs_tpu.das import kernels
    from consensus_specs_tpu.utils import env_flags
    monkeypatch.delenv("CS_TPU_DAS_FFT", raising=False)
    assert kernels.limb_fft_enabled() is False
    monkeypatch.setenv("CS_TPU_DAS_FFT", "limb")
    assert kernels.limb_fft_enabled() is True
    assert env_flags.knob("CS_TPU_DAS_FFT") == "limb"
    assert env_flags.knob("CS_TPU_DAS_FFT_MISSING", "d") == "d"
