"""das-core.md surface: custody columns, DataColumnSidecar
construction/verification, sampling-driven availability.

Runs against the hand-written ladder and (under ``--compiled``) the
markdown-compiled one.
"""
import random

import pytest

from consensus_specs_tpu.forks import build_spec


@pytest.fixture(scope="module")
def spec():
    return build_spec("eip7594", "minimal")


def test_custody_columns_deterministic_sorted_in_range(spec):
    cols_a = spec.get_custody_columns(2**200 + 17, 3)
    cols_b = spec.get_custody_columns(2**200 + 17, 3)
    assert cols_a == cols_b
    assert cols_a == sorted(cols_a)
    assert len(cols_a) == len(set(cols_a))
    assert all(0 <= int(c) < int(spec.NUMBER_OF_COLUMNS) for c in cols_a)
    # 3 subnets x columns-per-subnet
    per_subnet = int(spec.NUMBER_OF_COLUMNS) \
        // int(spec.DATA_COLUMN_SIDECAR_SUBNET_COUNT)
    assert len(cols_a) == 3 * per_subnet


def test_custody_columns_superset_as_count_grows(spec):
    """A node raising its custody count keeps every column it had."""
    node = 987654321
    small = set(map(int, spec.get_custody_columns(node, 1)))
    big = set(map(int, spec.get_custody_columns(node, 4)))
    assert small <= big


def test_custody_count_capped(spec):
    with pytest.raises(AssertionError):
        spec.get_custody_columns(
            1, int(spec.DATA_COLUMN_SIDECAR_SUBNET_COUNT) + 1)


def test_custody_coverage_across_nodes(spec):
    """Enough random nodes at CUSTODY_REQUIREMENT cover every column."""
    rng = random.Random(4)
    covered = set()
    for _ in range(100):
        node = rng.randrange(2**256)
        covered |= set(map(int, spec.get_custody_columns(node, 2)))
    assert covered == set(range(int(spec.NUMBER_OF_COLUMNS)))


def _sidecar(spec, n_blobs=1, index=0):
    """A structurally valid sidecar with placeholder cells/proofs (no
    crypto — structural checks only)."""
    cell = bytes(spec.BYTES_PER_CELL)
    return spec.DataColumnSidecar(
        index=index,
        column=[spec.Cell(cell)] * n_blobs,
        kzg_commitments=[spec.KZGCommitment(
            spec.G1_POINT_AT_INFINITY)] * n_blobs,
        kzg_proofs=[spec.KZGProof(spec.G1_POINT_AT_INFINITY)] * n_blobs,
        signed_block_header=spec.SignedBeaconBlockHeader(),
    )


def test_verify_data_column_sidecar_structural(spec):
    assert spec.verify_data_column_sidecar(_sidecar(spec, 2, 0))
    assert spec.verify_data_column_sidecar(
        _sidecar(spec, 1, int(spec.NUMBER_OF_COLUMNS) - 1))
    # out-of-range column index
    assert not spec.verify_data_column_sidecar(
        _sidecar(spec, 1, int(spec.NUMBER_OF_COLUMNS)))
    # empty column
    assert not spec.verify_data_column_sidecar(_sidecar(spec, 0, 0))
    # misaligned commitments
    bad = _sidecar(spec, 2, 0)
    bad.kzg_commitments = bad.kzg_commitments[:1]
    assert not spec.verify_data_column_sidecar(bad)


def test_get_data_column_sidecars_layout(spec):
    """Sidecar construction: column j of sidecar j, one cell per blob,
    commitments shared, header derived from the signed block."""
    rng = random.Random(7594_21)
    width = int(spec.FIELD_ELEMENTS_PER_BLOB)
    blob = b"".join(rng.randrange(int(spec.BLS_MODULUS)).to_bytes(32, "big")
                    for _ in range(width))
    commitment = spec.blob_to_kzg_commitment(blob)
    cells = spec.compute_cells(blob)
    # placeholder proofs: layout test, not a crypto test
    proofs = [spec.G1_POINT_AT_INFINITY] * len(cells)

    block = spec.SignedBeaconBlock()
    block.message.slot = 3
    block.message.body.blob_kzg_commitments = [commitment]
    sidecars = spec.get_data_column_sidecars(block, [(cells, proofs)])
    assert len(sidecars) == int(spec.NUMBER_OF_COLUMNS)
    for j in (0, 7, len(sidecars) - 1):
        sc = sidecars[j]
        assert int(sc.index) == j
        assert len(sc.column) == 1
        assert bytes(sc.column[0]) == spec.cell_to_bytes(cells[j])
        assert bytes(sc.kzg_commitments[0]) == bytes(commitment)
        assert spec.verify_data_column_sidecar(sc)
        assert int(sc.signed_block_header.message.slot) == 3
    from consensus_specs_tpu.utils.ssz import hash_tree_root
    assert sidecars[0].signed_block_header.message.body_root == \
        hash_tree_root(block.message.body)


def test_verify_sidecar_kzg_proofs_zero_blob_column(spec):
    """The whole-column KZG check through verify_cell_proof_batch: the
    zero blob (infinity commitment, zero cells, infinity proofs) is a
    valid multiproof family, and a tampered cell fails — engine and
    spec loop agree (real-proof columns are covered by
    test_das_engine with the same verify path)."""
    import os
    inf = spec.G1_POINT_AT_INFINITY
    sc = spec.DataColumnSidecar(
        index=3,
        column=[spec.Cell(bytes(spec.BYTES_PER_CELL))] * 2,
        kzg_commitments=[spec.KZGCommitment(inf)] * 2,
        kzg_proofs=[spec.KZGProof(inf)] * 2,
        signed_block_header=spec.SignedBeaconBlockHeader(),
    )
    assert spec.verify_data_column_sidecar_kzg_proofs(sc)
    bad = spec.DataColumnSidecar.decode_bytes(sc.serialize())
    bad.column[0] = spec.Cell(
        (1).to_bytes(32, "big") + bytes(spec.BYTES_PER_CELL - 32))
    assert not spec.verify_data_column_sidecar_kzg_proofs(bad)
    os.environ["CS_TPU_DAS"] = "0"
    try:
        assert spec.verify_data_column_sidecar_kzg_proofs(sc)
        assert not spec.verify_data_column_sidecar_kzg_proofs(bad)
    finally:
        del os.environ["CS_TPU_DAS"]


def test_is_data_available_sampling_paths(spec, blob_setup=None):
    """No stub -> deneb full-blob fallback; a stub that returns short
    means withheld -> unavailable; a stub with verifying cells ->
    available (exercised with real multiproofs in test_das_engine's
    fixtures — here the short-return and empty paths)."""
    root = b"\x07" * 32
    assert spec.is_data_available(root, [])
    commitment = spec.G1_POINT_AT_INFINITY
    try:
        spec.retrieve_cells_and_proofs = lambda r: []
        # one committed blob, zero sampled -> withheld
        assert not spec.is_data_available(root, [commitment])
        # empty sample set for the one blob: vacuous verify -> available
        spec.retrieve_cells_and_proofs = lambda r: [([], [], [])]
        assert spec.is_data_available(root, [commitment])
    finally:
        del spec.retrieve_cells_and_proofs
