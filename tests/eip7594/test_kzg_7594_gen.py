"""Generator smoke: emitted kzg_7594 vectors round-trip through the
verifier/recovery — on the ops library AND the spec surface (under
``--compiled``, the markdown-built ladder)."""
import importlib.util
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(scope="module")
def gen_cases():
    spec_path = os.path.join(_REPO, "generators", "kzg_7594", "main.py")
    spec = importlib.util.spec_from_file_location("gen_kzg_7594",
                                                  spec_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return {(c.handler_name, c.case_name): c for c in mod.make_cases()}


def _data(case):
    ((kind, payload),) = case.case_fn()
    assert kind == "data"
    return payload


def _unhex(s):
    assert s.startswith("0x")
    return bytes.fromhex(s[2:])


def test_verify_batch_vector_roundtrips_through_verifier(gen_cases):
    """One emitted verify_cell_proof_batch vector, fed back through the
    spec-surface verifier: the recorded output must reproduce."""
    from consensus_specs_tpu.forks import build_spec
    spec = build_spec("eip7594", "minimal")
    for name, expected in (("verify_batch_valid", True),
                           ("verify_batch_tampered_cell", False)):
        payload = _data(gen_cases[("verify_cell_proof_batch", name)])
        inp = payload["input"]
        assert payload["output"] is expected
        got = spec.verify_cell_proof_batch(
            [_unhex(c) for c in inp["row_commitments"]],
            inp["row_indices"], inp["column_indices"],
            [_unhex(c) for c in inp["cells"]],
            [_unhex(p) for p in inp["proofs"]])
        assert got is expected


def test_recover_vector_roundtrips(gen_cases):
    from consensus_specs_tpu.forks import build_spec
    spec = build_spec("eip7594", "minimal")
    payload = _data(gen_cases[("recover", "recover_half_missing_0")])
    inp = payload["input"]
    recovered = spec.recover_polynomial(
        inp["cell_ids"], [_unhex(c) for c in inp["cells"]])
    flat = b"".join(int(x).to_bytes(32, "big") for x in recovered)
    assert ["0x" + flat[i * 2048:(i + 1) * 2048].hex()
            for i in range(spec.cells_per_blob())] == payload["output"]


def test_compute_cells_vector_matches_spec_surface(gen_cases):
    from consensus_specs_tpu.forks import build_spec
    spec = build_spec("eip7594", "minimal")
    payload = _data(gen_cases[("compute_cells", "compute_cells_random_0")])
    cells = spec.compute_cells(_unhex(payload["input"]["blob"]))
    assert payload["output"] == [
        "0x" + spec.cell_to_bytes(c).hex() for c in cells]


def test_negative_vectors_emit_none_output(gen_cases):
    for key in (("compute_cells", "compute_cells_invalid_field_element"),
                ("recover", "recover_insufficient_cells_rejected")):
        assert _data(gen_cases[key])["output"] is None
