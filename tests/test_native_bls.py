"""Differential tests: native C BLS backend vs the python oracle.

The C library (csrc/bls12_381.c, loaded via ops/native_bls.py) plays the
reference's milagro/arkworks role (reference backend ladder
``tests/core/pyspec/eth2spec/utils/bls.py:30-53``).  Every API function
is checked against the oracle on honest inputs, malformed encodings, and
the subgroup/infinity edge cases the reference's ``bls`` vector suite
exercises; hash-to-G2 is pinned to the RFC 9380 IETF vectors.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.ops import native_bls
from consensus_specs_tpu.ops.bls12_381 import ciphersuite as py
from consensus_specs_tpu.ops.bls12_381.curve import (
    G1Point, G2Point, g1_from_compressed, G1_GENERATOR)
from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER, Fq

pytestmark = pytest.mark.skipif(
    not native_bls.available(), reason="native BLS library not built")

MSG = b"native backend differential message"
SKS = [1, 2, 3, 7, 1000, R_ORDER - 1]


@pytest.fixture(scope="module")
def fixture():
    pks = [py.SkToPk(sk) for sk in SKS]
    sigs = [py.Sign(sk, MSG) for sk in SKS]
    return pks, sigs, py.Aggregate(sigs)


def test_selftest():
    assert native_bls._lib.cbls_selftest() == 1


def test_sk_to_pk_matches_oracle():
    for sk in SKS:
        assert native_bls.SkToPk(sk) == py.SkToPk(sk)
    for bad in (0, R_ORDER, R_ORDER + 5):
        with pytest.raises(ValueError):
            native_bls.SkToPk(bad)


def test_sign_matches_oracle():
    for sk in (1, 42, R_ORDER - 1):
        for msg in (b"", b"x", MSG, b"\x00" * 100):
            assert native_bls.Sign(sk, msg) == py.Sign(sk, msg)
    with pytest.raises(ValueError):
        native_bls.Sign(0, MSG)


def test_verify_roundtrip(fixture):
    pks, sigs, _ = fixture
    assert native_bls.Verify(pks[0], MSG, sigs[0])
    assert not native_bls.Verify(pks[0], MSG + b"!", sigs[0])
    assert not native_bls.Verify(pks[1], MSG, sigs[0])
    assert not native_bls.Verify(pks[0], MSG, sigs[1])


def test_fast_aggregate_verify(fixture):
    pks, sigs, agg = fixture
    assert native_bls.FastAggregateVerify(pks, MSG, agg)
    assert not native_bls.FastAggregateVerify(pks[:-1], MSG, agg)
    assert not native_bls.FastAggregateVerify(pks, b"other", agg)
    assert not native_bls.FastAggregateVerify([], MSG, agg)
    assert native_bls.FastAggregateVerify(pks, MSG, agg) == \
        py.FastAggregateVerify(pks, MSG, agg)


def test_aggregate_verify_distinct_messages():
    msgs = [bytes([i]) * 32 for i in range(4)]
    pks = [py.SkToPk(i + 1) for i in range(4)]
    sig = py.Aggregate([py.Sign(i + 1, msgs[i]) for i in range(4)])
    assert native_bls.AggregateVerify(pks, msgs, sig)
    assert not native_bls.AggregateVerify(pks, list(reversed(msgs)), sig)
    assert not native_bls.AggregateVerify(pks, msgs[:3], sig)
    assert not native_bls.AggregateVerify([], [], sig)


def test_aggregate_matches_oracle(fixture):
    pks, sigs, agg = fixture
    assert native_bls.Aggregate(sigs) == agg
    assert native_bls.Aggregate(sigs[:1]) == py.Aggregate(sigs[:1])
    with pytest.raises(ValueError):
        native_bls.Aggregate([])


def test_aggregate_pks_matches_oracle(fixture):
    pks, _, _ = fixture
    assert native_bls.AggregatePKs(pks) == py.AggregatePKs(pks)
    with pytest.raises(ValueError):
        native_bls.AggregatePKs([])
    with pytest.raises(ValueError):
        native_bls.AggregatePKs([b"\x00" * 48])


def test_key_validate_edge_cases(fixture):
    pks, _, _ = fixture
    for pk in pks:
        assert native_bls.KeyValidate(pk) == py.KeyValidate(pk) is True
    # infinity pubkey: compressed-infinity flags, must be invalid
    inf_pk = bytes([0xC0]) + b"\x00" * 47
    assert native_bls.KeyValidate(inf_pk) == py.KeyValidate(inf_pk) is False
    # uncompressed flag bit unset
    bad_flag = bytes([pks[0][0] & 0x7F]) + pks[0][1:]
    assert native_bls.KeyValidate(bad_flag) == py.KeyValidate(bad_flag) is False
    # x >= p (non-canonical)
    big_x = bytes([0x9F]) + b"\xff" * 47
    assert native_bls.KeyValidate(big_x) == py.KeyValidate(big_x) is False
    # x not on curve: flip a byte until decompression fails in the oracle
    for b in range(256):
        cand = pks[0][:20] + bytes([b]) + pks[0][21:]
        try:
            g1_from_compressed(cand)
        except Exception:
            assert native_bls.KeyValidate(cand) is False
            break
    # wrong length
    assert native_bls.KeyValidate(b"\x01" * 47) is False


def test_non_subgroup_pubkey_rejected():
    # Build an E1 point OUTSIDE the r-subgroup: random x until on-curve,
    # then check it's not in G1 (overwhelmingly likely: cofactor > 1).
    for xi in range(1, 2000):
        x = Fq(xi)
        y2 = x * x * x + Fq(4)
        y = y2.sqrt()
        if y is None:
            continue
        pt = G1Point(x, y)
        if not pt.in_subgroup():
            enc = pt.to_compressed()
            assert py.KeyValidate(enc) is False
            assert native_bls.KeyValidate(enc) is False
            return
    pytest.fail("no non-subgroup point found in range")


def test_infinity_signature_semantics(fixture):
    pks, _, _ = fixture
    inf_sig = bytes([0xC0]) + b"\x00" * 95
    # infinity signature IS in the subgroup: decodes fine, verification
    # reduces to e(agg, H(m)) == 1 which is false for real keys
    assert native_bls.FastAggregateVerify(pks, MSG, inf_sig) == \
        py.FastAggregateVerify(pks, MSG, inf_sig) is False
    # malformed infinity encoding (sign bit set) must be rejected
    bad_inf = bytes([0xE0]) + b"\x00" * 95
    assert native_bls.FastAggregateVerify(pks, MSG, bad_inf) == \
        py.FastAggregateVerify(pks, MSG, bad_inf) is False


def test_hash_to_g2_ietf_vectors():
    # RFC 9380 G.10.2 suite vectors, same set the oracle test pins
    from tests.test_hash_to_curve import G2_VECTORS, G2_DST
    from consensus_specs_tpu.ops.bls12_381.fields import Fq2
    for msg, (x_re, x_im, y_re, y_im) in G2_VECTORS.items():
        out = native_bls.hash_to_g2_compressed(msg, G2_DST)
        expect = G2Point(Fq2(x_re, x_im), Fq2(y_re, y_im)).to_compressed()
        assert out == expect, msg


def test_hash_to_g2_matches_oracle_on_random_messages():
    from consensus_specs_tpu.ops.bls12_381.hash_to_curve import hash_to_g2
    for i in range(4):
        msg = bytes([i]) * (i * 7 + 1)
        assert native_bls.hash_to_g2_compressed(
            msg, b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
        ) == hash_to_g2(msg).to_compressed()


def test_pairing_check_compressed():
    # e([2]G1, G2) * e(-G1, [2]G2) == 1
    g1 = G1_GENERATOR
    from consensus_specs_tpu.ops.bls12_381.curve import G2_GENERATOR
    ps = [g1.double().to_compressed(), (-g1).to_compressed()]
    qs = [G2_GENERATOR.to_compressed(), G2_GENERATOR.double().to_compressed()]
    assert native_bls.pairing_check_compressed(ps, qs)
    assert not native_bls.pairing_check_compressed(ps, list(reversed(qs)))


def test_g1_msm_matches_oracle():
    pts = [G1_GENERATOR.mult(k) for k in (1, 5, 11)]
    scalars = [3, 2, 9]
    expect = G1Point.inf()
    for p, s in zip(pts, scalars):
        expect = expect + p.mult(s)
    got = native_bls.g1_msm_compressed(
        [p.to_compressed() for p in pts], scalars)
    assert got == expect.to_compressed()


def test_backend_switch_integration(fixture):
    """use_native() slots into the module switch; memo cleared on swap."""
    from consensus_specs_tpu.utils import bls
    pks, sigs, agg = fixture
    prev = bls.backend_name()
    restore = {"py": bls.use_py, "jax": bls.use_jax,
               "native": bls.use_native, "fastest": bls.use_fastest}
    try:
        bls.use_native()
        assert bls.backend_name() == "native"
        assert bls.FastAggregateVerify(pks, MSG, agg)
        assert bls.Verify(pks[0], MSG, sigs[0])
        assert not bls.Verify(pks[0], b"no", sigs[0])
        assert bls.AggregatePKs(pks) == py.AggregatePKs(pks)
    finally:
        restore.get(prev, bls.use_py)()
