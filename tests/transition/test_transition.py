"""Fork-transition tests: blocks spanning the fork boundary.

Reference model: ``test/<fork>/transition/test_transition.py`` driven by
``@with_fork_metas`` (context.py:627-664) - one scenario per adjacent
fork pair, emitted under the ``transition`` runner with the format
``tests/formats/transition/README.md`` (meta: post_fork / fork_epoch /
fork_block index / blocks_count; parts: pre, blocks_<i>, post).
"""
from consensus_specs_tpu.test_infra.context import (
    ForkMeta, with_fork_metas, AFTER_FORK_PAIRS, pytest_only,
)
from consensus_specs_tpu.test_infra.fork_transition import (
    transition_until_fork, state_transition_across_slots, do_fork,
    transition_to_next_epoch_and_append_blocks,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root

_METAS = [ForkMeta(pre, post, fork_epoch=2)
          for pre, post in AFTER_FORK_PAIRS]


def _finish(post_spec, fork_epoch, blocks, post_state):
    yield "post_fork", post_spec.fork
    yield "fork_epoch", int(fork_epoch)
    yield "blocks_count", len(blocks)
    yield "blocks", blocks
    yield "post", post_state


@with_fork_metas(_METAS)
def test_simple_transition(state, fork_epoch, spec, post_spec):
    """Empty blocks every slot from genesis through one post-fork epoch."""
    yield "pre", state
    blocks = state_transition_across_slots(
        spec, state, fork_epoch * spec.SLOTS_PER_EPOCH - 1)
    state, fork_block = do_fork(state, spec, post_spec, fork_epoch)
    blocks.append(fork_block)
    yield "fork_block", len(blocks) - 1
    transition_to_next_epoch_and_append_blocks(post_spec, state, blocks)

    assert int(state.slot) == (fork_epoch + 1) * spec.SLOTS_PER_EPOCH
    assert bytes(state.fork.current_version) == bytes(getattr(
        post_spec.config, f"{post_spec.fork.upper()}_FORK_VERSION"))
    yield from _finish(post_spec, fork_epoch, blocks, state)


@with_fork_metas(_METAS)
def test_transition_no_blocks_around_fork(state, fork_epoch, spec,
                                          post_spec):
    """Empty slots straddle the boundary: the first post-fork block comes
    half an epoch late and must build on the upgraded state."""
    yield "pre", state
    transition_until_fork(spec, state, fork_epoch)
    state, _ = do_fork(state, spec, post_spec, fork_epoch, with_block=False)
    blocks = []
    # half an epoch of empty slots, then blocks
    from consensus_specs_tpu.test_infra.block import next_slots
    next_slots(post_spec, state, int(spec.SLOTS_PER_EPOCH) // 2)
    transition_to_next_epoch_and_append_blocks(post_spec, state, blocks)
    assert len(blocks) == int(spec.SLOTS_PER_EPOCH)
    yield from _finish(post_spec, fork_epoch, blocks, state)


@with_fork_metas(_METAS)
def test_transition_preserves_registry(state, fork_epoch, spec, post_spec):
    """The upgrade must not touch validators/balances, and the post spec
    must keep producing valid epochs on the migrated state."""
    yield "pre", state
    transition_until_fork(spec, state, fork_epoch)
    # pre-spec replica of the boundary crossing: the epoch transition may
    # legitimately touch the registry; the UPGRADE itself must not
    replica = state.copy()
    spec.process_slots(replica, fork_epoch * spec.SLOTS_PER_EPOCH)
    state, _ = do_fork(state, spec, post_spec, fork_epoch, with_block=False)
    assert hash_tree_root(state.validators) == \
        hash_tree_root(replica.validators)
    assert hash_tree_root(state.balances) == hash_tree_root(replica.balances)
    blocks = []
    transition_to_next_epoch_and_append_blocks(post_spec, state, blocks)
    yield from _finish(post_spec, fork_epoch, blocks, state)


@pytest_only
@with_fork_metas(_METAS)
def test_transition_pre_spec_rejects_post_block(state, fork_epoch, spec,
                                                post_spec):
    """A first-post-fork-epoch block is invalid under the PRE spec: its
    proposer signed over the post fork version."""
    from consensus_specs_tpu.test_infra.context import expect_assertion_error
    transition_until_fork(spec, state, fork_epoch)
    pre_state_for_replay = state.copy()
    state, fork_block = do_fork(state, spec, post_spec, fork_epoch)
    if fork_block is None:
        return
    # replaying the post-fork block under the pre-fork spec must fail:
    # either the SSZ body shape or the state-root/signature check breaks
    def replay():
        replay_state = pre_state_for_replay.copy()
        spec.process_slots(replay_state, fork_block.message.slot)
        pre_block = spec.SignedBeaconBlock(
            message=spec.BeaconBlock(
                slot=fork_block.message.slot,
                proposer_index=fork_block.message.proposer_index,
                parent_root=fork_block.message.parent_root,
                state_root=fork_block.message.state_root),
            signature=fork_block.signature)
        spec.state_transition(replay_state, pre_block)
    expect_assertion_error(replay)
    yield


@with_fork_metas(_METAS)
def test_transition_attestation_from_pre_fork_included_after(
        state, fork_epoch, spec, post_spec):
    """An attestation produced under the PRE-fork spec rides a POST-fork
    block: the wire container is fork-stable and the post spec credits
    it (participation flags post-altair, pending attestations in
    phase0-shaped forks) - the reference's transition suites include
    pre-fork operations the same way."""
    from consensus_specs_tpu.test_infra.attestations import (
        get_valid_attestation)
    from consensus_specs_tpu.test_infra.block import (
        build_empty_block_for_next_slot, state_transition_and_sign_block)

    yield "pre", state
    blocks = state_transition_across_slots(
        spec, state, fork_epoch * spec.SLOTS_PER_EPOCH - 1)
    att = get_valid_attestation(spec, state, slot=state.slot, signed=True)
    state, fork_block = do_fork(state, spec, post_spec, fork_epoch)
    blocks.append(fork_block)
    yield "fork_block", len(blocks) - 1

    block = build_empty_block_for_next_slot(post_spec, state)
    block.body.attestations = type(block.body.attestations)(att)
    blocks.append(state_transition_and_sign_block(post_spec, state, block))

    assert int(state.slot) == fork_epoch * spec.SLOTS_PER_EPOCH + 1
    yield from _finish(post_spec, fork_epoch, blocks, state)


# the leak scenario needs headroom: set_state_in_leak advances
# MIN_EPOCHS_TO_INACTIVITY_PENALTY + 2 epochs before the fork may hit
_LEAK_METAS = [ForkMeta(pre, post, fork_epoch=8)
               for pre, post in AFTER_FORK_PAIRS]


@with_fork_metas(_LEAK_METAS)
def test_transition_with_leaking_pre_state(state, fork_epoch, spec,
                                           post_spec):
    """A chain in inactivity leak crosses the fork and keeps processing
    (the leak accounting moves from pending-attestation deltas to
    participation flags at altair-shaped boundaries)."""
    from consensus_specs_tpu.test_infra.rewards import set_state_in_leak
    set_state_in_leak(spec, state)
    assert spec.get_current_epoch(state) < fork_epoch
    yield "pre", state
    blocks = state_transition_across_slots(
        spec, state, fork_epoch * spec.SLOTS_PER_EPOCH - 1)
    state, fork_block = do_fork(state, spec, post_spec, fork_epoch)
    if fork_block is not None:
        blocks.append(fork_block)
    transition_to_next_epoch_and_append_blocks(post_spec, state, blocks)
    yield from _finish(post_spec, fork_epoch, blocks, state)


@with_fork_metas(_METAS)
def test_transition_with_exits_in_flight(state, fork_epoch, spec,
                                         post_spec):
    """Validators whose exits initiate PRE-fork complete their exit
    under the POST-fork spec with the same epochs."""
    current_epoch = spec.get_current_epoch(state)
    exit_epoch = fork_epoch + 2
    for index in (0, 1):
        state.validators[index].exit_epoch = exit_epoch
        state.validators[index].withdrawable_epoch = exit_epoch + \
            spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    assert current_epoch < fork_epoch
    yield "pre", state
    blocks = state_transition_across_slots(
        spec, state, fork_epoch * spec.SLOTS_PER_EPOCH - 1)
    state, fork_block = do_fork(state, spec, post_spec, fork_epoch)
    if fork_block is not None:
        blocks.append(fork_block)
    transition_to_next_epoch_and_append_blocks(post_spec, state, blocks)
    yield from _finish(post_spec, fork_epoch, blocks, state)
    for index in (0, 1):
        assert state.validators[index].exit_epoch == exit_epoch


@with_fork_metas(_METAS)
def test_transition_with_slashed_validators(state, fork_epoch, spec,
                                            post_spec):
    """Slashed flags and slashings-vector balances survive the upgrade
    byte-for-byte."""
    for index in (2, 3):
        state.validators[index].slashed = True
    state.slashings[0] = spec.Gwei(7 * 10 ** 9)
    pre_slashings = [int(s) for s in state.slashings]
    yield "pre", state
    blocks = state_transition_across_slots(
        spec, state, fork_epoch * spec.SLOTS_PER_EPOCH - 1)
    state, fork_block = do_fork(state, spec, post_spec, fork_epoch)
    if fork_block is not None:
        blocks.append(fork_block)
    yield from _finish(post_spec, fork_epoch, blocks, state)
    assert state.validators[2].slashed and state.validators[3].slashed
    assert [int(s) for s in state.slashings] == pre_slashings
