"""SSZ unit tests with hand-computed vectors.

Oracle values computed directly from the normative rules in the reference's
``ssz/simple-serialize.md`` (serialization + merkleization sections).
"""
from hashlib import sha256

import pytest

from consensus_specs_tpu.utils.ssz import (
    boolean, uint8, uint16, uint32, uint64, uint256, Bytes32, Bytes48, ByteList, Bitvector, Bitlist, Vector, List, Container, Union, serialize, hash_tree_root, deserialize, uint_to_bytes)


def h(a, b):
    return sha256(a + b).digest()


Z = b"\x00" * 32


def test_uint_serialize():
    assert serialize(uint16(0x0506)) == b"\x06\x05"
    assert serialize(uint8(0)) == b"\x00"
    assert serialize(uint64(2**64 - 1)) == b"\xff" * 8
    assert serialize(boolean(True)) == b"\x01"
    assert serialize(boolean(False)) == b"\x00"
    assert uint_to_bytes(uint32(1)) == b"\x01\x00\x00\x00"


def test_uint_bounds():
    with pytest.raises(ValueError):
        uint8(256)
    with pytest.raises(ValueError):
        uint64(-1)
    with pytest.raises(ValueError):
        uint64(2**64)
    assert uint64(2**64 - 1) == 2**64 - 1


def test_uint_htr():
    assert hash_tree_root(uint64(5)) == b"\x05" + b"\x00" * 31
    assert hash_tree_root(uint256(1)) == b"\x01" + b"\x00" * 31


def test_bytes_types():
    b32 = Bytes32(b"\x01" * 32)
    assert serialize(b32) == b"\x01" * 32
    assert hash_tree_root(b32) == b"\x01" * 32
    b48 = Bytes48(b"\x02" * 48)
    # 48 bytes -> 2 chunks (2nd padded) -> 1 hash
    assert hash_tree_root(b48) == h(b"\x02" * 32, b"\x02" * 16 + b"\x00" * 16)
    with pytest.raises(ValueError):
        Bytes32(b"\x00" * 31)


def test_bytelist():
    BL = ByteList[10]
    v = BL(b"abc")
    assert serialize(v) == b"abc"
    # limit 10 bytes -> 1 chunk; root = mix_in_length(chunk, 3)
    chunk = b"abc" + b"\x00" * 29
    assert hash_tree_root(v) == h(chunk, (3).to_bytes(32, "little"))
    assert deserialize(BL, b"abc") == v
    with pytest.raises(ValueError):
        BL(b"0123456789x")


def test_bitvector():
    BV = Bitvector[5]
    v = BV([1, 0, 1, 0, 1])
    assert serialize(v) == b"\x15"
    assert hash_tree_root(v) == b"\x15" + b"\x00" * 31
    assert deserialize(BV, b"\x15") == v
    # nonzero padding bit rejected
    with pytest.raises(ValueError):
        deserialize(BV, b"\x35")


def test_bitlist():
    BL = Bitlist[8]
    v = BL([1, 0, 1, 0, 1])
    assert serialize(v) == b"\x35"  # 0b00110101: bits 10101 + delimiter at 5
    root = h(b"\x15" + b"\x00" * 31, (5).to_bytes(32, "little"))
    assert hash_tree_root(v) == root
    assert deserialize(BL, b"\x35") == v
    # empty bitlist serializes to just the delimiter
    assert serialize(BL([])) == b"\x01"
    assert deserialize(BL, b"\x01") == BL([])
    with pytest.raises(ValueError):
        deserialize(BL, b"")
    with pytest.raises(ValueError):
        deserialize(BL, b"\x35\x00")


def test_vector_basic():
    V = Vector[uint16, 3]
    v = V([1, 2, 3])
    assert serialize(v) == b"\x01\x00\x02\x00\x03\x00"
    # 6 bytes -> 1 chunk, no hashing
    assert hash_tree_root(v) == b"\x01\x00\x02\x00\x03\x00" + b"\x00" * 26
    assert deserialize(V, serialize(v)) == v


def test_vector_composite_htr():
    V = Vector[Bytes32, 2]
    a, b = Bytes32(b"\xaa" * 32), Bytes32(b"\xbb" * 32)
    v = V([a, b])
    assert hash_tree_root(v) == h(bytes(a), bytes(b))
    V3 = Vector[Bytes32, 3]
    v3 = V3([a, b, a])
    assert hash_tree_root(v3) == h(h(bytes(a), bytes(b)), h(bytes(a), Z))


def test_list_basic_htr():
    L = List[uint64, 8]  # limit 8 uint64 = 64 bytes = 2 chunks
    v = L(1, 2, 3)
    data = b"".join(int(x).to_bytes(8, "little") for x in (1, 2, 3))
    assert serialize(v) == data
    chunk0 = data + b"\x00" * 8
    root = h(h(chunk0, Z), (3).to_bytes(32, "little"))
    assert hash_tree_root(v) == root
    assert deserialize(L, data) == v
    # empty list
    assert hash_tree_root(L()) == h(h(Z, Z), (0).to_bytes(32, "little"))


def test_list_limit():
    L = List[uint8, 3]
    with pytest.raises(ValueError):
        L(1, 2, 3, 4)
    v = L(1, 2, 3)
    with pytest.raises(ValueError):
        v.append(4)


class Checkpoint(Container):
    epoch: uint64
    root: Bytes32


def test_container_fixed():
    c = Checkpoint(epoch=3, root=b"\x07" * 32)
    assert serialize(c) == (3).to_bytes(8, "little") + b"\x07" * 32
    assert hash_tree_root(c) == h((3).to_bytes(32, "little"), b"\x07" * 32)
    assert deserialize(Checkpoint, serialize(c)) == c
    assert c.copy() == c and c.copy() is not c


class VarContainer(Container):
    a: uint16
    b: List[uint16, 4]
    c: uint8


def test_container_variable():
    v = VarContainer(a=0x0102, b=List[uint16, 4](5, 6), c=7)
    # fixed part: a (2) + offset (4) + c (1) = 7; b starts at 7
    expected = b"\x02\x01" + (7).to_bytes(4, "little") + b"\x07" + b"\x05\x00\x06\x00"
    assert serialize(v) == expected
    assert deserialize(VarContainer, expected) == v
    roots = [
        hash_tree_root(v.a), hash_tree_root(v.b), hash_tree_root(v.c)]
    assert hash_tree_root(v) == h(h(roots[0], roots[1]), h(roots[2], Z))


def test_container_field_validation():
    c = Checkpoint()
    c.epoch = 5
    assert c.epoch == 5 and isinstance(c.epoch, uint64)
    with pytest.raises(ValueError):
        c.epoch = 2**64  # overflow = invalid
    with pytest.raises(ValueError):
        c.epoch = -1
    with pytest.raises(AttributeError):
        c.bogus = 1


def test_container_root_cache_invalidation():
    c = Checkpoint(epoch=1, root=b"\x00" * 32)
    r1 = hash_tree_root(c)
    c.epoch = 2
    r2 = hash_tree_root(c)
    assert r1 != r2
    assert r2 == h((2).to_bytes(32, "little"), Z)


def test_union():
    U = Union[None, uint16, uint32]
    u0 = U(0)
    assert serialize(u0) == b"\x00"
    assert hash_tree_root(u0) == h(Z, (0).to_bytes(32, "little"))
    u1 = U(1, 0x0304)
    assert serialize(u1) == b"\x01\x04\x03"
    assert hash_tree_root(u1) == h(hash_tree_root(uint16(0x0304)), (1).to_bytes(32, "little"))
    assert deserialize(U, b"\x01\x04\x03") == u1


def test_nested_list_of_containers():
    L = List[Checkpoint, 4]
    a = Checkpoint(epoch=1, root=b"\x01" * 32)
    b = Checkpoint(epoch=2, root=b"\x02" * 32)
    v = L(a, b)
    # fixed-size elements: concatenation
    assert serialize(v) == serialize(a) + serialize(b)
    ra, rb = hash_tree_root(a), hash_tree_root(b)
    root = h(h(ra, rb), h(Z, Z))
    assert hash_tree_root(v) == h(root, (2).to_bytes(32, "little"))
    rt = deserialize(L, serialize(v))
    assert rt == v


def test_list_of_variable_elems():
    Inner = List[uint8, 3]
    L = List[Inner, 2]
    v = L(Inner(1), Inner(2, 3))
    # offsets: 2 elems -> 8 bytes of offsets; payloads at 8 and 9
    expected = (8).to_bytes(4, "little") + (9).to_bytes(4, "little") + b"\x01" + b"\x02\x03"
    assert serialize(v) == expected
    assert deserialize(L, expected) == v


def test_big_list_virtual_padding():
    # limit 2**40: root must be computable instantly via zero-subtree shortcut
    L = List[uint64, 2**40]
    v = L(42)
    root = hash_tree_root(v)
    assert isinstance(root, bytes) and len(root) == 32


def test_vector_mutation():
    V = Vector[uint64, 4]
    v = V()
    v[2] = 9
    assert list(v) == [0, 0, 9, 0]
    with pytest.raises(ValueError):
        v[0] = 2**64


def test_boolean_strictness():
    with pytest.raises(ValueError):
        boolean(2)
    with pytest.raises(ValueError):
        deserialize(boolean, b"\x02")
    assert deserialize(boolean, b"\x01") == boolean(True)


def test_variable_list_rejects_zero_first_offset():
    Inner = List[uint8, 3]
    L = List[Inner, 2]
    with pytest.raises(ValueError):
        deserialize(L, b"\x00\x00\x00\x00\xff\xff")
    assert deserialize(L, b"") == L()


def test_value_semantics_on_assignment():
    """Assignment snapshots by value; reads return live write-through views
    (remerkleable-compatible semantics the spec code relies on)."""
    class Outer(Container):
        a: Checkpoint
        b: Checkpoint

    o = Outer()
    o.a.epoch = 5          # read returns live view; mutation writes through
    assert o.a.epoch == 5
    o.b = o.a              # assignment snapshots
    o.a.epoch = 9
    assert o.b.epoch == 5 and o.a.epoch == 9

    L = List[Checkpoint, 4]
    lst = L()
    c = Checkpoint(epoch=1)
    lst.append(c)
    c.epoch = 7            # must not affect the appended snapshot
    assert lst[0].epoch == 1
    lst[0].epoch = 3       # live element view writes through
    assert lst[0].epoch == 3
