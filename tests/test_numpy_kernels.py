"""The numpy mirror of the limb kernels (CS_TPU_NUMPY_KERNELS=1).

The same kernel source (``ops/jax_bls``) executes on numpy arrays with
python-shim control flow (``ops/jax_bls/backend.py``).  This mode backs
the multichip dryrun's hybrid fallback on hosts where XLA:CPU cannot
compile the staged pipeline inside the driver budget, so its
correctness IS a driver-facing guarantee.  The switch is import-time,
hence the subprocess.
"""
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHECK = r"""
import sys
sys.path.insert(0, %(repo)r)
import numpy as np
from consensus_specs_tpu.ops.jax_bls.backend import NUMPY_KERNELS
assert NUMPY_KERNELS
import jax  # tree_util only

from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.ops import bls_jax
from consensus_specs_tpu.ops.jax_bls import htc as HTC, points as PT
from consensus_specs_tpu.ops.bls12_381 import hash_to_curve as ORC

bls.use_py()
wide = %(wide)r
# hash-to-curve equals the pure-python oracle
msgs = [b"np-kernel-0", b"np-kernel-1"] if wide else [b"np-kernel-0"]
pts = HTC.hash_to_g2_batch(msgs)
for i, m in enumerate(msgs):
    got = PT.g2_unpack(jax.tree_util.tree_map(lambda a: a[i], pts))
    assert got == ORC.hash_to_g2(m), "htc mismatch"

# a real aggregate verifies; a wrong message does not
sks = [1, 2, 3, 4] if wide else [1, 2]
msg = b"np-kernel-agg"
pks = [bls.SkToPk(sk) for sk in sks]
agg = bls.Aggregate([bls.Sign(sk, msg) for sk in sks])
items = [(pks, msg, agg), (pks, msg + b"!", agg)]
out = bls_jax.verify_aggregates_batch(items)
assert out == [True, False], out
print("NUMPY-KERNELS-OK")
"""


def _run_check(wide: bool):
    env = dict(os.environ, CS_TPU_NUMPY_KERNELS="1",
               CS_TPU_BLS_BATCH="2")
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no accelerator registration
    proc = subprocess.run(
        [sys.executable, "-c", _CHECK % {"repo": _REPO, "wide": wide}],
        env=env, capture_output=True, timeout=300, cwd=_REPO)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    assert b"NUMPY-KERNELS-OK" in proc.stdout


def test_numpy_kernel_mirror_matches_oracle():
    _run_check(wide=False)


import pytest  # noqa: E402
from consensus_specs_tpu.utils.env_flags import HEAVY  # noqa: E402


@pytest.mark.skipif(not HEAVY, reason="wider numpy-mirror differential "
                    "(CS_TPU_HEAVY=1)")
def test_numpy_kernel_mirror_wide():
    _run_check(wide=True)


_FR_FFT_CHECK = r"""
import sys
sys.path.insert(0, %(repo)r)
from consensus_specs_tpu.ops.jax_bls.backend import NUMPY_KERNELS
assert NUMPY_KERNELS
import random
from consensus_specs_tpu.ops import kzg as K
from consensus_specs_tpu.ops import kzg_7594 as K7
from consensus_specs_tpu.ops.jax_bls import fr_fft

rng = random.Random(61)
n = 256
roots = list(K.compute_roots_of_unity(n))
rows = [[rng.randrange(K.BLS_MODULUS) for _ in range(n)] for _ in range(4)]
assert fr_fft.fft_batch(rows, roots) == \
    [K7.fft_field(r, roots) for r in rows]
assert fr_fft.fft_batch(rows, roots, inv=True) == \
    [K7.fft_field(r, roots, inv=True) for r in rows]
# round trip through the kernel alone
back = fr_fft.fft_batch(fr_fft.fft_batch(rows, roots), roots, inv=True)
assert back == rows

# the DAS recovery grouped phases under CS_TPU_DAS_FFT=limb are
# byte-identical to the host-int path
import os
from consensus_specs_tpu.das import kernels
setup = K.trusted_setup("minimal")
blob = b"".join(rng.randrange(K.BLS_MODULUS).to_bytes(32, "big")
                for _ in range(setup.FIELD_ELEMENTS_PER_BLOB))
cells = K7.compute_cells(blob, setup)
n_cells = K7.cells_per_blob(setup)
keep = sorted(rng.sample(range(n_cells), n_cells // 2))
def _bytes(c):
    return b"".join(int(x).to_bytes(32, "big") for x in c)
reqs = [(keep, [_bytes(cells[i]) for i in keep])]
host = kernels.recover_cells_batch(reqs, setup)
os.environ["CS_TPU_DAS_FFT"] = "limb"
limb = kernels.recover_cells_batch(reqs, setup)
assert host == limb
print("FR-FFT-NUMPY-OK")
"""


def test_fr_fft_numpy_mirror_matches_host_fft():
    """The Fr limb FFT (DAS recovery kernel) in numpy-mirror mode:
    byte-identical to the python-int FFT, forward/inverse/roundtrip,
    and the full recovery pipeline under CS_TPU_DAS_FFT=limb."""
    env = dict(os.environ, CS_TPU_NUMPY_KERNELS="1")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _FR_FFT_CHECK % {"repo": _REPO}],
        env=env, capture_output=True, timeout=300, cwd=_REPO)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    assert b"FR-FFT-NUMPY-OK" in proc.stdout
