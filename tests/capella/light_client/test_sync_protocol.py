"""Capella light-client sync-protocol tests: the store machinery over
headers that carry the execution payload + inclusion branch.

Reference model: ``test/altair/light_client/test_sync.py`` shapes run at
the capella fork against ``specs/capella/light-client/sync-protocol.md``
(LightClientHeader gains ``execution``/``execution_branch``;
``is_valid_light_client_header`` verifies the body-root inclusion).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_config_overrides, always_bls,
    never_bls,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.sync_committee import (
    compute_aggregate_sync_committee_signature, compute_committee_indices,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root

capella_lc_active = with_config_overrides({
    "ALTAIR_FORK_EPOCH": 0, "BELLATRIX_FORK_EPOCH": 0,
    "CAPELLA_FORK_EPOCH": 0,
})


def _advance_chain(spec, state, n_blocks):
    out = []
    for _ in range(n_blocks):
        block = build_empty_block_for_next_slot(spec, state)
        signed = state_transition_and_sign_block(spec, state, block)
        out.append((signed, state.copy()))
    return out


def _signed_sync_aggregate(spec, signing_state, attested_root,
                           signature_slot, participation=1.0):
    committee_indices = compute_committee_indices(signing_state)
    n = int(len(committee_indices) * participation)
    participants = committee_indices[:n]
    bits = [i < n for i in range(len(committee_indices))]
    signature = compute_aggregate_sync_committee_signature(
        spec, signing_state, signature_slot - 1, participants,
        block_root=attested_root)
    return spec.SyncAggregate(sync_committee_bits=bits,
                              sync_committee_signature=signature)


def _bootstrap_store(spec, chain):
    signed_block, post_state = chain[0]
    bootstrap = spec.create_light_client_bootstrap(post_state, signed_block)
    trusted_root = hash_tree_root(signed_block.message)
    return spec.initialize_light_client_store(trusted_root, bootstrap)


@with_phases(["capella"])
@capella_lc_active
@spec_state_test
@never_bls
def test_bootstrap_header_carries_execution(spec, state):
    """A capella bootstrap header embeds the execution payload header
    with a valid body-root inclusion branch."""
    chain = _advance_chain(spec, state, 1)
    store = _bootstrap_store(spec, chain)
    signed_block, post_state = chain[0]
    header = store.finalized_header
    assert spec.is_valid_light_client_header(header)
    assert header.execution.block_hash == \
        post_state.latest_execution_payload_header.block_hash
    # tampering any execution field breaks the inclusion branch
    bad = header.copy()
    bad.execution.gas_limit += 1
    assert not spec.is_valid_light_client_header(bad)


@with_phases(["capella"])
@capella_lc_active
@spec_state_test
@never_bls
def test_tampered_execution_branch_rejected(spec, state):
    chain = _advance_chain(spec, state, 1)
    signed_block, _ = chain[0]
    header = spec.block_to_light_client_header(signed_block)
    assert spec.is_valid_light_client_header(header)
    bad = header.copy()
    bad.execution_branch[0] = b"\x27" * 32
    assert not spec.is_valid_light_client_header(bad)


@with_phases(["capella"])
@capella_lc_active
@spec_state_test
@always_bls
def test_process_light_client_update_capella(spec, state):
    """The full update pipeline accepts a capella header and advances
    the optimistic head."""
    chain = _advance_chain(spec, state, 2)
    store = _bootstrap_store(spec, chain)
    attested_block, attested_state = chain[1]

    attested_header = spec.block_to_light_client_header(attested_block)
    assert spec.is_valid_light_client_header(attested_header)
    signature_slot = attested_block.message.slot + 1
    sync_aggregate = _signed_sync_aggregate(
        spec, attested_state, hash_tree_root(attested_block.message),
        signature_slot)
    update = spec.LightClientUpdate(
        attested_header=attested_header,
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot,
    )
    spec.process_light_client_update(
        store, update, signature_slot,
        attested_state.genesis_validators_root)
    assert store.optimistic_header.beacon.slot == attested_block.message.slot
    assert store.optimistic_header.execution.block_hash == \
        attested_header.execution.block_hash


@with_phases(["capella"])
@capella_lc_active
@spec_state_test
@always_bls
def test_update_with_invalid_header_rejected(spec, state):
    """validate_light_client_update must reject an attested header whose
    execution branch does not include its execution payload."""
    chain = _advance_chain(spec, state, 2)
    store = _bootstrap_store(spec, chain)
    attested_block, attested_state = chain[1]

    attested_header = spec.block_to_light_client_header(attested_block)
    attested_header.execution.gas_used += 1  # breaks the inclusion proof
    signature_slot = attested_block.message.slot + 1
    sync_aggregate = _signed_sync_aggregate(
        spec, attested_state, hash_tree_root(attested_block.message),
        signature_slot)
    update = spec.LightClientUpdate(
        attested_header=attested_header,
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot,
    )
    try:
        spec.process_light_client_update(
            store, update, signature_slot,
            attested_state.genesis_validators_root)
        raise SystemExit("invalid capella header must be rejected")
    except AssertionError:
        pass
