"""Capella light-client merkle proofs incl. the execution branch.

Reference model:
``test/capella/light_client/test_single_merkle_proof.py`` against
``specs/capella/light-client/sync-protocol.md`` (LightClientHeader
carries the execution payload header + its body inclusion branch).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases_from, with_phases,
    with_config_overrides,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
)
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, compute_merkle_proof,
)

with_capella_and_later = with_all_phases_from("capella")
capella_lc_active = with_config_overrides({
    "ALTAIR_FORK_EPOCH": 0, "BELLATRIX_FORK_EPOCH": 0,
    "CAPELLA_FORK_EPOCH": 0,
})


@with_capella_and_later
@spec_state_test
def test_execution_merkle_proof(spec, state):
    from consensus_specs_tpu.forks.light_client import floorlog2
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    body = signed_block.message.body
    gindex = spec.EXECUTION_PAYLOAD_GINDEX
    proof = compute_merkle_proof(body, gindex)
    leaf = hash_tree_root(body.execution_payload)
    yield "object", body
    yield "proof", {
        "leaf": "0x" + bytes(leaf).hex(),
        "leaf_index": int(gindex),
        "branch": ["0x" + bytes(b).hex() for b in proof],
    }
    assert len(proof) == floorlog2(gindex)
    assert spec.is_valid_merkle_branch(
        leaf=leaf, branch=proof, depth=floorlog2(gindex),
        index=spec.get_subtree_index(gindex), root=hash_tree_root(body))


@with_capella_and_later
@spec_state_test
def test_current_sync_committee_merkle_proof(spec, state):
    from consensus_specs_tpu.forks.light_client import floorlog2
    gindex = spec.CURRENT_SYNC_COMMITTEE_GINDEX
    proof = compute_merkle_proof(state, gindex)
    assert spec.is_valid_merkle_branch(
        leaf=hash_tree_root(state.current_sync_committee), branch=proof,
        depth=floorlog2(gindex), index=spec.get_subtree_index(gindex),
        root=hash_tree_root(state))
    yield


@with_capella_and_later
@spec_state_test
def test_finality_root_merkle_proof_capella_state(spec, state):
    from consensus_specs_tpu.forks.light_client import floorlog2
    gindex = spec.FINALIZED_ROOT_GINDEX
    proof = compute_merkle_proof(state, gindex)
    assert spec.is_valid_merkle_branch(
        leaf=hash_tree_root(state.finalized_checkpoint.root), branch=proof,
        depth=floorlog2(gindex), index=spec.get_subtree_index(gindex),
        root=hash_tree_root(state))
    yield


@with_phases(["capella"])
@capella_lc_active
@spec_state_test
def test_header_execution_branch_round_trip(spec, state):
    """block_to_light_client_header emits a header whose execution
    branch verifies — and whose tampering is caught."""
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    header = spec.block_to_light_client_header(signed_block)
    assert spec.is_valid_light_client_header(header)
    assert header.execution.block_hash == \
        signed_block.message.body.execution_payload.block_hash
    tampered = header.copy()
    tampered.execution.gas_used = header.execution.gas_used + 1
    assert not spec.is_valid_light_client_header(tampered)


@with_phases(["capella"])
@spec_state_test
def test_pre_capella_header_must_be_empty(spec, state):
    """A header dated before the capella fork epoch must carry an empty
    execution header + branch (sync-protocol.md Modified
    is_valid_light_client_header)."""
    assert spec.config.CAPELLA_FORK_EPOCH > 0
    header = spec.LightClientHeader()
    header.beacon.slot = 0
    assert spec.is_valid_light_client_header(header)
    bad = header.copy()
    bad.execution.block_number = 1
    assert not spec.is_valid_light_client_header(bad)
