"""``process_historical_summaries_update`` coverage.

Reference model:
``test/capella/epoch_processing/test_process_historical_summaries_update.py``
against ``specs/capella/beacon-chain.md`` New
``process_historical_summaries_update`` (historical summaries replace
phase0's historical-roots accumulator).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases_from, with_phases,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.test_infra.block import next_epoch
from consensus_specs_tpu.utils.ssz import hash_tree_root

with_capella_and_later = with_all_phases_from("capella")
CAPELLA_ONLY = with_phases(["capella"])


def _epochs_per_period(spec):
    return int(spec.SLOTS_PER_HISTORICAL_ROOT // spec.SLOTS_PER_EPOCH)


@with_capella_and_later
@spec_state_test
def test_historical_summaries_accumulator(spec, state):
    """At the period boundary one summary lands, committing to the
    block/state root vectors."""
    period = _epochs_per_period(spec)
    while (spec.get_current_epoch(state) + 1) % period != 0:
        next_epoch(spec, state)
    pre_len = len(state.historical_summaries)
    yield from run_epoch_processing_with(
        spec, state, "process_historical_summaries_update")
    assert len(state.historical_summaries) == pre_len + 1
    summary = state.historical_summaries[-1]
    # the stage itself does not touch the root vectors, so the summary
    # must commit to their current contents
    assert summary.block_summary_root == hash_tree_root(state.block_roots)
    assert summary.state_summary_root == hash_tree_root(state.state_roots)


@CAPELLA_ONLY
@spec_state_test
def test_no_summary_off_boundary(spec, state):
    period = _epochs_per_period(spec)
    assert period > 1
    next_epoch(spec, state)
    if (spec.get_current_epoch(state) + 1) % period == 0:
        next_epoch(spec, state)
    pre_len = len(state.historical_summaries)
    yield from run_epoch_processing_with(
        spec, state, "process_historical_summaries_update")
    assert len(state.historical_summaries) == pre_len


@CAPELLA_ONLY
@spec_state_test
def test_historical_roots_untouched(spec, state):
    """Capella+ never appends to the phase0 historical_roots list."""
    period = _epochs_per_period(spec)
    pre_roots = len(state.historical_roots)
    for _ in range(period + 1):
        next_epoch(spec, state)
    assert len(state.historical_roots) == pre_roots
    assert len(state.historical_summaries) >= 1
