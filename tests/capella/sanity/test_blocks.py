"""Capella whole-block sanity transitions.

Reference model: ``test/capella/sanity/test_blocks.py`` (15 cases:
bls-change inclusion, change+deposit/exit combinations, duplicate
changes, withdrawals across epoch transitions and consecutive blocks)
against ``specs/capella/beacon-chain.md`` ``process_block``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_all_phases_from,
    expect_assertion_error,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block, transition_unsigned_block,
)
from consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload, compute_el_block_hash,
)
from consensus_specs_tpu.test_infra.deposits import prepare_state_and_deposit
from consensus_specs_tpu.test_infra.voluntary_exits import (
    prepare_signed_exits,
)

from tests.capella.block_processing.test_process_bls_to_execution_change \
    import get_signed_address_change
from tests.capella.block_processing.test_process_withdrawals import (
    prepare_expected_withdrawals,
)

with_capella_and_later = with_all_phases_from("capella")
CAPELLA_ONLY = with_phases(["capella"])


def _block_with_payload(spec, state):
    """Build the next-slot block and refresh its payload for the advanced
    state (withdrawal expectations move with the sweep cursor)."""
    block = build_empty_block_for_next_slot(spec, state)
    return block


@with_capella_and_later
@spec_state_test
def test_bls_change(spec, state):
    signed_change = get_signed_address_change(spec, state)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.bls_to_execution_changes.append(signed_change)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    validator = state.validators[0]
    assert bytes(validator.withdrawal_credentials[:1]) == \
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX


@with_capella_and_later
@spec_state_test
def test_deposit_and_bls_change(spec, state):
    deposit_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, deposit_index, amount,
                                        signed=True)
    signed_change = get_signed_address_change(spec, state, validator_index=1)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.deposits.append(deposit)
    block.body.bls_to_execution_changes.append(signed_change)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert len(state.validators) == deposit_index + 1
    assert bytes(state.validators[1].withdrawal_credentials[:1]) == \
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX


@with_capella_and_later
@spec_state_test
def test_exit_and_bls_change(spec, state):
    # move past shard-committee-period so the exit is admissible
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    index = 2
    signed_exits = prepare_signed_exits(spec, state, [index])
    signed_change = get_signed_address_change(spec, state,
                                              validator_index=index)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.voluntary_exits = signed_exits
    block.body.bls_to_execution_changes.append(signed_change)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    validator = state.validators[index]
    assert validator.exit_epoch < spec.FAR_FUTURE_EPOCH
    assert bytes(validator.withdrawal_credentials[:1]) == \
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX


@CAPELLA_ONLY
@spec_state_test
def test_invalid_duplicate_bls_changes_same_block(spec, state):
    signed_change = get_signed_address_change(spec, state)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.bls_to_execution_changes.append(signed_change)
    block.body.bls_to_execution_changes.append(signed_change)
    expect_assertion_error(
        lambda: transition_unsigned_block(spec, state.copy(), block))
    yield "blocks", []
    yield "post", None


@CAPELLA_ONLY
@spec_state_test
def test_invalid_two_bls_changes_of_different_addresses_same_validator_same_block(
        spec, state):
    change_a = get_signed_address_change(spec, state,
                                         to_execution_address=b"\x41" * 20)
    change_b = get_signed_address_change(spec, state,
                                         to_execution_address=b"\x42" * 20)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.bls_to_execution_changes.append(change_a)
    block.body.bls_to_execution_changes.append(change_b)
    expect_assertion_error(
        lambda: transition_unsigned_block(spec, state.copy(), block))
    yield "blocks", []
    yield "post", None


@CAPELLA_ONLY
@spec_state_test
def test_full_withdrawal_in_epoch_transition(spec, state):
    index = 0
    prepare_expected_withdrawals(spec, state, num_full=1)
    assert state.balances[index] > 0
    yield "pre", state
    # block crosses the epoch boundary; withdrawal pays out regardless
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.balances[index] == 0


@CAPELLA_ONLY
@spec_state_test
def test_partial_withdrawal_in_epoch_transition(spec, state):
    from consensus_specs_tpu.test_infra.block import build_empty_block
    index = 0
    prepare_expected_withdrawals(spec, state, num_partial=1)
    pre_balance = int(state.balances[index])
    assert pre_balance > int(spec.MAX_EFFECTIVE_BALANCE)
    yield "pre", state
    # block at the epoch boundary: epoch deltas + withdrawal both land
    block = build_empty_block(spec, state,
                              state.slot + spec.SLOTS_PER_EPOCH)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert int(state.balances[index]) < pre_balance
    # at most MAX remains (sync-committee/attestation penalties may have
    # shaved more, exactly as the reference allows)
    assert int(state.balances[index]) <= int(spec.MAX_EFFECTIVE_BALANCE)
    assert spec.get_expected_withdrawals(state) == []


@CAPELLA_ONLY
@spec_state_test
def test_many_partial_withdrawals_in_epoch_transition(spec, state):
    from consensus_specs_tpu.test_infra.block import build_empty_block
    count = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) + 1
    prepare_expected_withdrawals(spec, state, num_partial=count)
    assert len(spec.get_expected_withdrawals(state)) == \
        spec.MAX_WITHDRAWALS_PER_PAYLOAD
    yield "pre", state
    block = build_empty_block(spec, state,
                              state.slot + spec.SLOTS_PER_EPOCH)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    # one partial withdrawal exceeded the payload cap and is still owed
    assert len(spec.get_expected_withdrawals(state)) == 1


@CAPELLA_ONLY
@spec_state_test
def test_withdrawal_success_two_blocks(spec, state):
    """The sweep continues across consecutive blocks."""
    count = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) + 1
    prepare_expected_withdrawals(spec, state, num_full=count)
    yield "pre", state
    block_a = build_empty_block_for_next_slot(spec, state)
    signed_a = state_transition_and_sign_block(spec, state, block_a)
    assert len(block_a.body.execution_payload.withdrawals) == \
        spec.MAX_WITHDRAWALS_PER_PAYLOAD
    block_b = build_empty_block_for_next_slot(spec, state)
    signed_b = state_transition_and_sign_block(spec, state, block_b)
    assert len(block_b.body.execution_payload.withdrawals) >= 1
    yield "blocks", [signed_a, signed_b]
    yield "post", state
    assert all(int(state.balances[i]) == 0 for i in range(count))


@CAPELLA_ONLY
@spec_state_test
def test_invalid_withdrawal_fail_second_block_payload_isnt_compatible(
        spec, state):
    """Replaying block A's withdrawals in block B must fail."""
    count = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) * 2
    prepare_expected_withdrawals(spec, state, num_full=count)
    block_a = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block_a)
    stale_withdrawals = block_a.body.execution_payload.withdrawals

    block_b = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block_b.slot)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = stale_withdrawals
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield "pre", state
    expect_assertion_error(
        lambda: spec.process_withdrawals(state.copy(), payload))
    yield "post", None


@CAPELLA_ONLY
@spec_state_test
def test_top_up_and_partial_withdrawable_validator(spec, state):
    """A deposit top-up can push a max-effective validator into partial
    withdrawability at the next sweep."""
    index = 0
    from tests.capella.block_processing.test_process_withdrawals import (
        set_eth1_credentials)
    set_eth1_credentials(spec, state, index)
    state.validators[index].effective_balance = spec.MAX_EFFECTIVE_BALANCE
    state.balances[index] = spec.MAX_EFFECTIVE_BALANCE
    assert not spec.is_partially_withdrawable_validator(
        state.validators[index], state.balances[index])
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    deposit = prepare_state_and_deposit(spec, state, index, amount,
                                        signed=True)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.deposits.append(deposit)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert spec.is_partially_withdrawable_validator(
        state.validators[index], state.balances[index])


@CAPELLA_ONLY
@spec_state_test
def test_top_up_to_fully_withdrawn_validator(spec, state):
    """Top-up after a full withdrawal re-credits the drained balance."""
    index = 0
    prepare_expected_withdrawals(spec, state, num_full=1)
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    assert state.balances[index] == 0

    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    deposit = prepare_state_and_deposit(spec, state, index, amount,
                                        signed=True)
    yield "pre", state
    block2 = build_empty_block_for_next_slot(spec, state)
    block2.body.deposits.append(deposit)
    signed_block2 = state_transition_and_sign_block(spec, state, block2)
    yield "blocks", [signed_block2]
    yield "post", state
    # the top-up landed after this block's (empty) withdrawal sweep;
    # slot deltas (proposer/sync rewards or penalties) may shift it a bit
    assert int(state.balances[index]) > 0
