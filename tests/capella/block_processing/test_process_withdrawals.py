"""process_withdrawals operation tests.

Reference model: ``test/capella/block_processing/test_process_withdrawals.py``
against ``specs/capella/beacon-chain.md:346-403``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload)

WITHDRAWAL_FORKS = ["capella", "deneb"]


def set_eth1_credentials(spec, state, index):
    validator = state.validators[index]
    validator.withdrawal_credentials = (
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11
        + bytes([0x10 + index % 0xe0]) * 20)


def prepare_expected_withdrawals(spec, state, num_full=0, num_partial=0):
    """Mark validators withdrawable; returns (full_indices, partial_indices)."""
    assert num_full + num_partial <= len(state.validators)
    full = list(range(num_full))
    partial = list(range(num_full, num_full + num_partial))
    for index in full:
        set_eth1_credentials(spec, state, index)
        state.validators[index].withdrawable_epoch = \
            spec.get_current_epoch(state)
    for index in partial:
        set_eth1_credentials(spec, state, index)
        state.balances[index] = spec.MAX_EFFECTIVE_BALANCE + 10**9
    return full, partial


def run_withdrawals_processing(spec, state, payload, valid=True):
    pre_next_withdrawal_index = state.next_withdrawal_index
    expected = spec.get_expected_withdrawals(state)

    yield "pre", state
    yield "execution_payload", payload

    if not valid:
        expect_assertion_error(
            lambda: spec.process_withdrawals(state, payload))
        yield "post", None
        return

    spec.process_withdrawals(state, payload)
    yield "post", state

    if expected:
        assert state.next_withdrawal_index == \
            pre_next_withdrawal_index + len(expected)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_no_withdrawals(spec, state):
    assert spec.get_expected_withdrawals(state) == []
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_one_full_withdrawal(spec, state):
    full, _ = prepare_expected_withdrawals(spec, state, num_full=1)
    pre_balance = state.balances[full[0]]
    assert pre_balance > 0
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)
    assert state.balances[full[0]] == 0
    assert len(payload.withdrawals) == 1


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_one_partial_withdrawal(spec, state):
    _, partial = prepare_expected_withdrawals(spec, state, num_partial=1)
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)
    assert state.balances[partial[0]] == spec.MAX_EFFECTIVE_BALANCE
    assert len(payload.withdrawals) == 1


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_max_per_payload(spec, state):
    prepare_expected_withdrawals(
        spec, state, num_full=spec.MAX_WITHDRAWALS_PER_PAYLOAD + 2)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == spec.MAX_WITHDRAWALS_PER_PAYLOAD
    yield from run_withdrawals_processing(spec, state, payload)
    # sweep cursor advanced past the last processed withdrawal
    assert state.next_withdrawal_validator_index == \
        payload.withdrawals[-1].validator_index + 1


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_withdrawal_count(spec, state):
    prepare_expected_withdrawals(spec, state, num_full=1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = payload.withdrawals[:-1]  # drop the withdrawal
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_withdrawal_amount(spec, state):
    prepare_expected_withdrawals(spec, state, num_full=1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[0].amount += 1
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_withdrawal_index(spec, state):
    prepare_expected_withdrawals(spec, state, num_full=1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[0].index += 1
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_sweep_cursor_advances_without_withdrawals(spec, state):
    payload = build_empty_execution_payload(spec, state)
    pre_cursor = state.next_withdrawal_validator_index
    yield from run_withdrawals_processing(spec, state, payload)
    expected_cursor = (pre_cursor + min(
        len(state.validators), spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
    ) % len(state.validators)
    assert state.next_withdrawal_validator_index == expected_cursor


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_mixed_full_and_partial(spec, state):
    prepare_expected_withdrawals(spec, state, num_full=2, num_partial=2)
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)
    # full withdrawals zero the balance; partials skim to the cap
    assert state.balances[0] == 0 and state.balances[1] == 0
    assert state.balances[2] == spec.MAX_EFFECTIVE_BALANCE
    assert state.balances[3] == spec.MAX_EFFECTIVE_BALANCE


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_sweep_wraps_around_registry_end(spec, state):
    """The sweep cursor wraps modulo the registry length."""
    last = len(state.validators) - 1
    set_eth1_credentials(spec, state, last)
    state.validators[last].withdrawable_epoch = spec.get_current_epoch(state)
    state.next_withdrawal_validator_index = last
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)
    assert state.balances[last] == 0
    # non-full payload: the cursor jumps a whole sweep bound and wraps
    # modulo the registry (capella/beacon-chain.md process_withdrawals)
    expected_cursor = (last + spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP) \
        % len(state.validators)
    assert int(state.next_withdrawal_validator_index) == expected_cursor


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_bls_credentialed_validator_not_swept(spec, state):
    """A withdrawable validator still on 0x00 (BLS) credentials is
    skipped by the sweep — withdrawals need an execution address."""
    state.validators[0].withdrawable_epoch = spec.get_current_epoch(state)
    assert bytes(state.validators[0].withdrawal_credentials[:1]) == \
        spec.BLS_WITHDRAWAL_PREFIX
    assert spec.get_expected_withdrawals(state) == []
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)
    assert state.balances[0] > 0


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_exact_max_balance_no_partial(spec, state):
    """balance == MAX_EFFECTIVE_BALANCE is NOT an excess — no skim."""
    set_eth1_credentials(spec, state, 0)
    state.balances[0] = spec.MAX_EFFECTIVE_BALANCE
    assert spec.get_expected_withdrawals(state) == []
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_missing_expected_withdrawal(spec, state):
    prepare_expected_withdrawals(spec, state, num_full=2)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = payload.withdrawals[:1]
    yield from run_withdrawals_processing(spec, state, payload, valid=False)
