"""process_bls_to_execution_change operation tests.

Reference model:
``test/capella/block_processing/test_process_bls_to_execution_change.py``
against ``specs/capella/beacon-chain.md:466``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, always_bls, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.keys import pubkeys, pubkey_to_privkey
from consensus_specs_tpu.utils import bls

CHANGE_FORKS = ["capella", "deneb"]


def get_signed_address_change(spec, state, validator_index=0,
                              withdrawal_pubkey=None, to_execution_address=None,
                              bad_signature=False):
    if withdrawal_pubkey is None:
        # mock genesis uses pubkey as withdrawal key (test_infra/genesis.py)
        withdrawal_pubkey = pubkeys[validator_index]
    if to_execution_address is None:
        to_execution_address = b"\x42" * 20
    privkey = pubkey_to_privkey(bytes(withdrawal_pubkey))
    change = spec.BLSToExecutionChange(
        validator_index=validator_index,
        from_bls_pubkey=withdrawal_pubkey,
        to_execution_address=to_execution_address,
    )
    domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        genesis_validators_root=state.genesis_validators_root)
    signing_root = spec.compute_signing_root(change, domain)
    signature = bls.Sign(privkey, signing_root)
    if bad_signature:
        signature = bls.Sign(privkey, spec.Root(b"\x99" * 32))
    return spec.SignedBLSToExecutionChange(message=change, signature=signature)


def run_bls_to_execution_change_processing(spec, state, signed_change,
                                           valid=True):
    yield "pre", state
    yield "address_change", signed_change
    if not valid:
        expect_assertion_error(
            lambda: spec.process_bls_to_execution_change(state, signed_change))
        yield "post", None
        return
    spec.process_bls_to_execution_change(state, signed_change)
    yield "post", state

    validator = state.validators[signed_change.message.validator_index]
    assert bytes(validator.withdrawal_credentials[:1]) == \
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX
    assert bytes(validator.withdrawal_credentials[12:]) == \
        bytes(signed_change.message.to_execution_address)


@with_phases(CHANGE_FORKS)
@spec_state_test
def test_success(spec, state):
    signed_change = get_signed_address_change(spec, state)
    yield from run_bls_to_execution_change_processing(spec, state, signed_change)


@with_phases(CHANGE_FORKS)
@spec_state_test
def test_success_many_validators(spec, state):
    for index in (1, 3, 5):
        signed_change = get_signed_address_change(spec, state,
                                                  validator_index=index)
        spec.process_bls_to_execution_change(state, signed_change)
    signed_change = get_signed_address_change(spec, state, validator_index=7)
    yield from run_bls_to_execution_change_processing(spec, state, signed_change)


@with_phases(CHANGE_FORKS)
@spec_state_test
def test_invalid_out_of_range_validator_index(spec, state):
    signed_change = get_signed_address_change(spec, state)
    signed_change.message.validator_index = len(state.validators)
    yield from run_bls_to_execution_change_processing(spec, state,
                                                      signed_change, valid=False)


@with_phases(CHANGE_FORKS)
@spec_state_test
def test_invalid_already_eth1_credentials(spec, state):
    signed_change = get_signed_address_change(spec, state)
    # flip the validator to eth1 credentials first
    spec.process_bls_to_execution_change(state, signed_change)
    second = get_signed_address_change(spec, state)
    yield from run_bls_to_execution_change_processing(spec, state, second,
                                                      valid=False)


@with_phases(CHANGE_FORKS)
@spec_state_test
def test_invalid_withdrawal_pubkey_mismatch(spec, state):
    # signed by (and claiming) a different BLS withdrawal key
    signed_change = get_signed_address_change(
        spec, state, validator_index=0, withdrawal_pubkey=pubkeys[1])
    yield from run_bls_to_execution_change_processing(spec, state,
                                                      signed_change, valid=False)


@with_phases(CHANGE_FORKS)
@spec_state_test
@always_bls
def test_invalid_signature(spec, state):
    signed_change = get_signed_address_change(spec, state, bad_signature=True)
    yield from run_bls_to_execution_change_processing(spec, state,
                                                      signed_change, valid=False)


@with_phases(CHANGE_FORKS)
@spec_state_test
@always_bls
def test_invalid_current_fork_domain_signature(spec, state):
    """Address changes sign under the GENESIS fork version (they stay
    valid across forks); a signature under the current fork's domain
    must be rejected (capella/beacon-chain.md
    process_bls_to_execution_change)."""
    signed = get_signed_address_change(spec, state, validator_index=0)
    # re-sign under the (wrong) current-fork domain
    wrong_domain = spec.get_domain(
        state, spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        spec.get_current_epoch(state))
    signing_root = spec.compute_signing_root(signed.message, wrong_domain)
    privkey = pubkey_to_privkey(bytes(signed.message.from_bls_pubkey))
    signed.signature = bls.Sign(privkey, signing_root)
    yield from run_bls_to_execution_change_processing(
        spec, state, signed, valid=False)
