"""``process_withdrawals`` boundary and adversarial-payload coverage.

Reference model: ``test/capella/block_processing/test_process_withdrawals.py``
(53 cases) against ``specs/capella/beacon-chain.md``
``get_expected_withdrawals`` / ``process_withdrawals``: eligibility
predicates, sweep bounds, and every way a payload's withdrawal list can
disagree with the state's expectation.
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases,
)
from consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload)

from tests.capella.block_processing.test_process_withdrawals import (
    set_eth1_credentials, prepare_expected_withdrawals,
    run_withdrawals_processing,
)

WITHDRAWAL_FORKS = ["capella", "deneb"]
CAPELLA_ONLY = with_phases(["capella"])


def _make_fully_withdrawable(spec, state, index, balance=None):
    set_eth1_credentials(spec, state, index)
    state.validators[index].withdrawable_epoch = spec.get_current_epoch(state)
    if balance is not None:
        state.balances[index] = balance


def _make_partially_withdrawable(spec, state, index, excess=10**9):
    set_eth1_credentials(spec, state, index)
    state.validators[index].effective_balance = spec.MAX_EFFECTIVE_BALANCE
    state.balances[index] = spec.MAX_EFFECTIVE_BALANCE + excess


# -- successful sweeps -------------------------------------------------------

@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_all_fully_withdrawable_in_one_sweep(spec, state):
    """Every validator in one sweep window is fully withdrawable."""
    count = min(int(spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP),
                len(state.validators))
    for index in range(count):
        _make_fully_withdrawable(spec, state, index)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == spec.MAX_WITHDRAWALS_PER_PAYLOAD
    yield from run_withdrawals_processing(spec, state, payload)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_all_fully_withdrawable(spec, state):
    for index in range(len(state.validators)):
        _make_fully_withdrawable(spec, state, index)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == spec.MAX_WITHDRAWALS_PER_PAYLOAD
    yield from run_withdrawals_processing(spec, state, payload)
    # exactly the first MAX_WITHDRAWALS_PER_PAYLOAD validators were paid
    for w in payload.withdrawals:
        assert state.balances[w.validator_index] == 0


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_all_partially_withdrawable_in_one_sweep(spec, state):
    count = min(int(spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP),
                len(state.validators))
    for index in range(count):
        _make_partially_withdrawable(spec, state, index)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == spec.MAX_WITHDRAWALS_PER_PAYLOAD
    yield from run_withdrawals_processing(spec, state, payload)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_all_partially_withdrawable(spec, state):
    for index in range(len(state.validators)):
        _make_partially_withdrawable(spec, state, index)
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)
    for w in payload.withdrawals:
        assert state.balances[w.validator_index] == \
            spec.MAX_EFFECTIVE_BALANCE


@CAPELLA_ONLY
@spec_state_test
def test_success_two_partial_withdrawable(spec, state):
    _make_partially_withdrawable(spec, state, 0)
    _make_partially_withdrawable(spec, state, 1)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 2
    yield from run_withdrawals_processing(spec, state, payload)


@CAPELLA_ONLY
@spec_state_test
def test_success_max_partial_withdrawable(spec, state):
    for index in range(int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)):
        _make_partially_withdrawable(spec, state, index)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == spec.MAX_WITHDRAWALS_PER_PAYLOAD
    yield from run_withdrawals_processing(spec, state, payload)


@CAPELLA_ONLY
@spec_state_test
def test_success_max_plus_one_withdrawable(spec, state):
    for index in range(int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) + 1):
        _make_partially_withdrawable(spec, state, index)
    payload = build_empty_execution_payload(spec, state)
    # capped at the payload bound; the +1th waits for the next block
    assert len(payload.withdrawals) == spec.MAX_WITHDRAWALS_PER_PAYLOAD
    yield from run_withdrawals_processing(spec, state, payload)


# -- eligibility-predicate edges --------------------------------------------

@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_no_max_effective_balance(spec, state):
    """Excess balance but effective balance below MAX: not partial."""
    set_eth1_credentials(spec, state, 0)
    state.validators[0].effective_balance = \
        spec.MAX_EFFECTIVE_BALANCE - spec.EFFECTIVE_BALANCE_INCREMENT
    state.balances[0] = spec.MAX_EFFECTIVE_BALANCE + 10**9
    assert spec.get_expected_withdrawals(state) == []
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_no_excess_balance(spec, state):
    """Max effective balance but no excess: not partial."""
    set_eth1_credentials(spec, state, 0)
    state.validators[0].effective_balance = spec.MAX_EFFECTIVE_BALANCE
    state.balances[0] = spec.MAX_EFFECTIVE_BALANCE
    assert spec.get_expected_withdrawals(state) == []
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_success_excess_balance_but_no_max_effective_balance(spec, state):
    set_eth1_credentials(spec, state, 0)
    state.validators[0].effective_balance = \
        spec.MAX_EFFECTIVE_BALANCE - spec.EFFECTIVE_BALANCE_INCREMENT
    state.balances[0] = spec.MAX_EFFECTIVE_BALANCE + 1
    assert spec.get_expected_withdrawals(state) == []
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)


@CAPELLA_ONLY
@spec_state_test
def test_success_one_partial_withdrawable_not_yet_active(spec, state):
    """Activation status is irrelevant to partial withdrawability."""
    _make_partially_withdrawable(spec, state, 0)
    state.validators[0].activation_epoch = \
        spec.get_current_epoch(state) + 4
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)


@CAPELLA_ONLY
@spec_state_test
def test_success_one_partial_withdrawable_in_exit_queue(spec, state):
    _make_partially_withdrawable(spec, state, 0)
    spec.initiate_validator_exit(state, spec.ValidatorIndex(0))
    assert state.validators[0].exit_epoch > spec.get_current_epoch(state)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)


@CAPELLA_ONLY
@spec_state_test
def test_success_one_partial_withdrawable_exited(spec, state):
    _make_partially_withdrawable(spec, state, 0)
    state.validators[0].exit_epoch = spec.get_current_epoch(state)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)


@CAPELLA_ONLY
@spec_state_test
def test_success_one_partial_withdrawable_active_and_slashed(spec, state):
    _make_partially_withdrawable(spec, state, 0)
    state.validators[0].slashed = True
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)


@CAPELLA_ONLY
@spec_state_test
def test_success_one_partial_withdrawable_exited_and_slashed(spec, state):
    _make_partially_withdrawable(spec, state, 0)
    state.validators[0].slashed = True
    state.validators[0].exit_epoch = spec.get_current_epoch(state)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_withdrawable_epoch_but_0_balance(spec, state):
    """withdrawable_epoch reached but balance zero: nothing to pay."""
    _make_fully_withdrawable(spec, state, 0, balance=0)
    state.validators[0].effective_balance = 0
    assert spec.get_expected_withdrawals(state) == []
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_withdrawable_epoch_but_0_effective_balance_0_balance(spec, state):
    _make_fully_withdrawable(spec, state, 0, balance=0)
    state.validators[0].effective_balance = 0
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_withdrawable_epoch_but_0_effective_balance_nonzero_balance(
        spec, state):
    """Zero EFFECTIVE balance with real balance still fully withdraws."""
    _make_fully_withdrawable(spec, state, 0, balance=10**9)
    state.validators[0].effective_balance = 0
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)
    assert state.balances[0] == 0


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_no_withdrawals_but_some_next_epoch(spec, state):
    """withdrawable_epoch = next epoch: nothing due yet."""
    current = spec.get_current_epoch(state)
    set_eth1_credentials(spec, state, 0)
    state.validators[0].withdrawable_epoch = current + 1
    assert spec.get_expected_withdrawals(state) == []
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)


@CAPELLA_ONLY
@spec_state_test
def test_all_withdrawal(spec, state):
    """Whole registry fully withdrawable: repeated blocks drain it."""
    for index in range(len(state.validators)):
        _make_fully_withdrawable(spec, state, index)
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)
    paid = sum(1 for b in state.balances if int(b) == 0)
    assert paid == spec.MAX_WITHDRAWALS_PER_PAYLOAD


# -- invalid payload manipulations ------------------------------------------

@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_non_withdrawable_non_empty_withdrawals(spec, state):
    """No one is withdrawable, but the payload claims a withdrawal."""
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 0
    payload.withdrawals.append(spec.Withdrawal(
        index=0, validator_index=0,
        address=b"\x30" * 20, amount=10**9))
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_one_expected_full_withdrawal_and_none_in_withdrawals(
        spec, state):
    prepare_expected_withdrawals(spec, state, num_full=1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = type(payload.withdrawals)()
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_one_expected_partial_withdrawal_and_none_in_withdrawals(
        spec, state):
    prepare_expected_withdrawals(spec, state, num_partial=1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = type(payload.withdrawals)()
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_one_expected_full_withdrawal_and_duplicate_in_withdrawals(
        spec, state):
    prepare_expected_withdrawals(spec, state, num_full=1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals.append(payload.withdrawals[0])
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_two_expected_partial_withdrawal_and_duplicate_in_withdrawals(
        spec, state):
    prepare_expected_withdrawals(spec, state, num_partial=2)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[1] = payload.withdrawals[0]
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_max_per_slot_full_withdrawals_and_one_less_in_withdrawals(
        spec, state):
    prepare_expected_withdrawals(
        spec, state, num_full=int(spec.MAX_WITHDRAWALS_PER_PAYLOAD))
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = payload.withdrawals[:-1]
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_max_per_slot_partial_withdrawals_and_one_less_in_withdrawals(
        spec, state):
    prepare_expected_withdrawals(
        spec, state, num_partial=int(spec.MAX_WITHDRAWALS_PER_PAYLOAD))
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = payload.withdrawals[:-1]
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@CAPELLA_ONLY
@spec_state_test
def test_invalid_a_lot_fully_withdrawable_too_few_in_withdrawals(spec, state):
    prepare_expected_withdrawals(
        spec, state, num_full=int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) * 2)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = payload.withdrawals[:-2]
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@CAPELLA_ONLY
@spec_state_test
def test_invalid_a_lot_partially_withdrawable_too_few_in_withdrawals(
        spec, state):
    prepare_expected_withdrawals(
        spec, state, num_partial=int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) * 2)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = payload.withdrawals[:-2]
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@CAPELLA_ONLY
@spec_state_test
def test_invalid_a_lot_mixed_withdrawable_in_queue_too_few_in_withdrawals(
        spec, state):
    n = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)
    prepare_expected_withdrawals(spec, state, num_full=n, num_partial=n)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = payload.withdrawals[:-1]
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_incorrect_withdrawal_index(spec, state):
    prepare_expected_withdrawals(spec, state, num_full=1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[0].index += 1
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_incorrect_address_full(spec, state):
    prepare_expected_withdrawals(spec, state, num_full=1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[0].address = b"\xff" * 20
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_incorrect_address_partial(spec, state):
    prepare_expected_withdrawals(spec, state, num_partial=1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[0].address = b"\xff" * 20
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_incorrect_amount_full(spec, state):
    prepare_expected_withdrawals(spec, state, num_full=1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[0].amount += 1
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(WITHDRAWAL_FORKS)
@spec_state_test
def test_invalid_incorrect_amount_partial(spec, state):
    prepare_expected_withdrawals(spec, state, num_partial=1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[0].amount += 1
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@CAPELLA_ONLY
@spec_state_test
def test_invalid_one_of_many_incorrectly_full(spec, state):
    prepare_expected_withdrawals(
        spec, state, num_full=int(spec.MAX_WITHDRAWALS_PER_PAYLOAD))
    payload = build_empty_execution_payload(spec, state)
    mid = len(payload.withdrawals) // 2
    payload.withdrawals[mid].amount += 1
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@CAPELLA_ONLY
@spec_state_test
def test_invalid_one_of_many_incorrectly_partial(spec, state):
    prepare_expected_withdrawals(
        spec, state, num_partial=int(spec.MAX_WITHDRAWALS_PER_PAYLOAD))
    payload = build_empty_execution_payload(spec, state)
    mid = len(payload.withdrawals) // 2
    payload.withdrawals[mid].validator_index += 1
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@CAPELLA_ONLY
@spec_state_test
def test_invalid_many_incorrectly_full(spec, state):
    prepare_expected_withdrawals(
        spec, state, num_full=int(spec.MAX_WITHDRAWALS_PER_PAYLOAD))
    payload = build_empty_execution_payload(spec, state)
    for i, w in enumerate(payload.withdrawals):
        w.index += i + 1
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@CAPELLA_ONLY
@spec_state_test
def test_invalid_many_incorrectly_partial(spec, state):
    prepare_expected_withdrawals(
        spec, state, num_partial=int(spec.MAX_WITHDRAWALS_PER_PAYLOAD))
    payload = build_empty_execution_payload(spec, state)
    for i, w in enumerate(payload.withdrawals):
        w.address = bytes([i + 1]) * 20
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


# -- randomized mixes --------------------------------------------------------

def _run_random_withdrawals(spec, state, rng, full_fraction,
                            partial_fraction):
    for index in range(len(state.validators)):
        roll = rng.random()
        if roll < full_fraction:
            _make_fully_withdrawable(
                spec, state, index,
                balance=rng.randrange(1, 2 * int(spec.MAX_EFFECTIVE_BALANCE)))
        elif roll < full_fraction + partial_fraction:
            _make_partially_withdrawable(
                spec, state, index, excess=rng.randrange(1, 10**10))
    # start the sweep cursor somewhere random to cover wrap-around
    state.next_withdrawal_validator_index = spec.ValidatorIndex(
        rng.randrange(len(state.validators)))
    payload = build_empty_execution_payload(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)


@CAPELLA_ONLY
@spec_state_test
def test_random_full_withdrawals_0(spec, state):
    yield from _run_random_withdrawals(spec, state, Random(440), 0.3, 0.0)


@CAPELLA_ONLY
@spec_state_test
def test_random_full_withdrawals_1(spec, state):
    yield from _run_random_withdrawals(spec, state, Random(441), 0.6, 0.0)


@CAPELLA_ONLY
@spec_state_test
def test_random_full_withdrawals_2(spec, state):
    yield from _run_random_withdrawals(spec, state, Random(442), 0.9, 0.0)


@CAPELLA_ONLY
@spec_state_test
def test_random_full_withdrawals_3(spec, state):
    yield from _run_random_withdrawals(spec, state, Random(443), 1.0, 0.0)


@CAPELLA_ONLY
@spec_state_test
def test_random_partial_withdrawals_1(spec, state):
    yield from _run_random_withdrawals(spec, state, Random(451), 0.0, 0.3)


@CAPELLA_ONLY
@spec_state_test
def test_random_partial_withdrawals_2(spec, state):
    yield from _run_random_withdrawals(spec, state, Random(452), 0.0, 0.6)


@CAPELLA_ONLY
@spec_state_test
def test_random_partial_withdrawals_3(spec, state):
    yield from _run_random_withdrawals(spec, state, Random(453), 0.0, 0.9)


@CAPELLA_ONLY
@spec_state_test
def test_random_partial_withdrawals_4(spec, state):
    yield from _run_random_withdrawals(spec, state, Random(454), 0.0, 1.0)


@CAPELLA_ONLY
@spec_state_test
def test_random_partial_withdrawals_5(spec, state):
    yield from _run_random_withdrawals(spec, state, Random(455), 0.0, 0.5)


@CAPELLA_ONLY
@spec_state_test
def test_random_0(spec, state):
    yield from _run_random_withdrawals(spec, state, Random(456), 0.25, 0.25)
