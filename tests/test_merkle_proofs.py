"""Generalized-index and Merkle-proof tests.

Reference model: ``ssz/merkle-proofs.md`` rules plus the hardcoded gindex
assertions the reference emits into the altair module
(``pysetup/spec_builders/altair.py:43-48``: FINALIZED_ROOT_GINDEX=105,
CURRENT_SYNC_COMMITTEE_GINDEX=54, NEXT_SYNC_COMMITTEE_GINDEX=55).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.utils.ssz import (
    Container, List, Vector, Bitlist, uint64, Bytes32, hash_tree_root, get_generalized_index, concat_generalized_indices, get_generalized_index_length, generalized_index_sibling, generalized_index_child, generalized_index_parent, verify_merkle_proof, compute_merkle_proof, get_subtree_node_root, get_helper_indices, verify_merkle_multiproof)


class Inner(Container):
    a: uint64
    b: Bytes32


class Outer(Container):
    x: uint64
    inner: Inner
    items: List[uint64, 1024]
    vecs: Vector[Inner, 4]
    bits: Bitlist[100]


def test_gindex_arithmetic():
    assert get_generalized_index_length(1) == 0
    assert get_generalized_index_length(9) == 3
    assert generalized_index_sibling(8) == 9
    assert generalized_index_parent(9) == 4
    assert generalized_index_child(4, False) == 8
    assert generalized_index_child(4, True) == 9
    assert concat_generalized_indices(4, 3) == 9
    assert concat_generalized_indices(2, 2, 2) == 8


def test_gindex_container_paths():
    # Outer has 5 fields -> padded to 8 -> depth 3
    assert get_generalized_index(Outer, "x") == 8
    assert get_generalized_index(Outer, "inner") == 9
    # Inner has 2 fields -> depth 1
    assert get_generalized_index(Outer, "inner", "a") == 18
    assert get_generalized_index(Outer, "inner", "b") == 19
    # list length mixin
    assert get_generalized_index(Outer, "items", "__len__") == \
        get_generalized_index(Outer, "items") * 2 + 1


def test_altair_state_gindices_match_reference_constants():
    """The hardcoded reference constants pin our whole gindex pipeline."""
    from consensus_specs_tpu.forks import build_spec
    spec = build_spec("altair", "minimal")
    assert get_generalized_index(
        spec.BeaconState, "finalized_checkpoint", "root") == 105
    assert get_generalized_index(
        spec.BeaconState, "current_sync_committee") == 54
    assert get_generalized_index(
        spec.BeaconState, "next_sync_committee") == 55


def _example():
    return Outer(
        x=7,
        inner=Inner(a=3, b=b"\x22" * 32),
        items=[1, 2, 3, 4, 5],
        vecs=[Inner(a=i, b=bytes([i]) * 32) for i in range(4)],
        bits=[True, False, True],
    )


def test_single_proofs_verify_against_root():
    value = _example()
    root = hash_tree_root(value)
    for path in (("x",), ("inner",), ("inner", "b"), ("items",),
                 ("items", "__len__"), ("vecs",), ("vecs", 2),
                 ("vecs", 2, "a"), ("bits",)):
        gindex = get_generalized_index(Outer, *path)
        leaf = get_subtree_node_root(value, gindex)
        proof = compute_merkle_proof(value, gindex)
        assert len(proof) == get_generalized_index_length(gindex)
        assert verify_merkle_proof(leaf, proof, gindex, root), path
        # a corrupted leaf must fail
        assert not verify_merkle_proof(b"\x00" * 32, proof, gindex, root) \
            or leaf == b"\x00" * 32


def test_leaf_roots_match_field_roots():
    value = _example()
    gindex = get_generalized_index(Outer, "inner")
    assert get_subtree_node_root(value, gindex) == \
        hash_tree_root(value.inner)
    gindex = get_generalized_index(Outer, "vecs", 1)
    assert get_subtree_node_root(value, gindex) == \
        hash_tree_root(value.vecs[1])


def test_proof_changes_when_value_mutates():
    value = _example()
    gindex = get_generalized_index(Outer, "inner", "a")
    root = hash_tree_root(value)
    leaf = get_subtree_node_root(value, gindex)
    proof = compute_merkle_proof(value, gindex)
    assert verify_merkle_proof(leaf, proof, gindex, root)
    value.inner.a = 999
    new_root = hash_tree_root(value)
    assert new_root != root
    # old leaf no longer verifies against the new root
    assert not verify_merkle_proof(leaf, proof, gindex, new_root)
    # fresh leaf + proof do
    assert verify_merkle_proof(
        get_subtree_node_root(value, gindex),
        compute_merkle_proof(value, gindex), gindex, new_root)


def test_multiproof():
    value = _example()
    root = hash_tree_root(value)
    indices = [get_generalized_index(Outer, "x"),
               get_generalized_index(Outer, "inner", "a")]
    leaves = [get_subtree_node_root(value, g) for g in indices]
    helper_indices = get_helper_indices(indices)
    proof = [get_subtree_node_root(value, g) for g in helper_indices]
    assert verify_merkle_multiproof(leaves, proof, indices, root)
    assert not verify_merkle_multiproof(leaves[::-1], proof, indices, root)


def test_beacon_state_finalized_root_proof():
    """The altair light-client bootstrap proof shape end to end."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    spec = build_spec("altair", "minimal")
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 64, spec.MAX_EFFECTIVE_BALANCE)
    gindex = 105  # finalized_checkpoint.root
    proof = compute_merkle_proof(state, gindex)
    leaf = bytes(state.finalized_checkpoint.root)
    assert verify_merkle_proof(leaf, proof, gindex, hash_tree_root(state))
