"""Random-linear-combination (RLC) batch-verification suite.

Adversarial soundness, per-item bisect reporting, memo interplay,
determinism, path counters, KZG-batch deferral, and the differential
guarantee that the RLC flush (``CS_TPU_BLS_RLC=1``, default), the
per-lane flush (``CS_TPU_BLS_RLC=0``) and the pure-python backend agree
item-for-item across every enqueue site (proposer signature, randao,
attestations, sync aggregate).  See ``docs/bls-batching.md``.
"""
import os

import pytest

from consensus_specs_tpu.ops import bls_rlc
from consensus_specs_tpu.ops.bls12_381.curve import (
    G1_GENERATOR, G2_GENERATOR, g2_from_compressed, msm)
from consensus_specs_tpu.obs import registry
from consensus_specs_tpu.utils import bls

MSG_A = b"\xab" * 32
MSG_B = b"\xcd" * 32
INF_PK = bytes([0xC0]) + b"\x00" * 47
INF_SIG = bytes([0xC0]) + b"\x00" * 95

_PAIRINGS = registry.counter("bls.pairings")
_FLUSH = registry.counter("bls.flush")
_HITS = registry.counter("cache.hit")


def setup_module():
    bls.use_py()
    bls.bls_active = True


def setup_function(_fn):
    bls.use_py()
    bls.clear_verify_memo()


class _rlc_env:
    """Temporarily force CS_TPU_BLS_RLC (the switch re-reads os.environ
    at flush time when the variable is present)."""

    def __init__(self, value):
        self.value = value

    def __enter__(self):
        self.old = os.environ.get("CS_TPU_BLS_RLC")
        os.environ["CS_TPU_BLS_RLC"] = self.value

    def __exit__(self, *exc):
        if self.old is None:
            del os.environ["CS_TPU_BLS_RLC"]
        else:
            os.environ["CS_TPU_BLS_RLC"] = self.old


def _sig_items(n=3):
    """n valid (pubkeys, msg, sig) items: one 3-key aggregate + singles."""
    pks = [bls.SkToPk(i) for i in (1, 2, 3)]
    agg = bls.Aggregate([bls.Sign(i, MSG_A) for i in (1, 2, 3)])
    items = [(pks, MSG_A, agg)]
    for i in range(4, 3 + n):
        m = bytes([i]) * 32
        items.append(([bls.SkToPk(i)], m, bls.Sign(i, m)))
    return items[:n]


def _flush_batch(items):
    """Queue items through the public API and flush; returns (ok, batch)."""
    with bls.batched_verification() as batch:
        for pks, msg, sig in items:
            if len(pks) == 1:
                bls.Verify(pks[0], msg, sig)
            else:
                bls.FastAggregateVerify(pks, msg, sig)
    ok = batch.flush()
    return ok, batch


# ---------------------------------------------------------------------------
# One pairing per block (counter-asserted) + path labels
# ---------------------------------------------------------------------------

def test_rlc_flush_is_one_pairing():
    items = _sig_items(3)
    with _rlc_env("1"):
        p0, r0 = _PAIRINGS.total(), _FLUSH.value(path="rlc")
        ok, _ = _flush_batch(items)
        assert ok
        assert _PAIRINGS.total() - p0 == 1
        assert _FLUSH.value(path="rlc") - r0 == 1


def test_rlc_disabled_runs_lane_path():
    items = _sig_items(2)
    with _rlc_env("0"):
        assert not bls.rlc_enabled()
        p0, l0 = _PAIRINGS.total(), _FLUSH.value(path="lanes")
        ok, _ = _flush_batch(items)
        assert ok
        assert _PAIRINGS.total() - p0 == len(items)
        assert _FLUSH.value(path="lanes") - l0 == 1


def test_combined_failure_falls_back_and_bisects():
    items = _sig_items(3)
    bad = ([bls.SkToPk(9)], MSG_B, bls.Sign(9, MSG_A))   # wrong message
    with _rlc_env("1"):
        f0 = _FLUSH.value(path="fallback", reason="bisect")
        ok, batch = _flush_batch(items + [bad])
        assert not ok
        assert batch.last_results == [True, True, True, False]
        assert _FLUSH.value(path="fallback", reason="bisect") - f0 == 1


def test_assert_valid_reports_failing_indices():
    bad = ([bls.SkToPk(9)], MSG_B, bls.Sign(9, MSG_A))
    with bls.batched_verification() as batch:
        bls.FastAggregateVerify(*_sig_items(1)[0])
        bls.Verify(bad[0][0], bad[1], bad[2])
    with pytest.raises(AssertionError, match=r"items \[1\]"):
        batch.assert_valid()


# ---------------------------------------------------------------------------
# Adversarial soundness
# ---------------------------------------------------------------------------

def test_forged_pair_whose_sum_verifies_is_rejected():
    """sig1' = sig1 + D, sig2' = sig2 - D: the *unrandomized* fold
    sum(sig_i') equals sum(sig_i) so a naive combined check would accept;
    the RLC coefficients kill the cancellation (r1*D != r2*D w.h.p.)."""
    pk1, pk2 = bls.SkToPk(11), bls.SkToPk(12)
    s1, s2 = bls.Sign(11, MSG_A), bls.Sign(12, MSG_B)
    D = g2_from_compressed(bls.Sign(99, b"delta"))
    f1 = (g2_from_compressed(s1) + D).to_compressed()
    f2 = (g2_from_compressed(s2) - D).to_compressed()
    # the attack premise holds: the sums agree...
    assert bls.Aggregate([f1, f2]) == bls.Aggregate([s1, s2])
    # ...but the RLC flush rejects, and the bisect blames both items
    ok, batch = _flush_batch([([pk1], MSG_A, f1), ([pk2], MSG_B, f2)])
    assert not ok
    assert batch.last_results == [False, False]


def test_mixed_structural_invalids_bisect_exactly():
    """Invalid encodings / infinity pubkey / empty pubkeys / infinity
    signature inside an otherwise-valid batch surface the right per-item
    verdicts through the fallback."""
    good = _sig_items(1)[0]
    items = [
        good,
        ([INF_PK], MSG_A, bls.Sign(1, MSG_A)),      # infinity pubkey
        ([], MSG_A, bls.Sign(1, MSG_A)),            # empty pubkey list
        ([bls.SkToPk(2)], MSG_A, b"\x00" * 96),     # malformed signature
        ([b"\xff" * 48], MSG_A, bls.Sign(2, MSG_A)),  # x >= p pubkey
        ([bls.SkToPk(3)], MSG_A, INF_SIG),          # infinity signature
        _sig_items(3)[2],
    ]
    ok, batch = _flush_batch(items)
    assert not ok
    assert batch.last_results == [True, False, False, False, False,
                                  False, True]


def test_infinity_signature_accepted_only_for_degenerate_claim():
    """An infinity signature is a *valid encoding* but only verifies when
    the whole claim is degenerate — it must not poison the batch."""
    good = _sig_items(1)[0]
    ok, batch = _flush_batch([good, ([bls.SkToPk(4)], MSG_A, INF_SIG)])
    assert not ok
    assert batch.last_results == [True, False]


# ---------------------------------------------------------------------------
# Deterministic seeding
# ---------------------------------------------------------------------------

def test_scalar_derivation_is_deterministic_and_input_sensitive():
    items = [([b"\x01" * 48], MSG_A, b"\x02" * 96),
             ([b"\x03" * 48], MSG_B, b"\x04" * 96)]
    a = bls_rlc.derive_scalars(items)
    b = bls_rlc.derive_scalars(items)
    assert a == b and len(a) == 2
    assert all(0 < r < (1 << bls_rlc.SCALAR_BITS) for r in a)
    # any queued byte changing re-randomizes every coefficient
    mutated = [(items[0][0], MSG_A, b"\x05" * 96), items[1]]
    c = bls_rlc.derive_scalars(mutated)
    assert a[0] != c[0] and a[1] != c[1]
    # extra checks draw their own coefficients after the items
    extra = [([(G1_GENERATOR, G2_GENERATOR)], "kzg_batch")]
    d = bls_rlc.derive_scalars(items, extra)
    assert len(d) == 3


def test_oracle_g2_msm_matches_naive():
    sigs = [g2_from_compressed(bls.Sign(i, bytes([i]) * 32))
            for i in (1, 2, 3)]
    rs = [5, (1 << 127) + 3, 12345678901234567890]
    got = msm(sigs, rs)
    exp = sigs[0].mult(rs[0]) + sigs[1].mult(rs[1]) + sigs[2].mult(rs[2])
    assert got == exp


# ---------------------------------------------------------------------------
# Memo interplay (satellite: check before enqueue, record at flush)
# ---------------------------------------------------------------------------

def test_replayed_batch_skips_device_work_via_memo():
    items = _sig_items(3)
    ok, _ = _flush_batch(items)
    assert ok
    p0 = _PAIRINGS.total()
    h0 = _HITS.value(cache="bls_verify")
    ok, batch = _flush_batch(items)      # replay: all memo hits
    assert ok
    assert _PAIRINGS.total() == p0, "replay must not re-verify"
    assert _HITS.value(cache="bls_verify") - h0 == len(items)
    assert batch.last_results is None or batch.last_results == []


def test_memoized_failure_raises_at_enqueue():
    bad = ([bls.SkToPk(9)], MSG_B, bls.Sign(9, MSG_A))
    ok, _ = _flush_batch([bad])
    assert not ok
    # the second enqueue finds the memoized False and fails immediately
    with bls.batched_verification():
        assert bls.Verify(bad[0][0], bad[1], bad[2]) is False


def test_duplicate_triples_share_one_lane():
    item = _sig_items(1)[0]
    with bls.batched_verification() as batch:
        bls.FastAggregateVerify(*item)
        bls.FastAggregateVerify(*item)
        assert len(batch.items) == 1
    assert batch.flush()


# ---------------------------------------------------------------------------
# Differential: RLC vs lanes vs python backend
# ---------------------------------------------------------------------------

def _item_matrix():
    good = _sig_items(3)
    return good + [
        ([bls.SkToPk(9)], MSG_B, bls.Sign(9, MSG_A)),   # wrong message
        ([INF_PK], MSG_A, bls.Sign(1, MSG_A)),          # invalid pubkey
    ]


def _per_item_results(items):
    ok, batch = _flush_batch(items)
    if batch.last_results is not None and len(batch.last_results) == len(items):
        return ok, batch.last_results
    return ok, [True] * len(items)    # rlc-pass: everything valid


def test_differential_rlc_vs_lanes_vs_oracle():
    items = _item_matrix()
    oracle = [bls.FastAggregateVerify(pks, m, s) if len(pks) != 1
              else bls.Verify(pks[0], m, s) for pks, m, s in items]
    bls.clear_verify_memo()
    ok_rlc, res_rlc = _per_item_results(items)
    bls.clear_verify_memo()
    with _rlc_env("0"):
        ok_lanes, res_lanes = _per_item_results(items)
    assert res_rlc == res_lanes == oracle
    assert ok_rlc == ok_lanes == all(oracle)
    if _native_available():
        bls.use_native()
        bls.clear_verify_memo()
        ok_n, res_n = _per_item_results(items)
        assert (ok_n, res_n) == (ok_rlc, res_rlc)


def _native_available():
    from consensus_specs_tpu.ops import native_bls
    return native_bls.available()


# ---------------------------------------------------------------------------
# Deferred raw pairing checks (the KZG batch fold)
# ---------------------------------------------------------------------------

def test_defer_pairing_check_requires_scope_and_rlc():
    pairs = [(G1_GENERATOR, G2_GENERATOR)]
    assert not bls.defer_pairing_check(pairs)          # no active scope
    with _rlc_env("0"):
        with bls.batched_verification():
            assert not bls.defer_pairing_check(pairs)  # rlc off
    with _rlc_env("1"):
        with bls.batched_verification() as batch:
            assert bls.defer_pairing_check(pairs, label="t")
            assert len(batch.pairing_checks) == 1
            batch.pairing_checks.clear()               # don't evaluate


def test_deferred_pairing_check_folds_and_bisects():
    # a trivially-true product: e(P, Q) * e(-P, Q) == 1
    good_pairs = [(G1_GENERATOR, G2_GENERATOR),
                  (-G1_GENERATOR, G2_GENERATOR)]
    bad_pairs = [(G1_GENERATOR, G2_GENERATOR)]         # e(G1, G2) != 1
    item = _sig_items(1)[0]
    with _rlc_env("1"):
        p0 = _PAIRINGS.total()
        with bls.batched_verification() as batch:
            bls.FastAggregateVerify(*item)
            assert bls.defer_pairing_check(good_pairs, label="ok")
        assert batch.flush()
        assert _PAIRINGS.total() - p0 == 1             # sig + check: 1 pairing
        bls.clear_verify_memo()
        with bls.batched_verification() as batch:
            bls.FastAggregateVerify(*item)
            assert bls.defer_pairing_check(good_pairs, label="ok")
            assert bls.defer_pairing_check(bad_pairs, label="bad")
        assert not batch.flush()
        assert batch.last_results == [True]
        assert batch.last_pairing_results == [True, False]


# ---------------------------------------------------------------------------
# Device (jax) path: the numpy-kernel mirror executes the identical
# kernel source eagerly, so this differential covers the device math
# without paying XLA compiles (same rationale as test_numpy_kernels.py;
# import-time switch, hence the subprocess)
# ---------------------------------------------------------------------------

_NUMPY_RLC_CHECK = r"""
import sys
sys.path.insert(0, %(repo)r)
from consensus_specs_tpu.ops.jax_bls.backend import NUMPY_KERNELS
assert NUMPY_KERNELS
from consensus_specs_tpu.ops.bls12_381.curve import g2_from_compressed
from consensus_specs_tpu.obs import registry
from consensus_specs_tpu.utils import bls

bls.use_py()
msg = b"rlc-np" * 6
pks = [bls.SkToPk(i) for i in (1, 2, 3)]
agg = bls.Aggregate([bls.Sign(i, msg) for i in (1, 2, 3)])
pk2, msg2 = bls.SkToPk(5), b"\x11" * 32
sig2 = bls.Sign(5, msg2)

bls.use_jax()
pairings = registry.counter("bls.pairings")
with bls.batched_verification() as batch:
    assert bls.FastAggregateVerify(pks, msg, agg)
    assert bls.Verify(pk2, msg2, sig2)
assert batch.flush()
assert pairings.total() == 1, pairings.total()

# forged pair whose sum verifies must be rejected + bisected
bls.clear_verify_memo()
s1, s2 = bls.Sign(11, msg), bls.Sign(12, msg2)
D = g2_from_compressed(bls.Sign(99, b"delta"))
f1 = (g2_from_compressed(s1) + D).to_compressed()
f2 = (g2_from_compressed(s2) - D).to_compressed()
with bls.batched_verification() as batch:
    bls.Verify(bls.SkToPk(11), msg, f1)
    bls.Verify(bls.SkToPk(12), msg2, f2)
assert not batch.flush()
assert batch.last_results == [False, False], batch.last_results
print("NUMPY-RLC-OK")
"""


@pytest.mark.skipif(
    not os.environ.get("CS_TPU_HEAVY") == "1",
    reason="numpy-mirror RLC differential subprocess (CS_TPU_HEAVY=1)")
def test_numpy_kernel_rlc_differential():
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, CS_TPU_NUMPY_KERNELS="1")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _NUMPY_RLC_CHECK % {"repo": repo}],
        env=env, capture_output=True, timeout=600, cwd=repo)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    assert b"NUMPY-RLC-OK" in proc.stdout


# ---------------------------------------------------------------------------
# Full-block differential: every enqueue site (proposer signature,
# randao, attestations, altair sync aggregate) through one flush
# ---------------------------------------------------------------------------

def _build_signed_full_block(spec, state):
    from consensus_specs_tpu.test_infra.attestations import (
        get_valid_attestation)
    from consensus_specs_tpu.test_infra.block import (
        build_empty_block_for_next_slot, next_slots,
        state_transition_and_sign_block)
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attestations.append(attestation)
    if hasattr(spec, "SyncAggregate"):
        from consensus_specs_tpu.test_infra.sync_committee import (
            compute_aggregate_sync_committee_signature,
            compute_committee_indices)
        committee_indices = compute_committee_indices(state)
        block.body.sync_aggregate = spec.SyncAggregate(
            sync_committee_bits=[True] * len(committee_indices),
            sync_committee_signature=(
                compute_aggregate_sync_committee_signature(
                    spec, state, block.slot - 1, committee_indices)))
    return state_transition_and_sign_block(spec, state, block)


def _full_block_differential(spec, state):
    from consensus_specs_tpu.utils.ssz import hash_tree_root
    pre = state.copy()
    signed_block = _build_signed_full_block(spec, state)
    # replay the same signed block under both flush strategies
    bls.clear_verify_memo()
    s_rlc, s_lanes = pre.copy(), pre.copy()
    p0, r0 = _PAIRINGS.total(), _FLUSH.value(path="rlc")
    with _rlc_env("1"):
        spec.state_transition(s_rlc, signed_block, True)
    assert _FLUSH.value(path="rlc") - r0 == 1
    assert _PAIRINGS.total() - p0 == 1, \
        "a full block (proposer + randao + attestation [+ sync "\
        "aggregate]) must verify with ONE pairing"
    bls.clear_verify_memo()
    with _rlc_env("0"):
        spec.state_transition(s_lanes, signed_block, True)
    assert hash_tree_root(s_rlc) == hash_tree_root(s_lanes) \
        == hash_tree_root(state)


def test_full_block_differential_all_enqueue_sites():
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.test_infra.context import (
        _get_genesis_state, default_balances, default_activation_threshold)
    old_active = bls.bls_active
    bls.bls_active = True
    try:
        for fork in ("phase0", "altair"):
            spec = build_spec(fork, "minimal")
            state = _get_genesis_state(spec, default_balances,
                                       default_activation_threshold)
            _full_block_differential(spec, state)
    finally:
        bls.bls_active = old_active


@pytest.mark.slow
def test_blob_kzg_batch_defers_into_block_flush():
    from consensus_specs_tpu.ops import kzg as K
    setup = K.trusted_setup("minimal")
    width = setup.FIELD_ELEMENTS_PER_BLOB
    import random
    rng = random.Random(7)
    blob = b"".join(rng.randrange(K.BLS_MODULUS).to_bytes(32, "big")
                    for _ in range(width))
    commitment = K.blob_to_kzg_commitment(blob, setup)
    proof = K.compute_blob_kzg_proof(blob, commitment, setup)
    item = _sig_items(1)[0]
    with _rlc_env("1"):
        p0 = _PAIRINGS.total()
        with bls.batched_verification() as batch:
            bls.FastAggregateVerify(*item)
            assert K.verify_blob_kzg_proof_batch(
                [blob], [commitment], [proof], setup)
        assert batch.flush()
        assert _PAIRINGS.total() - p0 == 1, \
            "block signatures + blob-KZG batch must share ONE pairing"
        # wrong proof: the flush fails and the bisect blames the kzg check
        bls.clear_verify_memo()
        blob2 = b"".join(rng.randrange(K.BLS_MODULUS).to_bytes(32, "big")
                         for _ in range(width))
        bad_proof = K.compute_blob_kzg_proof(blob2, commitment, setup)
        with bls.batched_verification() as batch:
            bls.FastAggregateVerify(*item)
            assert K.verify_blob_kzg_proof_batch(
                [blob], [commitment], [bad_proof], setup)
        assert not batch.flush()
        assert batch.last_results == [True]
        assert batch.last_pairing_results == [False]
    # outside a scope the eager path still answers False directly
    assert not K.verify_blob_kzg_proof_batch(
        [blob], [commitment], [bad_proof], setup)
