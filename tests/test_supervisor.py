"""Engine-supervisor unit suite (``consensus_specs_tpu/supervisor``):
breaker state machine under a fake clock, deadline guards, sentinel
audits + quarantine, the unified ``env_flags.switch`` accessor, and the
``CS_TPU_SUPERVISOR=0`` pass-through contract."""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu import faults, supervisor
from consensus_specs_tpu.test_infra.metrics import counting
from consensus_specs_tpu.utils import env_flags

SITE = "merkle.dispatch"


@pytest.fixture(autouse=True)
def _supervisor_on(monkeypatch, tmp_path):
    """This suite drives the supervisor explicitly: pin the master
    switch ON regardless of the process env (the CI off-leg runs the
    whole suite under CS_TPU_SUPERVISOR=0; tests of the off behavior
    override to \"0\" themselves — the switch reads live), and point
    quarantine artifact dumps at the test's tmp dir so quarantining
    tests never dirty the working tree."""
    monkeypatch.setenv("CS_TPU_SUPERVISOR", "1")
    monkeypatch.setenv("CS_TPU_SIM_ARTIFACTS", str(tmp_path))


@pytest.fixture
def clock(monkeypatch):
    """Deterministic supervisor time: yields a one-element list; tests
    advance it by assignment."""
    t = [1000.0]
    monkeypatch.setattr(supervisor, "_clock", lambda: t[0])
    return t


@pytest.fixture
def knobs(monkeypatch):
    """Tight, deterministic breaker knobs (threshold 3, 10s window,
    100ms base backoff, fixed seed) applied and picked up by reset."""
    monkeypatch.setenv("CS_TPU_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("CS_TPU_BREAKER_WINDOW_MS", "10000")
    monkeypatch.setenv("CS_TPU_BREAKER_BACKOFF_MS", "100")
    monkeypatch.setenv("CS_TPU_BREAKER_BACKOFF_MAX_MS", "100000")
    monkeypatch.setenv("CS_TPU_SUPERVISOR_SEED", "7")
    supervisor.reset()
    yield
    supervisor.reset()


# ---------------------------------------------------------------------------
# circuit breaker state machine
# ---------------------------------------------------------------------------

def test_breaker_opens_at_threshold_within_window(clock, knobs):
    for _ in range(2):
        supervisor.note_failure(SITE)
    assert supervisor.states()[SITE] == "closed"
    assert supervisor.admit(SITE)
    supervisor.note_failure(SITE)
    assert supervisor.states()[SITE] == "open"
    with counting() as delta:
        assert not supervisor.admit(SITE)
    assert delta[f"supervisor.breaker.skips{{site={SITE}}}"] == 1


def test_failures_outside_window_do_not_trip(clock, knobs):
    supervisor.note_failure(SITE)
    supervisor.note_failure(SITE)
    clock[0] += 11.0          # past the 10s window
    supervisor.note_failure(SITE)
    assert supervisor.states()[SITE] == "closed"


def test_success_clears_the_failure_run(clock, knobs):
    supervisor.note_failure(SITE)
    supervisor.note_failure(SITE)
    supervisor.note_success(SITE)
    supervisor.note_failure(SITE)
    supervisor.note_failure(SITE)
    assert supervisor.states()[SITE] == "closed"   # run never reached 3


def test_backoff_probe_and_repromotion(clock, knobs):
    for _ in range(3):
        supervisor.note_failure(SITE)
    assert supervisor.states()[SITE] == "open"
    # before backoff: skipped
    assert not supervisor.admit(SITE)
    # after backoff (base 100ms, jitter <= 25%): the next admit is the
    # half-open probe
    clock[0] += 0.125 + 1e-6
    with counting() as delta:
        assert supervisor.admit(SITE)
    assert supervisor.states()[SITE] == "half_open"
    assert delta[f"supervisor.transitions{{site={SITE},to=half_open}}"] == 1
    supervisor.note_success(SITE)
    assert supervisor.states()[SITE] == "closed"


def test_probe_failure_doubles_backoff(clock, knobs):
    base_lo, base_hi = 0.1, 0.125
    for _ in range(3):
        supervisor.note_failure(SITE)
    first = supervisor._breakers[SITE].reopen_at - clock[0]
    assert base_lo <= first <= base_hi
    clock[0] += first + 1e-6
    assert supervisor.admit(SITE)                  # the probe
    supervisor.note_failure(SITE)                  # probe fails
    assert supervisor.states()[SITE] == "open"
    second = supervisor._breakers[SITE].reopen_at - clock[0]
    assert 2 * base_lo <= second <= 2 * base_hi    # doubled (+jitter)


def test_backoff_jitter_is_seeded_deterministic(clock, monkeypatch):
    def trip_once():
        monkeypatch.setenv("CS_TPU_BREAKER_THRESHOLD", "1")
        monkeypatch.setenv("CS_TPU_BREAKER_BACKOFF_MS", "100")
        monkeypatch.setenv("CS_TPU_SUPERVISOR_SEED", "42")
        supervisor.reset()
        supervisor.note_failure(SITE)
        return supervisor._breakers[SITE].reopen_at - clock[0]
    try:
        assert trip_once() == trip_once()
    finally:
        supervisor.reset()


def test_deadline_overruns_accumulate_past_successes(clock, knobs,
                                                     monkeypatch):
    """A dispatch that completes correctly but over budget books a
    ``reason=deadline`` breaker failure that interleaved successes must
    NOT clear — a persistently slow engine demotes."""
    for _ in range(2):
        supervisor.note_failure(SITE, "deadline")
        supervisor.note_success(SITE)
    supervisor.note_failure(SITE, "deadline")
    assert supervisor.states()[SITE] == "open"


# ---------------------------------------------------------------------------
# deadline guards
# ---------------------------------------------------------------------------

def test_deadline_scope_noop_without_budget(knobs):
    with supervisor.deadline_scope(SITE):
        supervisor.deadline_check()      # never raises when disarmed
    assert supervisor._deadline_stack_for_thread() == []


def test_deadline_check_raises_midwork(clock, monkeypatch):
    monkeypatch.setenv("CS_TPU_DEADLINE_MS", "10")
    supervisor.reset()
    try:
        with counting() as delta:
            with pytest.raises(supervisor.DeadlineExceeded):
                with supervisor.deadline_scope(SITE):
                    clock[0] += 0.02     # 20ms > the 10ms budget
                    supervisor.deadline_check()
        assert delta[f"supervisor.deadline.trips{{site={SITE}}}"] == 1
        assert supervisor._deadline_stack_for_thread() == []
    finally:
        supervisor.reset()


def test_completed_overrun_books_posthoc_trip(clock, monkeypatch):
    monkeypatch.setenv("CS_TPU_DEADLINE_MS", "10")
    supervisor.reset()
    try:
        with counting() as delta:
            with supervisor.deadline_scope(SITE):
                clock[0] += 0.02         # slow, but completes
        assert delta[f"supervisor.deadline.trips{{site={SITE}}}"] == 1
    finally:
        supervisor.reset()


def test_engine_deadline_falls_back_counted(clock, monkeypatch):
    """Engine-level wiring: a mid-work deadline inside an epoch kernel
    converts the call into a counted ``reason=deadline`` fallback and
    the spec loop serves it."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.ops import epoch_kernels
    from consensus_specs_tpu.tools.obs_report import build_state
    spec = build_spec("phase0", "minimal")
    state = build_state(spec, 8)
    monkeypatch.setenv("CS_TPU_DEADLINE_MS", "5")
    supervisor.reset()
    try:
        orig = epoch_kernels._registry_updates

        def slow(spec, state):
            clock[0] += 1.0
            supervisor.deadline_check()
            orig(spec, state)

        monkeypatch.setattr(epoch_kernels, "_registry_updates", slow)
        with counting() as delta:
            handled = epoch_kernels.try_process_registry_updates(spec, state)
        assert handled is False
        assert delta["epoch.fallbacks{reason=deadline}"] == 1
        assert delta["supervisor.deadline.trips"
                     "{site=epoch.registry_updates}"] == 1
    finally:
        supervisor.reset()


# ---------------------------------------------------------------------------
# sentinel audits + quarantine (driven through the real merkle engine)
# ---------------------------------------------------------------------------

def _rows(n=16):
    return np.arange(n * 64, dtype=np.uint8).reshape(n, 64)


def test_audit_passes_on_clean_engine(monkeypatch):
    from consensus_specs_tpu.utils.ssz import merkle
    monkeypatch.setenv("CS_TPU_AUDIT_RATE", "1")
    supervisor.reset()
    rows = _rows()
    with counting() as delta:
        out = merkle.hash_rows(rows)
    assert np.array_equal(out, merkle._hash_rows_scalar(rows))
    assert delta[f"supervisor.audits{{result=pass,site={SITE}}}"] == 1
    assert supervisor.states()[SITE] == "closed"


def test_corruption_caught_within_k_calls(monkeypatch, tmp_path):
    """The acceptance contract: a persistently corrupt engine result is
    caught by the sampled sentinel within K calls, the site is
    quarantined (breaker open, reason=audit), a replayable artifact is
    dumped, and subsequent calls skip the corrupt engine entirely."""
    from consensus_specs_tpu.utils.ssz import merkle
    k = 3
    monkeypatch.setenv("CS_TPU_AUDIT_RATE", str(k))
    monkeypatch.setenv("CS_TPU_SIM_ARTIFACTS", str(tmp_path))
    supervisor.reset()
    rows = _rows()
    golden = merkle._hash_rows_scalar(rows)
    schedule = faults.FaultSchedule(corrupt={SITE: [1]})
    caught_at = None
    with counting() as delta:
        with faults.injected(schedule):
            for i in range(1, k + 1):
                merkle.hash_rows(rows)
                if supervisor.states()[SITE] == "quarantined":
                    caught_at = i
                    break
    assert caught_at is not None and caught_at <= k
    assert delta[f"supervisor.audits{{result=fail,site={SITE}}}"] == 1
    assert delta[f"supervisor.quarantines{{site={SITE}}}"] == 1
    path = supervisor.last_quarantine()
    assert path is not None and os.path.isfile(path)
    # quarantined: the engine is never re-probed, every dispatch serves
    # the spec-shaped scalar path byte-identical
    with counting() as delta:
        out = merkle.hash_rows(rows)
    assert np.array_equal(out, golden)
    assert delta[f"supervisor.breaker.skips{{site={SITE}}}"] == 1
    assert delta[f"supervisor.audits{{result=fail,site={SITE}}}"] == 0


def test_quarantine_never_reprobes(clock, knobs):
    supervisor.quarantine(SITE, "test")
    clock[0] += 1e9
    assert not supervisor.admit(SITE)
    assert supervisor.states()[SITE] == "quarantined"


def test_audited_call_serves_spec_answer_on_mismatch(monkeypatch):
    """Even the corrupted call itself answers with the spec result —
    quarantine means the wrong answer never left the engine."""
    from consensus_specs_tpu.utils.ssz import merkle
    monkeypatch.setenv("CS_TPU_AUDIT_RATE", "1")
    supervisor.reset()
    rows = _rows()
    golden = merkle._hash_rows_scalar(rows)
    with supervisor.quarantine_hook(lambda s, d: None):
        with faults.injected(faults.FaultSchedule(corrupt={SITE: [1]})):
            out = merkle.hash_rows(rows)
    assert np.array_equal(out, golden)


def test_epoch_audit_passes_and_spec_serves(monkeypatch):
    """Epoch-site audit shape: the spec loop runs on the real state,
    the kernel on a probe copy, post-states merkleize identical."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.ops import epoch_kernels
    from consensus_specs_tpu.tools.obs_report import build_state
    from consensus_specs_tpu.utils.ssz import hash_tree_root
    spec = build_spec("phase0", "minimal")
    state = build_state(spec, 8)
    oracle = build_state(spec, 8)
    monkeypatch.setenv("CS_TPU_AUDIT_RATE", "1")
    supervisor.reset()
    with counting() as delta:
        handled = epoch_kernels.try_process_registry_updates(spec, state)
    assert handled is True
    site = "epoch.registry_updates"
    assert delta[f"supervisor.audits{{result=pass,site={site}}}"] == 1
    supervisor.reset()
    monkeypatch.delenv("CS_TPU_AUDIT_RATE")
    assert epoch_kernels.try_process_registry_updates(spec, oracle)
    assert bytes(hash_tree_root(state)) == bytes(hash_tree_root(oracle))


# ---------------------------------------------------------------------------
# the unified env_flags.switch accessor (live re-read regression)
# ---------------------------------------------------------------------------

def test_every_engine_switch_reads_live(monkeypatch):
    """Flipping each CS_TPU_* engine flag mid-process must be seen by
    its engine's enabled() accessor on the next call — one source of
    truth, no import-latched stragglers."""
    from consensus_specs_tpu.forkchoice import proto_array
    from consensus_specs_tpu.ops import epoch_kernels
    from consensus_specs_tpu.state import arrays
    from consensus_specs_tpu.utils import bls
    from consensus_specs_tpu.utils.ssz import forest
    probes = {
        "CS_TPU_VECTORIZED_EPOCH": epoch_kernels.enabled,
        "CS_TPU_PROTO_ARRAY": proto_array.enabled,
        "CS_TPU_STATE_ARRAYS": arrays.enabled,
        "CS_TPU_BLS_RLC": bls.rlc_enabled,
        "CS_TPU_SUPERVISOR": supervisor.enabled,
        "CS_TPU_HASH_FOREST":
            lambda: env_flags.switch("CS_TPU_HASH_FOREST"),
    }
    for var, probe in probes.items():
        monkeypatch.setenv(var, "1")
        assert probe() is True, var
        monkeypatch.setenv(var, "0")
        assert probe() is False, var
        monkeypatch.delenv(var)
        assert probe() is env_flags._SWITCH_DEFAULTS.get(var, True), \
            f"{var}: unset must fall back to the import-time default"
    # the forest scope gate itself honors the live read
    monkeypatch.setenv("CS_TPU_HASH_FOREST", "0")
    with forest.hash_forest():
        assert not forest.scope_active()
    monkeypatch.delenv("CS_TPU_HASH_FOREST")
    with forest.hash_forest():
        assert forest.scope_active()


def test_switch_refresh_resnapshots_defaults(monkeypatch):
    saved = dict(env_flags._SWITCH_DEFAULTS)
    try:
        monkeypatch.setenv("CS_TPU_PROTO_ARRAY", "0")
        env_flags.refresh()
        monkeypatch.delenv("CS_TPU_PROTO_ARRAY")
        # unset now falls back to the refreshed default (off)
        assert env_flags.switch("CS_TPU_PROTO_ARRAY") is False
    finally:
        env_flags._SWITCH_DEFAULTS.clear()
        env_flags._SWITCH_DEFAULTS.update(saved)
    assert env_flags.switch("CS_TPU_PROTO_ARRAY") \
        is saved["CS_TPU_PROTO_ARRAY"]


# ---------------------------------------------------------------------------
# CS_TPU_SUPERVISOR=0: exact pre-supervisor behavior
# ---------------------------------------------------------------------------

def test_supervisor_off_is_passthrough(clock, knobs, monkeypatch):
    supervisor.quarantine(SITE, "pre-existing")
    monkeypatch.setenv("CS_TPU_SUPERVISOR", "0")
    # a quarantined site admits, failures/audits book nothing
    assert supervisor.admit(SITE)
    with counting() as delta:
        supervisor.note_failure(SITE)
        supervisor.note_success(SITE)
        assert supervisor.audit_due(SITE) is False
        with supervisor.deadline_scope(SITE):
            supervisor.deadline_check()
    assert not delta.nonzero()
    assert supervisor._deadline_stack_for_thread() == []


def test_supervisor_off_engine_paths_unchanged(monkeypatch):
    """With the switch off and a breaker artificially open, the merkle
    engine must dispatch its batched path as if the supervisor did not
    exist (and still serve the fault-injection contract)."""
    from consensus_specs_tpu.utils.ssz import merkle
    supervisor.reset()
    supervisor.quarantine(SITE, "poisoned state that must be ignored")
    monkeypatch.setenv("CS_TPU_SUPERVISOR", "0")
    rows = _rows()
    golden = merkle._hash_rows_scalar(rows)
    with counting() as delta:
        out = merkle.hash_rows(rows)
    assert np.array_equal(out, golden)
    assert delta[f"supervisor.breaker.skips{{site={SITE}}}"] == 0
    # injected faults still fall back counted, exactly PR-8 behavior
    schedule = faults.FaultSchedule({SITE: [1]})
    with counting() as delta:
        with faults.injected(schedule):
            out = merkle.hash_rows(rows)
    assert np.array_equal(out, golden)
    assert schedule.fully_fired()
    assert delta["merkle.fallbacks{reason=injected}"] == 1


# ---------------------------------------------------------------------------
# telemetry surface
# ---------------------------------------------------------------------------

def test_supervisor_metrics_export(clock, knobs):
    from consensus_specs_tpu.obs import export
    for _ in range(3):
        supervisor.note_failure(SITE)
    assert not supervisor.admit(SITE)
    snap = export.snapshot()
    export.assert_schema(snap, require_nonempty=("supervisor.",))
    gauge = snap["metrics"]["supervisor.breaker"]["series"]
    assert gauge[f"{{site={SITE}}}"] == 1          # open
    prom = export.to_prometheus()
    assert "cs_tpu_supervisor_transitions" in prom
    assert f'site="{SITE}"' in prom


def test_states_reports_all_sites(knobs):
    states = supervisor.states()
    assert set(states) >= set(faults.SITES)
    assert all(v == "closed" for v in states.values())
