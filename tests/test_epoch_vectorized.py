"""Differential equivalence suite for the vectorized epoch engine.

``ops/epoch_kernels.py`` re-expresses the O(validators) epoch loops as
columnar array kernels; its exactness contract is bit-identical
post-state ``hash_tree_root`` against the per-validator spec loops.
This suite enforces that contract per fork and per epoch function over
randomized states seeded with the edge shapes the kernels special-case:
slashed validators (mid-withdrawability, the ``prev+1 == withdrawable``
eligibility boundary, and the ``process_slashings`` target epoch),
exited and exiting validators, ejection candidates at the balance
threshold, activation-queue stamps, finalized-boundary activation
eligibility, hysteresis-straddling balances, zero-participation epochs
and inactivity-leak epochs.

The engine's fallback/commit counters are asserted around every
vectorized run so a silent guard fallback cannot quietly turn these
comparisons into loop-vs-loop tautologies.
"""
from random import Random

import numpy as np
import pytest

from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.ops import epoch_kernels as ek
from consensus_specs_tpu.state import arrays as state_arrays
from consensus_specs_tpu.test_infra.attestations import (
    next_epoch_with_attestations)
from consensus_specs_tpu.test_infra.block import next_epoch
from consensus_specs_tpu.test_infra.epoch_processing import (
    get_process_calls, run_epoch_processing_to)
from consensus_specs_tpu.test_infra.genesis import create_genesis_state
from consensus_specs_tpu.test_infra.metrics import counting
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.ssz import (
    List, hash_tree_root, uint64)

PHASE0_FAMILY = ["phase0", "sharding", "custody_game"]
ALTAIR_FAMILY = ["altair", "bellatrix", "capella", "deneb",
                 "eip6110", "eip7002", "eip7594", "whisk", "eip6914"]

VECTORIZED_FNS = ["process_rewards_and_penalties", "process_registry_updates",
                  "process_slashings", "process_effective_balance_updates"]
ALTAIR_VECTORIZED_FNS = ["process_inactivity_updates"] + VECTORIZED_FNS

N_VALIDATORS = 64


@pytest.fixture(autouse=True)
def _engine_mode_reset():
    """Every test leaves the process-global switch back at auto, and
    runs with signature checks off (epoch processing never verifies)."""
    prev_bls = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = prev_bls
    ek.use_auto()
    state_arrays.use_auto()


def _spec(fork):
    return build_spec(fork, "minimal")


def _genesis(spec):
    return create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * N_VALIDATORS,
        spec.MAX_EFFECTIVE_BALANCE)


def _scatter_registry_edges(spec, state, rng, preserve_active=False):
    """Seed the registry with every eligibility/edge shape the kernels
    branch on.  Mutates fields directly (not via ``slash_validator``)
    so the same scatter works on every fork, whisk included.

    ``preserve_active``: phase0-family states carry pending attestations
    whose aggregation bits were sized against the committees of past
    slots; shapes that change WHO was active then (exits into the past,
    pending activations) would invalidate them for the spec loop too,
    so only activity-preserving shapes are scattered."""
    current_epoch = int(spec.get_current_epoch(state))
    prev_epoch = int(spec.get_previous_epoch(state))
    far = spec.FAR_FUTURE_EPOCH
    slashings_target = current_epoch + int(spec.EPOCHS_PER_SLASHINGS_VECTOR) // 2
    for i in range(len(state.validators)):
        v = state.validators[i]
        roll = rng.random()
        if roll < 0.08:
            # slashed, still delta-eligible (prev + 1 < withdrawable)
            v.slashed = True
            v.withdrawable_epoch = prev_epoch + 2 + rng.randint(0, 3)
        elif roll < 0.12:
            # slashed at the process_slashings target epoch
            v.slashed = True
            v.withdrawable_epoch = slashings_target
        elif roll < 0.16:
            # slashed eligibility BOUNDARY: prev + 1 == withdrawable
            v.slashed = True
            v.withdrawable_epoch = prev_epoch + 1
        elif roll < 0.22:
            # exited / exiting
            v.exit_epoch = current_epoch + rng.randint(1, 3) \
                if preserve_active \
                else max(int(v.activation_epoch) + 1, prev_epoch)
            v.withdrawable_epoch = int(v.exit_epoch) + rng.randint(1, 4)
        elif roll < 0.28:
            # ejection candidate: active at the balance threshold
            v.effective_balance = spec.config.EJECTION_BALANCE
        elif roll < 0.34 and not preserve_active:
            # pending activation right at the finalized boundary
            v.activation_epoch = far
            v.activation_eligibility_epoch = \
                int(state.finalized_checkpoint.epoch) - rng.randint(0, 1) \
                if int(state.finalized_checkpoint.epoch) else 0
        elif roll < 0.40 and not preserve_active:
            # fresh top-up: activation-queue stamp candidate
            v.activation_epoch = far
            v.activation_eligibility_epoch = far
            v.effective_balance = spec.MAX_EFFECTIVE_BALANCE
        # hysteresis-straddling balances (effective-balance updates)
        if rng.random() < 0.6:
            step = int(spec.EFFECTIVE_BALANCE_INCREMENT) \
                // int(spec.HYSTERESIS_QUOTIENT)
            state.balances[i] = max(
                0, int(state.balances[i]) + rng.randint(-3, 3) * step)
    if int(sum(state.slashings)) == 0:
        state.slashings[0] = spec.EFFECTIVE_BALANCE_INCREMENT * 7


def _scatter_participation(spec, state, rng, zero=False):
    for i in range(len(state.validators)):
        prev_flags = 0 if zero else rng.randint(0, 7)
        cur_flags = 0 if zero else rng.randint(0, 7)
        state.previous_epoch_participation[i] = \
            spec.ParticipationFlags(prev_flags)
        state.current_epoch_participation[i] = \
            spec.ParticipationFlags(cur_flags)
        state.inactivity_scores[i] = rng.randint(0, 40)


def _altair_state(fork, *, zero_participation=False, leak=False, seed=7):
    spec = _spec(fork)
    state = _genesis(spec)
    ek.use_loops()
    epochs = 7 if leak else 3
    for _ in range(epochs):
        next_epoch(spec, state)
    if not leak:
        # recent finality: not leaking, and a non-zero finalized epoch
        # for the activation-eligibility boundary
        state.finalized_checkpoint.epoch = spec.get_previous_epoch(state) - 1
    rng = Random(seed)
    _scatter_registry_edges(spec, state, rng)
    _scatter_participation(spec, state, rng, zero=zero_participation)
    assert spec.is_in_inactivity_leak(state) == leak
    return spec, state


def _phase0_state(fork, *, empty_attestations=False, seed=11):
    spec = _spec(fork)
    state = _genesis(spec)
    ek.use_loops()
    next_epoch(spec, state)
    if empty_attestations:
        next_epoch(spec, state)
        next_epoch(spec, state)
    else:
        _, _, state = next_epoch_with_attestations(spec, state, True, False)
        _, _, state = next_epoch_with_attestations(spec, state, True, True)
    rng = Random(seed)
    _scatter_registry_edges(spec, state, rng, preserve_active=True)
    return spec, state


def _assert_function_equivalence(spec, state, fns):
    """Each epoch sub-transition and the full epoch must commit the
    identical post-state through both engines."""
    for fn in fns:
        s_loop, s_vec = state.copy(), state.copy()
        ek.use_loops()
        run_epoch_processing_to(spec, s_loop, fn)
        getattr(spec, fn)(s_loop)
        ek.use_vectorized()
        with counting() as delta:
            run_epoch_processing_to(spec, s_vec, fn)
            getattr(spec, fn)(s_vec)
        assert delta["epoch.transition{path=vectorized}"] > 0, \
            f"{spec.fork}.{fn}: vectorized engine never committed"
        assert delta["epoch.fallbacks{reason=guard}"] == 0, \
            f"{spec.fork}.{fn}: unexpected guard fallback"
        assert hash_tree_root(s_loop) == hash_tree_root(s_vec), \
            f"{spec.fork}.{fn}: post-state roots diverge"
    s_loop, s_vec = state.copy(), state.copy()
    ek.use_loops()
    spec.process_epoch(s_loop)
    ek.use_vectorized()
    spec.process_epoch(s_vec)
    assert hash_tree_root(s_loop) == hash_tree_root(s_vec), \
        f"{spec.fork}: full process_epoch roots diverge"


@pytest.mark.parametrize("fork", ALTAIR_FAMILY)
def test_altair_family_differential(fork):
    spec, state = _altair_state(fork)
    _assert_function_equivalence(spec, state, ALTAIR_VECTORIZED_FNS)


@pytest.mark.parametrize("fork", PHASE0_FAMILY)
def test_phase0_family_differential(fork):
    spec, state = _phase0_state(fork)
    _assert_function_equivalence(spec, state, VECTORIZED_FNS)


@pytest.mark.parametrize("fork", ["altair", "deneb"])
def test_zero_participation_epoch(fork):
    spec, state = _altair_state(fork, zero_participation=True, seed=13)
    _assert_function_equivalence(spec, state, ALTAIR_VECTORIZED_FNS)


def test_phase0_no_attestations_epoch():
    spec, state = _phase0_state("phase0", empty_attestations=True, seed=17)
    _assert_function_equivalence(spec, state, VECTORIZED_FNS)


@pytest.mark.parametrize("fork", ["altair", "phase0"])
def test_inactivity_leak_epoch(fork):
    if fork == "phase0":
        spec = _spec(fork)
        state = _genesis(spec)
        ek.use_loops()
        next_epoch(spec, state)
        _, _, state = next_epoch_with_attestations(spec, state, True, False)
        for _ in range(6):     # let finality lapse into a leak
            next_epoch(spec, state)
        _, _, state = next_epoch_with_attestations(spec, state, True, False)
        _scatter_registry_edges(spec, state, Random(19), preserve_active=True)
        assert spec.is_in_inactivity_leak(state)
        _assert_function_equivalence(spec, state, VECTORIZED_FNS)
    else:
        spec, state = _altair_state(fork, leak=True, seed=23)
        _assert_function_equivalence(spec, state, ALTAIR_VECTORIZED_FNS)


def test_guard_fallback_matches_loop():
    """A state that could overflow a uint64 lane must fall back to the
    spec loop — and the fallback result must equal a forced-loop run."""
    spec, state = _altair_state("altair", seed=29)
    # big inactivity score: eff * score overflows the intermediate uint64
    # lane (trips the engine's guard) while the final penalty still fits,
    # so the per-validator spec loop processes the state normally
    state.inactivity_scores[3] = 10**9
    s_loop, s_vec = state.copy(), state.copy()
    ek.use_loops()
    spec.process_rewards_and_penalties(s_loop)
    ek.use_vectorized()
    with counting() as delta:
        spec.process_rewards_and_penalties(s_vec)
    assert delta["epoch.fallbacks{reason=guard}"] == 1
    assert hash_tree_root(s_loop) == hash_tree_root(s_vec)


@pytest.mark.parametrize("store_on", [True, False])
def test_registry_poisoning_mid_epoch(store_on):
    """Cache-poisoning regression (the PR-4-review bug shape): mutate
    the registry through the SSZ sequence API BETWEEN kernel reads of
    one epoch, with warm columns.  The next kernel read must see fresh
    columns — the StateArrays store revalidates against the sequence
    mutation generation (store on) or re-extracts per call (store off);
    a stale snapshot would keep validator 5's old effective balance and
    commit a divergent post-state."""
    (state_arrays.use_arrays if store_on else state_arrays.use_fallback)()
    spec, state = _altair_state("altair", seed=43)
    s_loop, s_vec = state.copy(), state.copy()

    ek.use_vectorized()
    with counting() as delta:
        assert ek.try_process_rewards_and_penalties(spec, s_vec)
    assert delta["cache.miss{cache=state_arrays}"] > 0   # columns warmed
    # poison: a raw SSZ write the engine never saw
    s_vec.validators[5].effective_balance = spec.EFFECTIVE_BALANCE_INCREMENT
    assert ek.try_process_effective_balance_updates(spec, s_vec)

    ek.use_loops()
    spec.process_rewards_and_penalties(s_loop)
    s_loop.validators[5].effective_balance = spec.EFFECTIVE_BALANCE_INCREMENT
    spec.process_effective_balance_updates(s_loop)

    assert hash_tree_root(s_loop) == hash_tree_root(s_vec), \
        f"store_on={store_on}: stale registry columns after SSZ mutation"


def test_env_flag_disables_auto(monkeypatch):
    spec, state = _altair_state("altair", seed=31)
    monkeypatch.setenv("CS_TPU_VECTORIZED_EPOCH", "0")
    ek.use_auto()
    assert not ek.enabled()
    assert ek.backend_name() == "loops"
    assert not ek.try_process_rewards_and_penalties(spec, state)
    # the live switch must flip back on without a reimport — asserted
    # with an explicit "1" so the test also holds on the
    # CS_TPU_VECTORIZED_EPOCH=0 CI off-leg, where the import-time
    # default (what an unset variable falls back to) is off
    monkeypatch.setenv("CS_TPU_VECTORIZED_EPOCH", "1")
    assert ek.enabled()
    assert ek.backend_name() == "vectorized"
    # unset restores the import-time default, whatever it was
    monkeypatch.delenv("CS_TPU_VECTORIZED_EPOCH")
    from consensus_specs_tpu.utils import env_flags
    assert ek.enabled() == \
        env_flags._SWITCH_DEFAULTS["CS_TPU_VECTORIZED_EPOCH"]


def test_registry_churn_pressure():
    """More ejections and activations than one epoch's churn: the
    incremental exit-queue simulation must match the spec recurrence."""
    spec = _spec("deneb")
    state = _genesis(spec)
    ek.use_loops()
    for _ in range(3):
        next_epoch(spec, state)
    state.finalized_checkpoint.epoch = spec.get_previous_epoch(state) - 1
    far = spec.FAR_FUTURE_EPOCH
    for i in range(len(state.validators)):
        v = state.validators[i]
        if i % 3 == 0:
            v.effective_balance = spec.config.EJECTION_BALANCE  # eject
        elif i % 3 == 1:
            v.activation_epoch = far                            # activate
            v.activation_eligibility_epoch = \
                state.finalized_checkpoint.epoch
    _scatter_participation(spec, state, Random(37))
    _assert_function_equivalence(spec, state, ["process_registry_updates"])


def test_write_back_wholesale_matches_targeted():
    """Both _write_u64_list strategies (targeted ``__setitem__`` vs
    wholesale item swap, dedup-pool and direct-build variants) must
    produce the same list content and root as plain per-index writes."""
    BalanceList = List[uint64, 1 << 40]
    rng = Random(41)
    n = 512
    base = [rng.randrange(0, 2**40) for _ in range(n)]

    def reference(new_vals):
        ref = BalanceList(base)
        for i, v in enumerate(new_vals):
            ref[i] = uint64(v)
        return hash_tree_root(ref)

    # targeted: a handful of changes
    few = list(base)
    few[3], few[200] = few[3] + 1, 0
    # wholesale + dedup pool: everything changes, few distinct values
    pooled = [base[i] % 5 for i in range(n)]
    # wholesale direct: everything changes, all-distinct values
    distinct = [base[i] + i + 1 for i in range(n)]
    for new_vals in (few, pooled, distinct):
        seq = BalanceList(base)
        ek._write_u64_list(
            seq, uint64,
            np.array(base, dtype=np.uint64), np.array(new_vals, dtype=np.uint64))
        assert [int(x) for x in seq] == [int(v) for v in new_vals]
        assert hash_tree_root(seq) == reference(new_vals)


def test_kernels_jit_under_jax():
    """The pure kernels must produce identical uint64 lanes under
    ``jax.jit`` (device dispatch path) as under numpy."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp
    prev_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        rng = np.random.default_rng(43)
        n = 256
        balances = rng.integers(0, 2**35, n, dtype=np.uint64)
        rewards = rng.integers(0, 2**20, n, dtype=np.uint64)
        penalties = rng.integers(0, 2**36, n, dtype=np.uint64)
        eff = rng.integers(1, 32, n, dtype=np.uint64) * np.uint64(10**9)
        scores = rng.integers(0, 50, n, dtype=np.uint64)
        eligible = rng.random(n) < 0.8
        participating = rng.random(n) < 0.6

        host = ek.apply_deltas_kernel(np, balances, rewards, penalties)
        dev = jax.jit(lambda b, r, p: ek.apply_deltas_kernel(jnp, b, r, p))(
            balances, rewards, penalties)
        np.testing.assert_array_equal(host, np.asarray(dev))

        kw = dict(increment=10**9, downward_threshold=2 * 10**8,
                  upward_threshold=5 * 10**8,
                  max_effective_balance=32 * 10**9)
        host = ek.effective_balance_kernel(np, balances, eff, **kw)
        dev = jax.jit(lambda b, e: ek.effective_balance_kernel(
            jnp, b, e, **kw))(balances, eff)
        np.testing.assert_array_equal(host, np.asarray(dev))

        kw = dict(bias=4, recovery_rate=16, in_leak=False)
        host = ek.inactivity_updates_kernel(
            np, scores, eligible, participating, **kw)
        dev = jax.jit(lambda s, e, p: ek.inactivity_updates_kernel(
            jnp, s, e, p, **kw))(scores, eligible, participating)
        np.testing.assert_array_equal(host, np.asarray(dev))
    finally:
        jax.config.update("jax_enable_x64", prev_x64)


def test_install_vectorized_epoch_idempotent():
    calls = []

    class FakeSpec:
        fork = "phase0"

        def process_slashings(self, state):
            calls.append("loop")

    ek.install_vectorized_epoch(FakeSpec)
    wrapped_once = FakeSpec.__dict__["process_slashings"]
    ek.install_vectorized_epoch(FakeSpec)
    assert FakeSpec.__dict__["process_slashings"] is wrapped_once
    assert wrapped_once._vectorized_epoch_wrapper

    ek.use_loops()     # dispatch declines -> the original body runs
    FakeSpec().process_slashings(None)
    assert calls == ["loop"]


def test_compiled_ladder_vectorized_differential():
    """``install_vectorized_epoch`` routes the engine into the markdown-
    compiled ladder (``use_compiled_registry`` wiring): the wrapped
    compiled altair spec must commit the same full-epoch post-state
    through the array engine as through its verbatim-emitted loops."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    subprocess.run([sys.executable, "-m", "consensus_specs_tpu.compiler"],
                   check=True, cwd=repo, capture_output=True)
    from consensus_specs_tpu.config import load_config, load_preset
    from consensus_specs_tpu.forks.compiled.altair import CompiledAltairSpec
    # wrap the whole lineage: process_effective_balance_updates lives on
    # the compiled phase0 base, not on the altair class itself
    for klass in CompiledAltairSpec.__mro__:
        if isinstance(klass.__dict__.get("fork"), str):
            ek.install_vectorized_epoch(klass)
    spec = CompiledAltairSpec(load_preset("minimal"), load_config("minimal"),
                              preset_name="minimal")
    state = _genesis(spec)
    ek.use_loops()
    for _ in range(3):
        next_epoch(spec, state)
    state.finalized_checkpoint.epoch = spec.get_previous_epoch(state) - 1
    rng = Random(47)
    _scatter_registry_edges(spec, state, rng)
    _scatter_participation(spec, state, rng)
    s_loop, s_vec = state.copy(), state.copy()
    ek.use_loops()
    spec.process_epoch(s_loop)
    ek.use_vectorized()
    with counting() as delta:
        spec.process_epoch(s_vec)
    assert delta["epoch.transition{path=vectorized}"] > 0, \
        "compiled ladder never dispatched to the vectorized engine"
    assert hash_tree_root(s_loop) == hash_tree_root(s_vec), \
        "compiled-ladder post-state roots diverge"


def test_epoch_ordering_covers_vectorized_fns():
    """Every function the engine vectorizes appears in each fork's
    epoch ordering (guards the dispatch wiring against reorderings)."""
    for fork in PHASE0_FAMILY + ALTAIR_FAMILY:
        calls = get_process_calls(_spec(fork))
        expected = VECTORIZED_FNS if fork in PHASE0_FAMILY \
            else ALTAIR_VECTORIZED_FNS
        for fn in expected:
            assert fn in calls, (fork, fn)


# ---------------------------------------------------------------------------
# speclint uint64-hazard regressions: the real findings the U1xx pass
# surfaced in ops/epoch_kernels.py, each pinned against the spec-loop
# oracle at the shape that makes the fixed/annotated line load-bearing
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fork", ["phase0", "deneb"])
def test_registry_mass_ejection_sum_dtype_regression(fork):
    """Pins the explicit-dtype reductions in ``_registry_updates``
    (active-set churn limit and exit-queue churn counter, both formerly
    dtype-less bool ``.sum()``s): eject half the registry so the churn
    recurrence advances ``queue_epoch`` repeatedly — every advance
    consumes both counts — and require bit-identical post-state."""
    spec = _spec(fork)
    state = _genesis(spec)
    ek.use_loops()
    next_epoch(spec, state)
    for i in range(0, len(state.validators), 2):
        state.validators[i].effective_balance = spec.config.EJECTION_BALANCE
    s_loop, s_vec = state.copy(), state.copy()
    ek.use_loops()
    spec.process_registry_updates(s_loop)
    ek.use_vectorized()
    with counting() as delta:
        spec.process_registry_updates(s_vec)
    assert delta["epoch.transition{path=vectorized}"] == 1
    assert delta["epoch.fallbacks{reason=guard}"] == 0
    assert hash_tree_root(s_loop) == hash_tree_root(s_vec)
    # the queue really did saturate: ejections spread over >= 2 epochs,
    # so the per-epoch churn counter (the second fixed sum) was consumed
    exits = {int(v.exit_epoch) for v in s_vec.validators
             if v.exit_epoch != spec.FAR_FUTURE_EPOCH}
    assert len(exits) >= 2


def test_phase0_minimal_balance_reward_bounds_regression():
    """Pins the ``max_attester = base_reward - proposer_reward``
    unsigned subtraction (# noqa: U101): at one-increment effective
    balances ``base_reward`` is at its minimum and the proposer cut
    rounds to its extreme relative value — the lane must not wrap."""
    spec, state = _phase0_state("phase0", seed=31)
    for i in range(len(state.validators)):
        state.validators[i].effective_balance = \
            spec.EFFECTIVE_BALANCE_INCREMENT
    _assert_function_equivalence(spec, state,
                                 ["process_rewards_and_penalties"])


def test_phase0_leak_minimal_balance_base_pen_regression():
    """Pins the ``base_pen = BASE_REWARDS_PER_EPOCH * base_reward -
    proposer_reward`` unsigned subtraction (# noqa: U101), which only
    runs in an inactivity leak, at minimum-balance extremes."""
    spec = _spec("phase0")
    state = _genesis(spec)
    ek.use_loops()
    next_epoch(spec, state)
    _, _, state = next_epoch_with_attestations(spec, state, True, False)
    for _ in range(6):     # let finality lapse into a leak
        next_epoch(spec, state)
    _, _, state = next_epoch_with_attestations(spec, state, True, False)
    for i in range(len(state.validators)):
        state.validators[i].effective_balance = \
            spec.EFFECTIVE_BALANCE_INCREMENT
    assert spec.is_in_inactivity_leak(state)
    _assert_function_equivalence(spec, state,
                                 ["process_rewards_and_penalties"])
