"""Deneb registry updates: EIP-7514 activation-churn cap.

Reference model:
``test/deneb/epoch_processing/test_process_registry_updates.py`` against
``specs/deneb/beacon-chain.md`` (``get_validator_activation_churn_limit``
= min(MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT, churn)).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases,
)



def _queue_n_eligible(spec, state, n):
    """Make the first n validators eligible for activation dequeue:
    eligibility epoch 0 <= the genesis finalized epoch, activation
    still unset."""
    indices = []
    for i in range(n):
        v = state.validators[i]
        v.activation_eligibility_epoch = 0
        v.activation_epoch = spec.FAR_FUTURE_EPOCH
        indices.append(i)
    return indices


@with_phases(["deneb"])
@spec_state_test
def test_activation_churn_is_capped(spec, state):
    """More eligible validators than the churn: only the (EIP-7514
    capped) activation-churn's worth dequeue per sweep."""
    cap = int(spec.MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT)
    n = cap + 3
    indices = _queue_n_eligible(spec, state, n)
    limit = int(spec.get_validator_activation_churn_limit(state))

    yield "pre", state
    spec.process_registry_updates(state)
    yield "post", state

    activated = [i for i in indices
                 if state.validators[i].activation_epoch
                 != spec.FAR_FUTURE_EPOCH]
    assert len(activated) == min(n, limit)
    assert len(activated) <= cap


@with_phases(["deneb"])
@spec_state_test
def test_activation_churn_limit_value(spec, state):
    """The deneb limit is the capella churn clamped by the EIP-7514 cap."""
    base = spec.get_validator_churn_limit(state)
    got = spec.get_validator_activation_churn_limit(state)
    assert got == min(spec.MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT, base)
