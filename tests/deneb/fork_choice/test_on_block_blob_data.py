"""Deneb fork-choice blob data-availability tests.

Reference model: ``test/deneb/fork_choice/test_on_block.py`` with the
``retrieve_blobs_and_proofs`` stub swapped per scenario
(``specs/deneb/fork-choice.md:53-60``).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, never_bls, pytest_only,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store_and_block, tick_and_add_block,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root


@with_phases(["deneb"])
@spec_state_test
@never_bls
def test_on_block_no_commitments_is_available(spec, state):
    """No blob commitments: the empty batch verifies (md:571 'True if
    there are zero blobs')."""
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state.copy(), block)
    test_steps = []
    tick_and_add_block(spec, store, signed_block, test_steps)
    assert hash_tree_root(signed_block.message) in store.blocks
    yield "steps", test_steps


@with_phases(["deneb"])
@spec_state_test
@never_bls
def test_invalid_on_block_data_unavailable(spec, state):
    """Commitments present but blobs unretrievable: on_block must reject
    (is_data_available raises/fails)."""
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.blob_kzg_commitments = [spec.G1_POINT_AT_INFINITY]
    signed_block = state_transition_and_sign_block(spec, state.copy(), block)

    def retrieve_blobs_and_proofs(beacon_block_root):
        raise AssertionError("blobs not available")

    spec.retrieve_blobs_and_proofs = retrieve_blobs_and_proofs
    try:
        test_steps = []
        tick_and_add_block(spec, store, signed_block, test_steps,
                           valid=False)
        assert hash_tree_root(signed_block.message) not in store.blocks
    finally:
        del spec.retrieve_blobs_and_proofs
    yield "steps", test_steps


@with_phases(["deneb"])
@spec_state_test
@never_bls
def test_invalid_on_block_mismatched_blob_count(spec, state):
    """Commitment count != retrieved blob count fails batch verification."""
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.blob_kzg_commitments = [spec.G1_POINT_AT_INFINITY]
    signed_block = state_transition_and_sign_block(spec, state.copy(), block)

    spec.retrieve_blobs_and_proofs = lambda root: ([], [])
    try:
        test_steps = []
        tick_and_add_block(spec, store, signed_block, test_steps,
                           valid=False)
    finally:
        del spec.retrieve_blobs_and_proofs
    yield "steps", test_steps


@with_phases(["deneb"])
@spec_state_test
@pytest_only
def test_on_block_accepted_when_blobs_available(spec, state):
    """With a real blob + commitment + proof wired into retrieval, the
    availability gate passes and the block enters the store."""
    from consensus_specs_tpu.ops import kzg as K
    test_steps = []
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    setup = K.trusted_setup(spec.preset_name)
    blob = b"".join(
        (i % 255).to_bytes(32, "big")
        for i in range(setup.FIELD_ELEMENTS_PER_BLOB))
    commitment = K.blob_to_kzg_commitment(blob, setup)
    proof = K.compute_blob_kzg_proof(blob, commitment, setup)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.blob_kzg_commitments.append(commitment)
    signed = state_transition_and_sign_block(spec, state, block)
    spec.retrieve_blobs_and_proofs = lambda root: ([blob], [proof])
    try:
        assert spec.is_data_available(
            hash_tree_root(block), block.body.blob_kzg_commitments)
        tick_and_add_block(spec, store, signed, test_steps)
    finally:
        del spec.__dict__["retrieve_blobs_and_proofs"]
    assert hash_tree_root(block) in store.blocks
    yield "steps", test_steps
