"""Deneb light-client merkle proofs + blob-gas header rules.

Reference model: ``test/deneb/light_client/test_single_merkle_proof.py``
against ``specs/deneb/light-client/sync-protocol.md`` (execution header
gains blob_gas_used/excess_blob_gas; pre-deneb headers must zero them).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_config_overrides,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
)
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, compute_merkle_proof,
)

DENEB_ONLY = with_phases(["deneb"])
deneb_lc_active = with_config_overrides({
    "ALTAIR_FORK_EPOCH": 0, "BELLATRIX_FORK_EPOCH": 0,
    "CAPELLA_FORK_EPOCH": 0, "DENEB_FORK_EPOCH": 0,
})


@DENEB_ONLY
@spec_state_test
def test_execution_merkle_proof(spec, state):
    from consensus_specs_tpu.forks.light_client import floorlog2
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    body = signed_block.message.body
    gindex = spec.EXECUTION_PAYLOAD_GINDEX
    proof = compute_merkle_proof(body, gindex)
    leaf = hash_tree_root(body.execution_payload)
    yield "object", body
    yield "proof", {
        "leaf": "0x" + bytes(leaf).hex(),
        "leaf_index": int(gindex),
        "branch": ["0x" + bytes(b).hex() for b in proof],
    }
    assert spec.is_valid_merkle_branch(
        leaf=leaf, branch=proof, depth=floorlog2(gindex),
        index=spec.get_subtree_index(gindex), root=hash_tree_root(body))


@DENEB_ONLY
@spec_state_test
def test_next_sync_committee_merkle_proof_deneb_state(spec, state):
    from consensus_specs_tpu.forks.light_client import floorlog2
    gindex = spec.NEXT_SYNC_COMMITTEE_GINDEX
    proof = compute_merkle_proof(state, gindex)
    assert spec.is_valid_merkle_branch(
        leaf=hash_tree_root(state.next_sync_committee), branch=proof,
        depth=floorlog2(gindex), index=spec.get_subtree_index(gindex),
        root=hash_tree_root(state))
    yield


@DENEB_ONLY
@deneb_lc_active
@spec_state_test
def test_header_with_blob_gas_fields(spec, state):
    """Deneb headers carry blob-gas fields through the LC header."""
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    header = spec.block_to_light_client_header(signed_block)
    assert spec.is_valid_light_client_header(header)
    assert header.execution.blob_gas_used == \
        signed_block.message.body.execution_payload.blob_gas_used


@DENEB_ONLY
@with_config_overrides({
    "ALTAIR_FORK_EPOCH": 0, "BELLATRIX_FORK_EPOCH": 0,
    "CAPELLA_FORK_EPOCH": 0, "DENEB_FORK_EPOCH": 4})
@spec_state_test
def test_pre_deneb_header_must_zero_blob_gas(spec, state):
    """Headers dated before DENEB_FORK_EPOCH must zero the blob-gas
    fields (sync-protocol.md Modified is_valid_light_client_header)."""
    header = spec.LightClientHeader()
    header.beacon.slot = 0  # epoch 0 < DENEB_FORK_EPOCH=4, >= capella
    # capella-era rules apply: execution branch must prove the leaf; an
    # empty header with blob gas set is invalid before proof checking
    header.execution.blob_gas_used = 1
    assert not spec.is_valid_light_client_header(header)
