"""Deneb light-client sync-protocol tests: headers with blob-gas fields
through the store machinery.

Reference model: ``test/altair/light_client/test_sync.py`` shapes run at
the deneb fork against ``specs/deneb/light-client/sync-protocol.md``
(execution header gains ``blob_gas_used``/``excess_blob_gas``; both must
be zero for pre-deneb headers).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_config_overrides, always_bls,
    never_bls,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.sync_committee import (
    compute_aggregate_sync_committee_signature, compute_committee_indices,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root

deneb_lc_active = with_config_overrides({
    "ALTAIR_FORK_EPOCH": 0, "BELLATRIX_FORK_EPOCH": 0,
    "CAPELLA_FORK_EPOCH": 0, "DENEB_FORK_EPOCH": 0,
})


def _advance_chain(spec, state, n_blocks):
    out = []
    for _ in range(n_blocks):
        block = build_empty_block_for_next_slot(spec, state)
        signed = state_transition_and_sign_block(spec, state, block)
        out.append((signed, state.copy()))
    return out


def _signed_sync_aggregate(spec, signing_state, attested_root,
                           signature_slot):
    committee_indices = compute_committee_indices(signing_state)
    bits = [True] * len(committee_indices)
    signature = compute_aggregate_sync_committee_signature(
        spec, signing_state, signature_slot - 1, committee_indices,
        block_root=attested_root)
    return spec.SyncAggregate(sync_committee_bits=bits,
                              sync_committee_signature=signature)


def _bootstrap_store(spec, chain):
    signed_block, post_state = chain[0]
    bootstrap = spec.create_light_client_bootstrap(post_state, signed_block)
    trusted_root = hash_tree_root(signed_block.message)
    return spec.initialize_light_client_store(trusted_root, bootstrap)


@with_phases(["deneb"])
@deneb_lc_active
@spec_state_test
@never_bls
def test_bootstrap_header_carries_blob_gas(spec, state):
    """A deneb bootstrap header validates with its blob-gas fields and
    fails once they are tampered (the inclusion branch covers them)."""
    chain = _advance_chain(spec, state, 1)
    store = _bootstrap_store(spec, chain)
    header = store.finalized_header
    assert spec.is_valid_light_client_header(header)
    bad = header.copy()
    bad.execution.blob_gas_used += 1
    assert not spec.is_valid_light_client_header(bad)


@with_phases(["deneb"])
@deneb_lc_active
@spec_state_test
@always_bls
def test_process_light_client_update_deneb(spec, state):
    chain = _advance_chain(spec, state, 2)
    store = _bootstrap_store(spec, chain)
    attested_block, attested_state = chain[1]

    attested_header = spec.block_to_light_client_header(attested_block)
    assert spec.is_valid_light_client_header(attested_header)
    signature_slot = attested_block.message.slot + 1
    sync_aggregate = _signed_sync_aggregate(
        spec, attested_state, hash_tree_root(attested_block.message),
        signature_slot)
    update = spec.LightClientUpdate(
        attested_header=attested_header,
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot,
    )
    spec.process_light_client_update(
        store, update, signature_slot,
        attested_state.genesis_validators_root)
    assert store.optimistic_header.beacon.slot == attested_block.message.slot
    assert store.optimistic_header.execution.excess_blob_gas == \
        attested_header.execution.excess_blob_gas


@with_phases(["deneb"])
@deneb_lc_active
@spec_state_test
@always_bls
def test_update_with_tampered_blob_gas_rejected(spec, state):
    chain = _advance_chain(spec, state, 2)
    store = _bootstrap_store(spec, chain)
    attested_block, attested_state = chain[1]

    attested_header = spec.block_to_light_client_header(attested_block)
    attested_header.execution.excess_blob_gas += 1  # breaks inclusion
    signature_slot = attested_block.message.slot + 1
    sync_aggregate = _signed_sync_aggregate(
        spec, attested_state, hash_tree_root(attested_block.message),
        signature_slot)
    update = spec.LightClientUpdate(
        attested_header=attested_header,
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot,
    )
    try:
        spec.process_light_client_update(
            store, update, signature_slot,
            attested_state.genesis_validators_root)
        raise SystemExit("tampered deneb header must be rejected")
    except AssertionError:
        pass
