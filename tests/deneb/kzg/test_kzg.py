"""KZG polynomial-commitment library tests.

Reference model: ``tests/generators/kzg_4844/main.py`` cases against
``specs/deneb/polynomial-commitments.md``.  The mathematical identity
tests (constant/linear blobs) pin the trusted-setup loading, bit-reversal
permutation and MSM independently of the proof machinery.
"""
import pytest

from consensus_specs_tpu.ops import kzg as K
from consensus_specs_tpu.ops.bls12_381.curve import (
    G1_GENERATOR, g1_from_compressed)

SETUP = K.trusted_setup("minimal")
WIDTH = SETUP.FIELD_ELEMENTS_PER_BLOB
BLS_MODULUS = K.BLS_MODULUS


def _fe(x):
    return (x % BLS_MODULUS).to_bytes(32, "big")


def _blob_from_values(values):
    assert len(values) == WIDTH
    return b"".join(_fe(v) for v in values)


def _random_blob(seed):
    rng = __import__("random").Random(seed)
    return _blob_from_values([rng.randrange(BLS_MODULUS)
                              for _ in range(WIDTH)])


# Commitments/proofs over the fixed random blobs, shared across tests:
# each blob_to_kzg_commitment / compute_blob_kzg_proof is a 4096-point
# host MSM (~5-10 s on a 1-core box), so recomputing them per test
# dominated the suite's KZG slice.
_COMMIT_MEMO = {}


def _commitment(seed):
    if ("c", seed) not in _COMMIT_MEMO:
        _COMMIT_MEMO[("c", seed)] = K.blob_to_kzg_commitment(
            _random_blob(seed), SETUP)
    return _COMMIT_MEMO[("c", seed)]


def _blob_proof(seed):
    if ("p", seed) not in _COMMIT_MEMO:
        _COMMIT_MEMO[("p", seed)] = K.compute_blob_kzg_proof(
            _random_blob(seed), _commitment(seed), SETUP)
    return _COMMIT_MEMO[("p", seed)]


# ---------------------------------------------------------------------------
# structural helpers
# ---------------------------------------------------------------------------

def test_reverse_bits():
    assert K.reverse_bits(0, 8) == 0
    assert K.reverse_bits(1, 8) == 4
    assert K.reverse_bits(3, 8) == 6
    assert K.bit_reversal_permutation([0, 1, 2, 3]) == [0, 2, 1, 3]


def test_roots_of_unity():
    roots = K.compute_roots_of_unity(WIDTH)
    assert len(roots) == WIDTH
    assert roots[0] == 1
    w = roots[1]
    assert pow(w, WIDTH, BLS_MODULUS) == 1
    assert pow(w, WIDTH // 2, BLS_MODULUS) == BLS_MODULUS - 1


def test_bytes_to_bls_field_rejects_modulus():
    with pytest.raises(AssertionError):
        K.bytes_to_bls_field(BLS_MODULUS.to_bytes(32, "big"))
    assert K.bytes_to_bls_field(_fe(BLS_MODULUS - 1)) == BLS_MODULUS - 1


def test_validate_kzg_g1():
    K.validate_kzg_g1(K.G1_POINT_AT_INFINITY)       # infinity allowed
    K.validate_kzg_g1(G1_GENERATOR.to_compressed())  # generator fine
    with pytest.raises(Exception):
        K.validate_kzg_g1(b"\x12" * 48)              # garbage rejected


def test_g1_lincomb_small():
    """MSM vs naive scalar arithmetic on tiny inputs."""
    pts = [G1_GENERATOR.mult(3).to_compressed(),
           G1_GENERATOR.mult(5).to_compressed()]
    out = K.g1_lincomb(pts, [7, 11])
    assert out == G1_GENERATOR.mult(3 * 7 + 5 * 11).to_compressed()
    # empty MSM = point at infinity
    assert K.g1_lincomb([], []) == K.G1_POINT_AT_INFINITY


# ---------------------------------------------------------------------------
# commitment identities (validate setup + brp + MSM end to end)
# ---------------------------------------------------------------------------

def test_constant_blob_commitment_is_c_times_g():
    """sum_i L_i(tau) = 1 so commit(c,...,c) == [c]G."""
    c = 0x1234
    blob = _blob_from_values([c] * WIDTH)
    commitment = K.blob_to_kzg_commitment(blob, SETUP)
    assert commitment == G1_GENERATOR.mult(c).to_compressed()


def test_linear_blob_commitment_matches_monomial_setup():
    """p(X) = a*X + b evaluated on the brp domain must commit to
    a*[tau]G + b*G (checks Lagrange<->monomial consistency of the setup)."""
    a, b = 3, 10
    roots_brp = K.bit_reversal_permutation(
        list(K.compute_roots_of_unity(WIDTH)))
    blob = _blob_from_values([(a * w + b) % BLS_MODULUS for w in roots_brp])
    commitment = K.blob_to_kzg_commitment(blob, SETUP)
    tau_g = g1_from_compressed(SETUP.KZG_SETUP_G1_MONOMIAL[1])
    expect = (tau_g.mult(a) + G1_GENERATOR.mult(b)).to_compressed()
    assert commitment == expect


def test_evaluate_polynomial_in_evaluation_form():
    """Barycentric evaluation of a linear polynomial is exact everywhere."""
    a, b = 5, 9
    roots_brp = K.bit_reversal_permutation(
        list(K.compute_roots_of_unity(WIDTH)))
    poly = [(a * w + b) % BLS_MODULUS for w in roots_brp]
    # in-domain: indexing shortcut
    assert K.evaluate_polynomial_in_evaluation_form(
        poly, roots_brp[5], WIDTH) == poly[5]
    # out-of-domain: barycentric formula
    z = 98765
    assert K.evaluate_polynomial_in_evaluation_form(
        poly, z, WIDTH) == (a * z + b) % BLS_MODULUS


# ---------------------------------------------------------------------------
# proof round trips
# ---------------------------------------------------------------------------

def test_compute_and_verify_kzg_proof():
    blob = _random_blob(42)
    commitment = _commitment(42)
    z = _fe(123456789)
    proof, y = K.compute_kzg_proof(blob, z, SETUP)
    assert K.verify_kzg_proof(commitment, z, y, proof, SETUP)
    # wrong claimed y fails
    bad_y = _fe(K.bytes_to_bls_field(y) + 1)
    assert not K.verify_kzg_proof(commitment, z, bad_y, proof, SETUP)


def test_compute_kzg_proof_in_domain_point():
    """z on a root of unity exercises the special-case quotient."""
    blob = _random_blob(7)
    commitment = _commitment(7)
    roots_brp = K.bit_reversal_permutation(
        list(K.compute_roots_of_unity(WIDTH)))
    z = _fe(roots_brp[3])
    proof, y = K.compute_kzg_proof(blob, z, SETUP)
    # in-domain evaluation is just the blob element
    assert K.bytes_to_bls_field(y) == K.blob_to_polynomial(blob, WIDTH)[3]
    assert K.verify_kzg_proof(commitment, z, y, proof, SETUP)


def test_verify_blob_kzg_proof_roundtrip():
    blob = _random_blob(1)
    commitment = _commitment(1)
    proof = _blob_proof(1)
    assert K.verify_blob_kzg_proof(blob, commitment, proof, SETUP)
    assert not K.verify_blob_kzg_proof(blob, commitment,
                                       K.G1_POINT_AT_INFINITY, SETUP)


def test_verify_blob_kzg_proof_batch():
    blobs = [_random_blob(i) for i in range(2)]
    commitments = [_commitment(i) for i in range(2)]
    proofs = [_blob_proof(i) for i in range(2)]
    assert K.verify_blob_kzg_proof_batch(blobs, commitments, proofs, SETUP)
    # swapped proofs must fail
    assert not K.verify_blob_kzg_proof_batch(
        blobs, commitments, proofs[::-1], SETUP)
    # empty batch verifies
    assert K.verify_blob_kzg_proof_batch([], [], [], SETUP)
