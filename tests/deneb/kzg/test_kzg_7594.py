"""EIP-7594 sampling tests: FFT, cells, multiproofs, erasure recovery.

Reference model: the eip7594 test surface against
``specs/_features/eip7594/polynomial-commitments-sampling.md``.
"""
import random

import pytest

from consensus_specs_tpu.ops import kzg as K
from consensus_specs_tpu.ops import kzg_7594 as S

SETUP = K.trusted_setup("minimal")
WIDTH = SETUP.FIELD_ELEMENTS_PER_BLOB
EXT = 2 * WIDTH
BLS_MODULUS = K.BLS_MODULUS


def _random_blob(seed):
    rng = random.Random(seed)
    return b"".join(rng.randrange(BLS_MODULUS).to_bytes(32, "big")
                    for _ in range(WIDTH))


def _cells_to_bytes(cell):
    return [int(x).to_bytes(32, "big") for x in cell]


def test_fft_roundtrip():
    rng = random.Random(11)
    vals = [rng.randrange(BLS_MODULUS) for _ in range(256)]
    roots = list(K.compute_roots_of_unity(256))
    freq = S.fft_field(vals, roots)
    back = S.fft_field(freq, roots, inv=True)
    assert back == vals


def test_fft_matches_direct_evaluation():
    """FFT output i must equal p(w^i) for the coefficient polynomial."""
    rng = random.Random(12)
    coeffs = [rng.randrange(BLS_MODULUS) for _ in range(64)]
    roots = list(K.compute_roots_of_unity(64))
    freq = S.fft_field(coeffs, roots)
    for i in (0, 1, 5, 63):
        assert freq[i] == S.evaluate_polynomialcoeff(coeffs, roots[i])


def test_polynomial_arithmetic():
    a = [1, 2, 3]
    b = [5, 7]
    prod = S.multiply_polynomialcoeff(a, b)
    # (1+2x+3x^2)(5+7x) = 5 + 17x + 29x^2 + 21x^3
    assert prod == [5, 17, 29, 21]
    quot = S.divide_polynomialcoeff(prod, b)
    assert quot == [1, 2, 3]
    z = 9
    assert S.evaluate_polynomialcoeff(prod, z) == \
        S.evaluate_polynomialcoeff(a, z) * S.evaluate_polynomialcoeff(b, z) \
        % BLS_MODULUS


def test_interpolation_and_vanishing():
    rng = random.Random(13)
    xs = [rng.randrange(BLS_MODULUS) for _ in range(6)]
    ys = [rng.randrange(BLS_MODULUS) for _ in range(6)]
    poly = S.interpolate_polynomialcoeff(xs, ys)
    for x, y in zip(xs, ys):
        assert S.evaluate_polynomialcoeff(poly, x) == y
    vanish = S.vanishing_polynomialcoeff(xs)
    for x in xs:
        assert S.evaluate_polynomialcoeff(vanish, x) == 0


def test_compute_cells_extends_the_blob():
    """First half of the (de-brp'd) extended data = original evaluations."""
    blob = _random_blob(21)
    cells = S.compute_cells(blob, SETUP)
    assert len(cells) == S.cells_per_blob(SETUP)
    flat_rbo = [x for cell in cells for x in cell]
    extended = S.fft_field(
        K.bit_reversal_permutation(flat_rbo),
        list(K.compute_roots_of_unity(EXT)), inv=False)
    # instead of comparing domains directly, interpolate back: the
    # extended evaluations must agree with the original polynomial
    polynomial = K.blob_to_polynomial(blob, WIDTH)
    coeffs = S.polynomial_eval_to_coeff(polynomial, SETUP)
    roots_ext = list(K.compute_roots_of_unity(EXT))
    brp_ext = K.bit_reversal_permutation(list(range(EXT)))
    for probe in (0, 1, 77, EXT - 1):
        idx = brp_ext[probe]
        assert flat_rbo[probe] == S.evaluate_polynomialcoeff(
            coeffs, roots_ext[idx])


def test_cell_multiproof_verifies():
    blob = _random_blob(22)
    commitment = K.blob_to_kzg_commitment(blob, SETUP)
    polynomial = K.blob_to_polynomial(blob, WIDTH)
    coeffs = S.polynomial_eval_to_coeff(polynomial, SETUP)
    cell_id = 3
    coset = S.coset_for_cell(cell_id, SETUP)
    proof, ys = S.compute_kzg_proof_multi_impl(coeffs, coset, SETUP)
    assert S.verify_cell_proof(commitment, cell_id, _cells_to_bytes(ys),
                               proof, SETUP)
    # tampered cell data must fail
    bad = list(ys)
    bad[0] = (bad[0] + 1) % BLS_MODULUS
    assert not S.verify_cell_proof(commitment, cell_id,
                                   _cells_to_bytes(bad), proof, SETUP)
    # batch wrapper
    assert S.verify_cell_proof_batch(
        [commitment], [0], [cell_id], [_cells_to_bytes(ys)], [proof], SETUP)


def test_recover_polynomial_from_half_the_cells():
    blob = _random_blob(23)
    cells = S.compute_cells(blob, SETUP)
    n_cells = S.cells_per_blob(SETUP)
    rng = random.Random(99)
    kept = sorted(rng.sample(range(n_cells), n_cells // 2))
    recovered = S.recover_polynomial(
        kept, [_cells_to_bytes(cells[i]) for i in kept], SETUP)
    full = [x for cell in cells for x in cell]
    assert recovered == full


def test_recover_rejects_insufficient_cells():
    blob = _random_blob(24)
    cells = S.compute_cells(blob, SETUP)
    n_cells = S.cells_per_blob(SETUP)
    kept = list(range(n_cells // 2 - 1))
    with pytest.raises(AssertionError):
        S.recover_polynomial(
            kept, [_cells_to_bytes(cells[i]) for i in kept], SETUP)


def test_recover_rejects_duplicate_cell_ids():
    blob = _random_blob(25)
    cells = S.compute_cells(blob, SETUP)
    n_cells = S.cells_per_blob(SETUP)
    kept = list(range(n_cells // 2))
    kept[-1] = kept[0]      # duplicate id keeps the count at n/2
    with pytest.raises(AssertionError):
        S.recover_polynomial(
            kept, [_cells_to_bytes(cells[i]) for i in kept], SETUP)


def test_bytes_to_cell_flat_length_gate():
    """The flat-bytes cell encoding is exact-length (one cell), like
    the spec body and the engine — a short flat cell must be rejected
    at parse time, not corrupt a recovery slice downstream."""
    with pytest.raises(AssertionError):
        S.bytes_to_cell(b"\x00" * 32)
    full = b"\x00" * (32 * S.FIELD_ELEMENTS_PER_CELL)
    assert S.bytes_to_cell(full) == [0] * S.FIELD_ELEMENTS_PER_CELL
    # the legacy chunk-list form is unaffected
    assert S.bytes_to_cell([b"\x00" * 32]) == [0]


def _g2_lincomb_naive(points, scalars):
    """The pre-PR-11 double-and-add loop, kept as the differential
    oracle for the group-generic Pippenger swap."""
    from consensus_specs_tpu.ops.bls12_381.curve import (
        G2Point, g2_from_compressed)
    result = G2Point.inf()
    for x, a in zip(points, scalars):
        result = result + g2_from_compressed(bytes(x)).mult(
            int(a) % BLS_MODULUS)
    return result.to_compressed()


def test_g2_lincomb_pippenger_matches_naive_loop():
    """curve.msm bucket method vs the old per-point double-and-add —
    byte-identical compressed output, including the edge shapes (empty,
    zero scalars, repeated points).  Forces the python path: the native
    backend serves <= 64 points before Pippenger is reached."""
    import random as _random
    from unittest import mock
    from consensus_specs_tpu.ops import native_bls
    rng = _random.Random(99)
    pts = SETUP.KZG_SETUP_G2_MONOMIAL[:6] + [SETUP.KZG_SETUP_G2_MONOMIAL[2]]
    scalars = [rng.randrange(BLS_MODULUS) for _ in range(5)] + [0, 1]
    with mock.patch.object(native_bls, "available", return_value=False):
        assert S.g2_lincomb(pts, scalars) == \
            _g2_lincomb_naive(pts, scalars)
        assert S.g2_lincomb([], []) == _g2_lincomb_naive(
            [SETUP.KZG_SETUP_G2_MONOMIAL[0]], [0])
    if native_bls.available():
        # and the native path agrees with both
        assert S.g2_lincomb(pts, scalars) == _g2_lincomb_naive(pts, scalars)
