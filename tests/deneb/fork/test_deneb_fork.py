"""upgrade_to_deneb fork tests (``specs/deneb/fork.md:77``)."""
from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, never_bls,
)
from consensus_specs_tpu.test_infra.block import next_epoch
from consensus_specs_tpu.utils.ssz import hash_tree_root


def run_fork_test(post_spec, pre_state):
    yield "pre", pre_state
    post_state = post_spec.upgrade_to_deneb(pre_state)

    for field in ("genesis_time", "genesis_validators_root", "slot",
                  "eth1_deposit_index", "justification_bits",
                  "next_withdrawal_index", "next_withdrawal_validator_index"):
        assert getattr(pre_state, field) == getattr(post_state, field)
    for field in ("block_roots", "state_roots", "historical_roots",
                  "validators", "balances", "randao_mixes", "slashings",
                  "previous_epoch_participation",
                  "current_epoch_participation", "inactivity_scores",
                  "current_sync_committee", "next_sync_committee",
                  "historical_summaries"):
        assert hash_tree_root(getattr(pre_state, field)) == \
            hash_tree_root(getattr(post_state, field))

    assert post_state.fork.previous_version == pre_state.fork.current_version
    assert bytes(post_state.fork.current_version) == \
        bytes(post_spec.config.DENEB_FORK_VERSION)

    post_header = post_state.latest_execution_payload_header
    assert post_header.block_hash == \
        pre_state.latest_execution_payload_header.block_hash
    assert post_header.blob_gas_used == 0
    assert post_header.excess_blob_gas == 0
    yield "post", post_state


@with_phases(["capella"])
@spec_state_test
@never_bls
def test_deneb_fork_basic(spec, state):
    post_spec = build_spec("deneb", spec.preset_name)
    yield from run_fork_test(post_spec, state)


@with_phases(["capella"])
@spec_state_test
@never_bls
def test_deneb_fork_next_epoch(spec, state):
    next_epoch(spec, state)
    post_spec = build_spec("deneb", spec.preset_name)
    yield from run_fork_test(post_spec, state)
