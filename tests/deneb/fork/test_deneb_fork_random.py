"""Randomized-pre-state upgrade_to_deneb tests.

Reference model: ``test/deneb/fork/test_deneb_fork_random.py`` — seeded
random capella states (random participation, balances, leak, large
validator churn) pushed through the fork upgrade, checking the
roots-preserving invariants of ``run_fork_test``.
"""
from random import Random

from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, never_bls,
)
from consensus_specs_tpu.test_infra.block import next_epoch, next_slots
from consensus_specs_tpu.test_infra.random_scenarios import randomize_state
from consensus_specs_tpu.test_infra.rewards import set_state_in_leak

from tests.deneb.fork.test_deneb_fork import run_fork_test

CAPELLA_PRE = with_phases(["capella"])


def _randomized(spec, state, seed, leak=False, exit_fraction=0.05,
                slash_fraction=0.05):
    next_epoch(spec, state)
    next_epoch(spec, state)
    rng = Random(seed)
    randomize_state(spec, state, rng, exit_fraction=exit_fraction,
                    slash_fraction=slash_fraction)
    if leak:
        set_state_in_leak(spec, state)
    return state


@CAPELLA_PRE
@spec_state_test
@never_bls
def test_deneb_fork_random_0(spec, state):
    post_spec = build_spec("deneb", spec.preset_name)
    yield from run_fork_test(post_spec, _randomized(spec, state, 5010))


@CAPELLA_PRE
@spec_state_test
@never_bls
def test_deneb_fork_random_1(spec, state):
    post_spec = build_spec("deneb", spec.preset_name)
    yield from run_fork_test(post_spec, _randomized(spec, state, 5011))


@CAPELLA_PRE
@spec_state_test
@never_bls
def test_deneb_fork_random_2(spec, state):
    post_spec = build_spec("deneb", spec.preset_name)
    yield from run_fork_test(post_spec, _randomized(spec, state, 5012))


@CAPELLA_PRE
@spec_state_test
@never_bls
def test_deneb_fork_random_leak(spec, state):
    post_spec = build_spec("deneb", spec.preset_name)
    yield from run_fork_test(
        post_spec, _randomized(spec, state, 5013, leak=True))


@CAPELLA_PRE
@spec_state_test
@never_bls
def test_deneb_fork_random_heavy_exits(spec, state):
    post_spec = build_spec("deneb", spec.preset_name)
    yield from run_fork_test(
        post_spec,
        _randomized(spec, state, 5014, exit_fraction=0.3,
                    slash_fraction=0.0))


@CAPELLA_PRE
@spec_state_test
@never_bls
def test_deneb_fork_random_heavy_slashes(spec, state):
    post_spec = build_spec("deneb", spec.preset_name)
    yield from run_fork_test(
        post_spec,
        _randomized(spec, state, 5015, exit_fraction=0.0,
                    slash_fraction=0.3))


@CAPELLA_PRE
@spec_state_test
@never_bls
def test_deneb_fork_random_mid_epoch(spec, state):
    """Upgrade landing mid-epoch (not on a boundary slot)."""
    post_spec = build_spec("deneb", spec.preset_name)
    state = _randomized(spec, state, 5016)
    next_slots(spec, state, 3)
    yield from run_fork_test(post_spec, state)
