"""Deneb seeded randomized scenarios: random blocks carrying random blob
commitments (with matching versioned hashes through the payload) on top
of the phase0 random-op mix.

Reference model: ``test/deneb/random/test_random.py`` (16 seeded
scenarios from the randomized_block_tests DSL).
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases,
)
from consensus_specs_tpu.test_infra.block import (
    next_epoch, next_slots, state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.random_scenarios import (
    randomize_state, random_block,
)
from consensus_specs_tpu.test_infra.rewards import set_state_in_leak
from consensus_specs_tpu.utils.ssz import hash_tree_root

DENEB_ONLY = with_phases(["deneb"])


def _skip_slashed_proposers(spec, state):
    """Randomized registries can hand proposer duty to a slashed
    validator, whose block the spec rejects; advance past those slots."""
    probe = state.copy()
    spec.process_slots(probe, probe.slot + 1)
    skipped = 0
    while probe.validators[spec.get_beacon_proposer_index(probe)].slashed:
        spec.process_slots(probe, probe.slot + 1)
        skipped += 1
    if skipped:
        next_slots(spec, state, skipped)


def _random_blob_block(spec, state, rng):
    """A random-op block additionally carrying 0..MAX_BLOBS_PER_BLOCK
    commitments (infinity points: valid commitments whose data the
    NoopExecutionEngine treats as available)."""
    _skip_slashed_proposers(spec, state)
    block = random_block(spec, state, rng)
    n_blobs = rng.randint(0, spec.MAX_BLOBS_PER_BLOCK)
    block.body.blob_kzg_commitments = [spec.G1_POINT_AT_INFINITY] * n_blobs
    return block


def _run_scenario(spec, state, seed, epochs=1, leak=False,
                  blocks_per_epoch=4):
    rng = Random(seed)
    next_epoch(spec, state)
    next_epoch(spec, state)
    if leak:
        set_state_in_leak(spec, state)
    randomize_state(spec, state, rng, exit_fraction=0.05,
                    slash_fraction=0.05)
    yield "pre", state
    signed_blocks = []
    for _ in range(epochs):
        for _ in range(blocks_per_epoch):
            if rng.random() < 0.3:
                next_slots(spec, state, rng.randint(1, 2))
            block = _random_blob_block(spec, state, rng)
            signed_blocks.append(
                state_transition_and_sign_block(spec, state, block))
        next_epoch(spec, state)
    assert hash_tree_root(state) is not None
    yield "blocks", signed_blocks
    yield "post", state


@DENEB_ONLY
@spec_state_test
def test_random_blob_blocks_0(spec, state):
    yield from _run_scenario(spec, state, seed=440)


@DENEB_ONLY
@spec_state_test
def test_random_blob_blocks_1(spec, state):
    yield from _run_scenario(spec, state, seed=441)


@DENEB_ONLY
@spec_state_test
def test_random_blob_blocks_2(spec, state):
    yield from _run_scenario(spec, state, seed=442)


@DENEB_ONLY
@spec_state_test
def test_random_blob_blocks_multi_epoch(spec, state):
    yield from _run_scenario(spec, state, seed=443, epochs=2)


@DENEB_ONLY
@spec_state_test
def test_random_blob_blocks_leak_0(spec, state):
    yield from _run_scenario(spec, state, seed=444, leak=True)


@DENEB_ONLY
@spec_state_test
def test_random_blob_blocks_leak_1(spec, state):
    yield from _run_scenario(spec, state, seed=445, leak=True)


@DENEB_ONLY
@spec_state_test
def test_random_blob_blocks_sparse(spec, state):
    """Longer slot gaps between blocks (epoch-boundary crossings)."""
    rng = Random(446)
    next_epoch(spec, state)
    next_epoch(spec, state)
    randomize_state(spec, state, rng, exit_fraction=0.02,
                    slash_fraction=0.02)
    yield "pre", state
    signed_blocks = []
    for _ in range(4):
        next_slots(spec, state, rng.randint(3, 9))
        block = _random_blob_block(spec, state, rng)
        signed_blocks.append(
            state_transition_and_sign_block(spec, state, block))
    yield "blocks", signed_blocks
    yield "post", state


@DENEB_ONLY
@spec_state_test
def test_random_blob_blocks_max_blobs_every_block(spec, state):
    """Every block saturated at MAX_BLOBS_PER_BLOCK commitments."""
    rng = Random(447)
    next_epoch(spec, state)
    next_epoch(spec, state)
    yield "pre", state
    signed_blocks = []
    for _ in range(4):
        block = random_block(spec, state, rng)
        block.body.blob_kzg_commitments = \
            [spec.G1_POINT_AT_INFINITY] * spec.MAX_BLOBS_PER_BLOCK
        signed_blocks.append(
            state_transition_and_sign_block(spec, state, block))
    yield "blocks", signed_blocks
    yield "post", state
