"""Single-merkle-proof vectors: blob-commitment inclusion in the block body.

Reference model: ``test/deneb/merkle_proof/test_single_merkle_proof.py``
(blob sidecar inclusion proofs) and the ``merkle_proof`` vector format
(``tests/formats/merkle_proof/README.md``: object.ssz_snappy + proof.yaml
with leaf / leaf_index / branch).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, never_bls, pytest_only,
)
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, get_generalized_index, get_generalized_index_length,
    get_subtree_node_root, compute_merkle_proof, verify_merkle_proof,
)


def _body_with_commitments(spec, n):
    body = spec.BeaconBlockBody()
    commitments = [bytes([0x01, i]) + bytes(46) for i in range(n)]
    body.blob_kzg_commitments = body.blob_kzg_commitments.__class__(
        *commitments)
    return body


def _run_blob_commitment_proof(spec, body, blob_index):
    gindex = get_generalized_index(
        type(body), "blob_kzg_commitments", blob_index)
    leaf = get_subtree_node_root(body, gindex)
    branch = compute_merkle_proof(body, gindex)
    yield "object", body
    yield "proof", {
        "leaf": "0x" + leaf.hex(),
        "leaf_index": int(gindex),
        "branch": ["0x" + b.hex() for b in branch],
    }
    assert len(branch) == get_generalized_index_length(gindex)
    assert len(branch) == int(spec.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH)
    assert verify_merkle_proof(leaf, branch, gindex, hash_tree_root(body))


@with_phases(["deneb"])
@spec_state_test
@never_bls
def test_blob_kzg_commitment_merkle_proof_first(spec, state):
    body = _body_with_commitments(spec, 1)
    yield from _run_blob_commitment_proof(spec, body, 0)


@with_phases(["deneb"])
@spec_state_test
@never_bls
def test_blob_kzg_commitment_merkle_proof_max_blobs(spec, state):
    n = int(spec.MAX_BLOBS_PER_BLOCK)
    body = _body_with_commitments(spec, n)
    yield from _run_blob_commitment_proof(spec, body, n - 1)


@pytest_only
@with_phases(["deneb"])
@spec_state_test
@never_bls
def test_blob_kzg_commitment_proof_rejects_wrong_root(spec, state):
    body = _body_with_commitments(spec, 2)
    gindex = get_generalized_index(type(body), "blob_kzg_commitments", 1)
    leaf = get_subtree_node_root(body, gindex)
    branch = compute_merkle_proof(body, gindex)
    other = _body_with_commitments(spec, 3)
    assert not verify_merkle_proof(
        leaf, branch, gindex, hash_tree_root(other))
    yield


@pytest_only
@with_phases(["deneb"])
@spec_state_test
@never_bls
def test_blob_kzg_commitment_proof_rejects_wrong_index(spec, state):
    body = _body_with_commitments(spec, 2)
    g0 = get_generalized_index(type(body), "blob_kzg_commitments", 0)
    g1 = get_generalized_index(type(body), "blob_kzg_commitments", 1)
    leaf = get_subtree_node_root(body, g0)
    branch = compute_merkle_proof(body, g0)
    assert not verify_merkle_proof(leaf, branch, g1, hash_tree_root(body))
    yield
