"""Deneb sanity blocks: blob commitments through the full transition and
the EIP-7045 extended attestation-inclusion window.

Reference model: ``test/deneb/sanity/test_blocks.py`` (blob-carrying
blocks) and the EIP-7045 cases in
``test/deneb/block_processing/test_process_attestation.py`` against
``specs/deneb/beacon-chain.md``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, next_slots, next_epoch,
    state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation


def _blob_block(spec, state, n_commitments):
    block = build_empty_block_for_next_slot(spec, state)
    block.body.blob_kzg_commitments = [
        spec.G1_POINT_AT_INFINITY] * n_commitments
    return block


@with_phases(["deneb"])
@spec_state_test
def test_zero_blob_block(spec, state):
    yield "pre", state
    block = _blob_block(spec, state, 0)
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state


@with_phases(["deneb"])
@spec_state_test
def test_one_blob_block(spec, state):
    yield "pre", state
    block = _blob_block(spec, state, 1)
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state
    assert state.latest_block_header.body_root == \
        signed.message.body_root if hasattr(signed.message, "body_root") \
        else True


@with_phases(["deneb"])
@spec_state_test
def test_max_blobs_block(spec, state):
    yield "pre", state
    block = _blob_block(spec, state, spec.MAX_BLOBS_PER_BLOCK)
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state


@with_phases(["deneb"])
@spec_state_test
def test_invalid_blob_count_block(spec, state):
    """MAX_BLOBS_PER_BLOCK + 1 commitments invalidate the whole block."""
    yield "pre", state
    block = _blob_block(spec, state, spec.MAX_BLOBS_PER_BLOCK + 1)
    expect_assertion_error(
        lambda: state_transition_and_sign_block(spec, state, block))
    yield "post", None


@with_phases(["deneb"])
@spec_state_test
def test_attestation_included_after_epoch_window(spec, state):
    """EIP-7045: a current-or-previous-epoch attestation is includable at
    ANY later slot — beyond phase0's one-epoch SLOTS_PER_EPOCH bound."""
    next_epoch(spec, state)  # leave genesis epoch
    attestation = get_valid_attestation(spec, state, signed=True)
    # advance past the pre-deneb inclusion bound (slot + SLOTS_PER_EPOCH)
    next_slots(spec, state, spec.SLOTS_PER_EPOCH + 2)
    assert state.slot > attestation.data.slot + spec.SLOTS_PER_EPOCH
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attestations = [attestation]
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state


@with_phases(["deneb"])
@spec_state_test
def test_attestation_from_two_epochs_ago_invalid(spec, state):
    """The window extends only within current/previous target epochs:
    an attestation two epochs old still fails the target check."""
    next_epoch(spec, state)
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, 2 * spec.SLOTS_PER_EPOCH + 2)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attestations = [attestation]
    expect_assertion_error(
        lambda: state_transition_and_sign_block(spec, state, block))
    yield "post", None
