"""Deneb block-processing deltas: blob commitment caps, EIP-7044 exits,
EIP-7045 attestation windows.

Reference models: ``test/deneb/block_processing/test_process_execution_payload.py``,
``test/deneb/block_processing/test_process_voluntary_exit.py``,
``test/deneb/sanity/test_blocks.py``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, always_bls, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block, next_epoch, next_slots,
    state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload,
)
from consensus_specs_tpu.test_infra.keys import privkeys
from consensus_specs_tpu.utils import bls


@with_phases(["deneb"])
@spec_state_test
def test_invalid_exceed_max_blobs_per_block(spec, state):
    body = spec.BeaconBlockBody(
        execution_payload=build_empty_execution_payload(spec, state))
    body.blob_kzg_commitments = [
        spec.G1_POINT_AT_INFINITY] * (spec.MAX_BLOBS_PER_BLOCK + 1)
    yield "pre", state
    expect_assertion_error(
        lambda: spec.process_execution_payload(
            state, body, spec.EXECUTION_ENGINE))
    yield "post", None


@with_phases(["deneb"])
@spec_state_test
def test_max_blobs_per_block_ok(spec, state):
    body = spec.BeaconBlockBody(
        execution_payload=build_empty_execution_payload(spec, state))
    body.blob_kzg_commitments = [
        spec.G1_POINT_AT_INFINITY] * spec.MAX_BLOBS_PER_BLOCK
    yield "pre", state
    spec.process_execution_payload(state, body, spec.EXECUTION_ENGINE)
    yield "post", state


@with_phases(["deneb"])
@spec_state_test
def test_versioned_hash_prefix(spec, state):
    vh = spec.kzg_commitment_to_versioned_hash(spec.G1_POINT_AT_INFINITY)
    assert bytes(vh[:1]) == spec.VERSIONED_HASH_VERSION_KZG
    assert len(vh) == 32


@with_phases(["deneb"])
@spec_state_test
@always_bls
def test_voluntary_exit_uses_capella_domain(spec, state):
    """EIP-7044: exits are signed over CAPELLA_FORK_VERSION regardless of
    the current fork (beacon-chain.md:411)."""
    current_epoch = spec.get_current_epoch(state)
    validator_index = spec.get_active_validator_indices(
        state, current_epoch)[0]
    # make the validator old enough
    state.validators[validator_index].activation_epoch = 0
    state.slot = spec.SLOTS_PER_EPOCH * (spec.config.SHARD_COMMITTEE_PERIOD + 1)

    exit_msg = spec.VoluntaryExit(epoch=0, validator_index=validator_index)
    domain = spec.compute_domain(spec.DOMAIN_VOLUNTARY_EXIT,
                                 spec.config.CAPELLA_FORK_VERSION,
                                 state.genesis_validators_root)
    signing_root = spec.compute_signing_root(exit_msg, domain)
    signed = spec.SignedVoluntaryExit(
        message=exit_msg,
        signature=bls.Sign(privkeys[validator_index], signing_root))
    spec.process_voluntary_exit(state, signed)
    assert state.validators[validator_index].exit_epoch < spec.FAR_FUTURE_EPOCH

    # the *current* fork domain must NOT validate
    state2 = state.copy()
    state2.validators[validator_index].exit_epoch = spec.FAR_FUTURE_EPOCH
    bad_domain = spec.get_domain(state2, spec.DOMAIN_VOLUNTARY_EXIT, 0)
    bad_root = spec.compute_signing_root(exit_msg, bad_domain)
    bad_signed = spec.SignedVoluntaryExit(
        message=exit_msg,
        signature=bls.Sign(privkeys[validator_index], bad_root))
    expect_assertion_error(
        lambda: spec.process_voluntary_exit(state2, bad_signed))


@with_phases(["deneb"])
@spec_state_test
def test_attestation_included_after_one_epoch_eip7045(spec, state):
    """Pre-deneb this inclusion (delay > SLOTS_PER_EPOCH) is invalid;
    deneb accepts it and still grants the target flag."""
    next_epoch(spec, state)
    attestation = get_valid_attestation(spec, state, index=0, signed=True)
    # advance well past the old upper bound
    next_slots(spec, state, spec.SLOTS_PER_EPOCH + 3)
    yield "pre", state
    block = build_empty_block(spec, state, state.slot + 1)
    block.body.attestations.append(attestation)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert any(f != 0 for f in state.previous_epoch_participation)
