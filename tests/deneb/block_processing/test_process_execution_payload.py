"""Deneb ``process_execution_payload``: versioned-hash validation against
blob transactions, via a test engine that implements the check the
NoopExecutionEngine stubs out.

Reference model:
``test/deneb/block_processing/test_process_execution_payload.py``
against ``specs/deneb/beacon-chain.md`` process_execution_payload
(commitment cap + versioned hashes into the NewPayloadRequest).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload, compute_el_block_hash,
    get_sample_opaque_tx, tx_with_versioned_hashes,
    BlobVersionedHashesExecutionEngine, BLOB_TX_TYPE,
)
from consensus_specs_tpu.test_infra.block import next_slot

DENEB_ONLY = with_phases(["deneb"])


def _run_payload_test(spec, state, mutate=None, valid=True, engine=None):
    """Build body(payload + commitments), optionally mutate, run the
    processor with the versioned-hash-validating engine."""
    next_slot(spec, state)
    opaque_tx, _, commitments, _ = get_sample_opaque_tx(spec, blob_count=2)
    payload = build_empty_execution_payload(spec, state)
    payload.transactions = [opaque_tx]
    payload.block_hash = compute_el_block_hash(spec, payload)
    body = spec.BeaconBlockBody(
        execution_payload=payload,
        blob_kzg_commitments=commitments,
    )
    if mutate is not None:
        mutate(spec, body)
        # a real proposer would re-commit the mutated payload unless the
        # mutation IS a block-hash corruption
        if mutate.__name__ != "bad_block_hash":
            body.execution_payload.block_hash = compute_el_block_hash(
                spec, body.execution_payload)
    engine = engine or BlobVersionedHashesExecutionEngine(spec)
    yield "pre", state
    yield "execution", {"execution_valid": valid}
    yield "body", body
    if valid:
        spec.process_execution_payload(state, body, engine)
        yield "post", state
    else:
        expect_assertion_error(
            lambda: spec.process_execution_payload(state, body, engine))
        yield "post", None


@DENEB_ONLY
@spec_state_test
def test_valid_blob_tx_payload(spec, state):
    yield from _run_payload_test(spec, state)


@DENEB_ONLY
@spec_state_test
def test_invalid_incorrect_blob_tx_type(spec, state):
    def mutate(spec, body):
        tx = bytearray(bytes(body.execution_payload.transactions[0]))
        tx[0] = 0x04                    # not BLOB_TX_TYPE: hashes unparsed
        body.execution_payload.transactions[0] = tx
    yield from _run_payload_test(spec, state, mutate, valid=False)


@DENEB_ONLY
@spec_state_test
def test_invalid_transaction_length_1_extra_byte(spec, state):
    def mutate(spec, body):
        tx = bytes(body.execution_payload.transactions[0]) + b"\x00"
        body.execution_payload.transactions[0] = tx
    yield from _run_payload_test(spec, state, mutate, valid=False)


@DENEB_ONLY
@spec_state_test
def test_invalid_transaction_length_1_byte_short(spec, state):
    def mutate(spec, body):
        tx = bytes(body.execution_payload.transactions[0])[:-1]
        body.execution_payload.transactions[0] = tx
    yield from _run_payload_test(spec, state, mutate, valid=False)


@DENEB_ONLY
@spec_state_test
def test_invalid_transaction_empty(spec, state):
    def mutate(spec, body):
        body.execution_payload.transactions[0] = bytes([BLOB_TX_TYPE])
    yield from _run_payload_test(spec, state, mutate, valid=False)


@DENEB_ONLY
@spec_state_test
def test_invalid_transaction_32_extra_bytes(spec, state):
    def mutate(spec, body):
        tx = bytes(body.execution_payload.transactions[0]) + b"\x11" * 32
        body.execution_payload.transactions[0] = tx
    yield from _run_payload_test(spec, state, mutate, valid=False)


@DENEB_ONLY
@spec_state_test
def test_invalid_no_transactions_with_commitments(spec, state):
    def mutate(spec, body):
        body.execution_payload.transactions = []
    yield from _run_payload_test(spec, state, mutate, valid=False)


@DENEB_ONLY
@spec_state_test
def test_invalid_incorrect_commitment(spec, state):
    def mutate(spec, body):
        c = bytearray(bytes(body.blob_kzg_commitments[0]))
        c[-1] ^= 0xFF
        body.blob_kzg_commitments[0] = c
    yield from _run_payload_test(spec, state, mutate, valid=False)


@DENEB_ONLY
@spec_state_test
def test_invalid_incorrect_commitments_order(spec, state):
    def mutate(spec, body):
        a, b = body.blob_kzg_commitments[0], body.blob_kzg_commitments[1]
        body.blob_kzg_commitments[0] = b
        body.blob_kzg_commitments[1] = a
    yield from _run_payload_test(spec, state, mutate, valid=False)


@DENEB_ONLY
@spec_state_test
def test_invalid_block_hash(spec, state):
    def bad_block_hash(spec, body):
        body.execution_payload.block_hash = spec.Hash32(b"\x12" * 32)
    yield from _run_payload_test(spec, state, bad_block_hash, valid=False)


@DENEB_ONLY
@spec_state_test
def test_zeroed_commitment(spec, state):
    """An all-zero commitment is hash-consistent if the tx carries its
    versioned hash — the payload processor accepts it (validity of the
    commitment itself is the kzg library's concern)."""
    def mutate(spec, body):
        zero = spec.KZGCommitment(b"\x00" * 48)
        body.blob_kzg_commitments = [zero]
        body.execution_payload.transactions = [tx_with_versioned_hashes(
            [spec.kzg_commitment_to_versioned_hash(zero)])]
    yield from _run_payload_test(spec, state, mutate, valid=True)


@DENEB_ONLY
@spec_state_test
def test_invalid_correct_input_execution_invalid(spec, state):
    class RejectingEngine(BlobVersionedHashesExecutionEngine):
        def notify_new_payload(self, *a, **k) -> bool:
            return False
    yield from _run_payload_test(
        spec, state, valid=False, engine=RejectingEngine(spec))


@DENEB_ONLY
@spec_state_test
def test_multiple_blob_txs(spec, state):
    """Versioned hashes concatenate across several blob transactions in
    payload order."""
    def mutate(spec, body):
        h = [spec.kzg_commitment_to_versioned_hash(c)
             for c in body.blob_kzg_commitments]
        body.execution_payload.transactions = [
            tx_with_versioned_hashes(h[:1]), tx_with_versioned_hashes(h[1:])]
    yield from _run_payload_test(spec, state, mutate, valid=True)
