"""Differential tests: JAX SHA-256 kernel vs hashlib.

Heavy tier only (``CS_TPU_HEAVY=1`` / ``make test-crypto``): every test
here jit-compiles the batched SHA-256 program — minutes of cold XLA:CPU
compile per message-length shape on a 1-core host. The default suite
covers the merkle plug through the C hasher (``tests/test_ssz.py`` and
the suite-wide merkleization) and the kernel itself through this gated
tier.
"""
import hashlib
import os

import pytest

from consensus_specs_tpu.utils.env_flags import HEAVY

pytestmark = pytest.mark.skipif(
    not HEAVY, reason="jit of the SHA-256 kernel: set CS_TPU_HEAVY=1")

from consensus_specs_tpu.ops import sha256 as k


def test_hash64_batch_matches_hashlib():
    for n in (1, 2, 3, 7, 256, 300):
        data = os.urandom(64 * n)
        out = k.hash64_batch(data, n)
        assert len(out) == 32 * n
        for i in range(n):
            expect = hashlib.sha256(data[i * 64:(i + 1) * 64]).digest()
            assert out[i * 32:(i + 1) * 32] == expect


@pytest.mark.parametrize("length", [0, 1, 55, 56, 63, 64, 65, 119, 120, 128, 1000])
def test_sha256_bytes_matches_hashlib(length):
    msg = os.urandom(length)
    assert k.sha256_bytes(msg) == hashlib.sha256(msg).digest()


def test_merkle_layer_uses_kernel():
    from consensus_specs_tpu.utils.ssz import merkle
    k.install_merkle_hasher()
    try:
        n = 512  # above _BATCH_THRESHOLD
        data = os.urandom(64 * n)
        got = merkle.hash_layer(data)
        expect = b"".join(
            hashlib.sha256(data[i * 64:(i + 1) * 64]).digest() for i in range(n)
        )
        assert got == expect
    finally:
        merkle.set_batched_hasher(None)
